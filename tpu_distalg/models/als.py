"""ALS matrix factorization (the reference's "matrix decomposition").

Re-design of ``/root/reference/matrix_computation/matrix_decomposition.py``:
the reference broadcasts the FULL dense R, U, V to every task and solves one
row per Spark task (``:46-48,52-62``) — SURVEY.md §2.3 calls this the one
place the broadcast-everything design visibly fails to scale. Here R stays
row-sharded over the mesh ``data`` axis permanently; each half-sweep is a
batched normal-equation solve under GSPMD: the k×k Gram is computed once
(the reference recomputes it in every task), the cross-shard contraction
``Uᵀ·R`` is an XLA-inserted AllReduce over ICI, and factors carry sharding
constraints so nothing dense is ever replicated needlessly.

R's rows are zero-padded to the shard count; padded rows solve to exactly
zero factor rows (zero RHS against a PD Gram), so they contribute nothing to
Grams, RMSE numerator, or the V-update — the RMSE denominator uses the true
m·n (``matrix_decomposition.py:19-21``).

Measured cost attribution at bench scale (4096×16384 rank-64, one v5e,
``scripts/als_profile.py`` — scan-wrapped component benchmarks):
~2.16 ms/sweep total = solves ~1.5-1.7 ms + per-sweep RMSE ~1.4 ms
(overlapped by XLA). The sweep is bound by full passes over the 268 MB
R (two solve right-hand sides + the RMSE diff) with the HIGHEST-
precision multi-pass matmuls adding ~30-40% — and those pins are
load-bearing: DEFAULT-precision right-hand sides or a HIGH (bf16x3)
RMSE save ~0.4 ms each but cost the exact rank-k recovery this module
asserts (final rmse 2e-5). Rejected, measured: a blocked RMSE that
avoids materialising the (m, n) diff runs SLOWER (1.67 vs 1.40 ms —
the scan serialises and the narrow matmuls under-fill the MXU), and
an algebraic RMSE via ‖R‖² − 2·tr((UᵀR)V) + tr((UᵀU)(VᵀV)) dies on
f32 cancellation (resolving rmse 2e-5 against ‖R‖²~1e8 needs ~10
significant digits). The design is at its traffic floor given the
precision contract.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from tpu_distalg.ops import linalg
from tpu_distalg.parallel import (
    DATA_AXIS,
    data_parallel,
    pad_rows,
    tree_allreduce_sum,
)
from tpu_distalg.utils import metrics


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    """Knob names follow ``matrix_decomposition.py:12-17``."""

    lam: float = 0.01
    m: int = 100
    n: int = 500
    k: int = 10
    n_iterations: int = 5
    seed: int = 0


@dataclasses.dataclass
class ALSResult:
    U: jax.Array
    V: jax.Array
    rmse_history: jax.Array  # per-sweep RMSE

    @property
    def final_rmse(self) -> float:
        return float(self.rmse_history[-1])


def synthesize_rank_k(config: ALSConfig) -> np.ndarray:
    """R = U₀·V₀ᵀ with U₀, V₀ ~ U[0,1) — the reference's synthetic
    exactly-rank-k target (``matrix_decomposition.py:42``)."""
    rng = np.random.default_rng(config.seed)
    U0 = rng.random((config.m, config.k), dtype=np.float32)
    V0 = rng.random((config.n, config.k), dtype=np.float32)
    return U0 @ V0.T


def model_padded_n(config: ALSConfig, mesh: Mesh) -> int:
    """Columns of R (= rows of V) after padding ``n`` up to a multiple
    of the model-axis size, so the model-parallel V sharding ALWAYS
    engages (it used to silently replicate V whenever
    ``n % n_model != 0`` — VERDICT weak #4). Padded columns are zero →
    their V rows solve to exactly zero (zero RHS against a PD Gram) and
    touch neither the U-update Gram nor the RMSE; the RMSE denominator
    and the Gram regularisation keep using the TRUE ``config.n``."""
    from tpu_distalg.parallel import MODEL_AXIS

    n_model = mesh.shape[MODEL_AXIS]
    return -(-config.n // n_model) * n_model


def make_fit_fn(mesh: Mesh, config: ALSConfig):
    import warnings

    from tpu_distalg.parallel import MODEL_AXIS, partition

    denom = config.m * config.n  # true element count, not padded
    # shard the item factor over the model axis — the model-parallel
    # einsum SURVEY.md §2.3 calls for, replacing the reference's
    # broadcast of full V to every task (:46-48). fit() pads R's
    # columns to model_padded_n, so with R padded the sharding ALWAYS
    # engages; a caller handing this closure an unpadded R gets a
    # LOGGED disengage instead of the old silent replication.
    n_model = mesh.shape[MODEL_AXIS]
    n_pad = model_padded_n(config, mesh)

    def _v_engaged(n_cols: int) -> bool:
        if n_model <= 1:
            return False
        if n_cols % n_model:
            warnings.warn(
                f"ALS model axis DISENGAGED: R has {n_cols} columns, "
                f"not a multiple of the model-axis size {n_model} — V "
                f"will be replicated. Pad R's columns to {n_pad} "
                "(als.fit does) to engage the model-parallel sharding.",
                stacklevel=3)
            return False
        return True

    def fit(R, U0, V0):
        v_engaged = _v_engaged(R.shape[1])
        def sweep(carry, _):
            U, V = carry
            # U-update: (VᵀV + λ·n·I) uᵢ = Vᵀ R[i,:]  (:52-54, :24-33)
            G_v = linalg.gram(V, config.lam, config.n)
            U = linalg.solve_factor_block(G_v, V, R)
            U = partition.constrain(U, "U", "als_train", mesh)
            # V-update against Rᵀ: (UᵀU + λ·m·I) vⱼ = Uᵀ R[:,j]  (:60-62)
            G_u = linalg.gram(U, config.lam, config.m)
            V = linalg.solve_factor_block(G_u, U, R.T)
            if v_engaged:
                V = partition.constrain(V, "V", "als_train", mesh)
            # padded rows are exactly zero on both sides; 'highest'
            # precision keeps the reconstruction error measurement from
            # being floored by TPU bf16 matmul passes
            diff = R - jnp.matmul(U, V.T, precision=lax.Precision.HIGHEST)
            err = jnp.sqrt(jnp.sum(diff * diff) / denom)  # :19-21
            return (U, V), err

        (U, V), errs = jax.lax.scan(
            sweep, (U0, V0), None, length=config.n_iterations
        )
        return U, V, errs

    return jax.jit(fit)


def fit(mesh: Mesh, config: ALSConfig = ALSConfig(),
        R: np.ndarray | None = None,
        *,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 5) -> ALSResult:
    """Fit U·Vᵀ ≈ R; optionally checkpointed per ``checkpoint_every``
    sweeps (carry = the (U, V) factor pair; ALS sweeps are
    deterministic functions of the factors, so segmented and straight
    runs are bitwise-identical)."""
    if R is None:
        R = synthesize_rank_k(config)
    elif R.shape != (config.m, config.n):
        # caller-supplied R wins: m/n drive the RMSE denominator, the
        # Gram regularisation scale, and the U truncation
        config = dataclasses.replace(config, m=R.shape[0], n=R.shape[1])
    n_shards = mesh.shape[DATA_AXIS]
    R_padded, _mask = pad_rows(np.asarray(R, dtype=np.float32), n_shards)
    # column padding engages the model-axis V sharding for ANY n (the
    # padded columns are zero → zero V rows, algebraically inert)
    n_pad = model_padded_n(config, mesh)
    if n_pad != config.n:
        R_padded = np.pad(R_padded, ((0, 0), (0, n_pad - config.n)))

    rng = np.random.default_rng(config.seed + 1)
    # U0 is never read: the first half-sweep recomputes U from (V, R)
    # exactly as the reference's first parallelize(range(m)) pass does.
    # V0's RANDOM entries cover only the true n rows (the padded tail
    # is zero and never read either — the first sweep's U-update uses
    # V0, whose padded rows multiply R's zero columns).
    U0 = np.zeros((R_padded.shape[0], config.k), dtype=np.float32)
    V0 = np.zeros((n_pad, config.k), dtype=np.float32)
    V0[: config.n] = rng.random((config.n, config.k), dtype=np.float32)

    from tpu_distalg.parallel import partition

    R_dev = partition.put(R_padded, "R", "als_train", mesh)
    U_dev = partition.put(U0, "U", "als_train", mesh)
    V_dev = partition.put(V0, "V0", "als_train", mesh)

    if checkpoint_dir is None:
        fn = make_fit_fn(mesh, config)
        U, V, errs = fn(R_dev, U_dev, V_dev)
        metrics.guard_finite(errs, "ALS rmse history")
        return ALSResult(U=U[: config.m], V=V[: config.n],
                         rmse_history=errs)

    from tpu_distalg.utils import checkpoint as ckpt

    def run_seg(fn, state, t0):
        del t0  # sweeps carry no PRNG; the factors are the whole state
        U, V = state
        U = partition.put(U, "U", "als_train", mesh)
        V = partition.put(V, "V0", "als_train", mesh)
        U, V, errs = fn(R_dev, U, V)
        return (U, V), errs

    (U, V), errs, _ = ckpt.run_segmented(
        checkpoint_dir, checkpoint_every, config.n_iterations,
        make_seg_fn=lambda seg: make_fit_fn(
            mesh, dataclasses.replace(config, n_iterations=seg)),
        run_seg=run_seg,
        state0=(U_dev, V_dev),
        tag="als",
    )
    return ALSResult(
        U=jnp.asarray(U)[: config.m], V=jnp.asarray(V)[: config.n],
        rmse_history=jnp.asarray(errs),
    )


def _make_streamed_block_fns(mesh: Mesh, config: ALSConfig, n: int):
    """The three jitted pieces of one streamed sweep: the per-R-block
    U-solve + partial-contraction, the V-update from the accumulated
    contractions, and the per-block RMSE accumulation. All matmuls pin
    HIGHEST precision — the same contract the resident path carries
    (module docstring: default-precision right-hand sides cost the
    exact rank-k recovery)."""
    from jax.sharding import PartitionSpec as P

    _HI = lax.Precision.HIGHEST
    k = config.k

    def _solve_block(Rb, V, G_v):
        R = Rb[0]                                       # (bp, n)
        U_b = linalg.solve_factor_block(G_v, V, R)      # (bp, k)
        C_inc = jnp.matmul(U_b.T, R, precision=_HI)     # (k, n)
        UtU_inc = jnp.matmul(U_b.T, U_b, precision=_HI)
        return (U_b[None],) + tree_allreduce_sum((C_inc, UtU_inc))

    solve_fn = jax.jit(data_parallel(
        _solve_block, mesh,
        in_specs=(P(DATA_AXIS, None, None), P(), P()),
        out_specs=(P(DATA_AXIS, None, None), P(), P())))

    def _v_update(UtU, C):
        # (UᵀU + λ·m·I) vⱼ = (UᵀR)[:, j] — reg_rows = the factor ROW
        # count, the reference's X_dim quirk (ops/linalg.gram)
        G_u = UtU + config.lam * config.m * jnp.eye(k, dtype=UtU.dtype)
        cho = jax.scipy.linalg.cho_factor(G_u)
        return jax.scipy.linalg.cho_solve(cho, C).T     # (n, k)

    v_update_fn = jax.jit(_v_update)

    def _rmse_block(Rb, U_b, V):
        diff = Rb[0] - jnp.matmul(U_b[0], V.T, precision=_HI)
        return tree_allreduce_sum(jnp.sum(diff * diff))

    rmse_fn = jax.jit(data_parallel(
        _rmse_block, mesh,
        in_specs=(P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
                  P()),
        out_specs=P()))

    gram_fn = jax.jit(
        lambda V: linalg.gram(V, config.lam, n))
    return solve_fn, v_update_fn, rmse_fn, gram_fn


def fit_streamed(dataset, config: ALSConfig | None = None, *,
                 rmse_every: int = 1) -> ALSResult:
    """ALS over a :class:`~tpu_distalg.data.ShardedDataset` of R rows
    (``dense_rows_f32`` layout) — R never resident: each half-sweep
    STREAMS the row blocks through the prefetch pipeline (gather ∥ H2D
    ∥ solve), so R is bounded by DISK, not HBM — the scale SURVEY §2.3
    says the reference's broadcast-everything design visibly fails at,
    and the cap VERDICT "what's missing" #3 flagged for this repo.

    Per sweep: one streaming pass solves the U row-blocks against the
    current V while accumulating the cross-shard contractions
    ``UᵀR (k, n)`` and ``UᵀU (k, k)`` block by block (the only state
    that persists between blocks is O(k·n) — never R); the V-update
    then solves against the accumulated normal equations, exactly the
    resident sweep's algebra with the n-column contraction distributed
    over blocks. ``rmse_every=r`` streams ONE extra evaluation pass
    every r-th sweep (``0``: once, after the final sweep) — the honest
    cost of measuring ‖R − UVᵀ‖ when R lives on disk. Zero padding
    rows (the builder's) solve to zero U rows and touch nothing.

    Trajectories are bitwise-identical across dataset backends (same
    staged bytes, same jitted block fns — tests/test_data.py); vs the
    resident :func:`fit` they agree to float tolerance (the blocked
    contraction changes the summation order, not the algebra)."""
    import contextlib

    mesh = dataset.mesh
    meta = dataset.meta
    m_true = int(meta.get("m", dataset.n2))
    n = dataset.pd
    if config is None:
        config = ALSConfig(m=m_true, n=n, k=int(meta.get("k", 10)))
    if (config.m, config.n) != (m_true, n):
        config = dataclasses.replace(config, m=m_true, n=n)
    k = config.k
    nb, S = dataset.n_blocks, dataset.n_shards
    solve_fn, v_update_fn, rmse_fn, gram_fn = _make_streamed_block_fns(
        mesh, config, n)

    from tpu_distalg.parallel import partition

    rng = np.random.default_rng(config.seed + 1)
    V = partition.put(rng.random((n, k), dtype=np.float32),
                      "V0", "als_train", mesh)
    # every sweep streams the blocks in order: one block per shard per
    # step, the same LOCAL block id on every shard
    ids = np.tile(np.arange(nb, dtype=np.int64)[:, None, None],
                  (1, S, 1))
    serialize = not dataset.on_tpu
    denom = config.m * config.n
    errs = []
    from tpu_distalg.telemetry import events as tevents

    for sweep in range(config.n_iterations):
        tevents.mark(f"als_stream:sweep@{sweep}", emit_event=False)
        G_v = gram_fn(V)
        C = jnp.zeros((k, n), jnp.float32)
        UtU = jnp.zeros((k, k), jnp.float32)
        us = []
        with contextlib.closing(dataset.stream(ids)) as batches:
            for staged in batches:
                U_b, C_inc, UtU_inc = solve_fn(staged, V, G_v)
                C, UtU = C + C_inc, UtU + UtU_inc
                us.append(U_b)
                if serialize:
                    # tda: ignore[TDA011] -- deliberate: on host
                    # (CPU-mesh) backends this bounds the stream's
                    # in-flight blocks; never taken on TPU
                    jax.block_until_ready(UtU)
        V = v_update_fn(UtU, C)
        want_rmse = (rmse_every and (sweep + 1) % rmse_every == 0) or \
            (sweep + 1 == config.n_iterations)
        if want_rmse:
            acc = jnp.float32(0.0)
            with contextlib.closing(dataset.stream(ids)) as batches:
                for b, staged in enumerate(batches):
                    acc = acc + rmse_fn(staged, us[b], V)
                    if serialize:
                        # tda: ignore[TDA011] -- deliberate: see the
                        # solve loop above (host-backend stream bound)
                        jax.block_until_ready(acc)
            errs.append(jnp.sqrt(acc / denom))
    U = jnp.stack(us, axis=1).reshape(dataset.n2, k)
    errs = jnp.stack(errs) if errs else jnp.zeros((0,))
    metrics.guard_finite(errs, "streamed ALS rmse history")
    return ALSResult(U=U[: config.m], V=V, rmse_history=errs)


def fit_rowstore(config: ALSConfig = ALSConfig(), *,
                 density: float = 0.08, ps_shards: int = 2,
                 user_block: int = 32,
                 model_budget_rows: int | None = None) -> dict:
    """Observed-entry ALS with the item factor V living in the
    SHARDED ROW STORE (``cluster/rowstore.py``, table ``als_train`` —
    the same rule table the in-process trainer places V under): the
    worker holds U and the ratings locally but NEVER materializes V
    whole. Each user-block U-solve pulls only the V rows that block's
    observed items reference, each V-update pushes per-row deltas
    (one contribution at the store's own version → age 0, weight 1 —
    an exact row replacement through the weighted-merge arithmetic),
    and items nobody rated are never pulled, pushed, or versioned.

    ``model_budget_rows`` is the >1-host-RAM contract: the peak V rows
    any single pull materializes must stay under it or the fit RAISES
    (the row store's streaming claim fails loudly, never silently
    densifies). numpy-only — a host fleet worker, no mesh.

    Returns ``{U, V, rmse_history, peak_pull_rows,
    sparse_pull_fraction, rows_pulled, rows_pushed}`` where the
    fraction is measured pulls over the dense pull-everything
    baseline and V is a final snapshot (test/report surface, outside
    the budget)."""
    from tpu_distalg.cluster import rowstore as _rowstore

    rng = np.random.default_rng(config.seed)
    m, n, k, lam = config.m, config.n, config.k, config.lam
    R = synthesize_rank_k(config)
    observed = rng.random((m, n)) < density
    user_cols = [np.flatnonzero(observed[i]) for i in range(m)]
    item_users = [np.flatnonzero(observed[:, j]) for j in range(n)]
    touched_items = np.flatnonzero(observed.any(axis=0))
    n_obs = int(observed.sum())
    if not n_obs:
        raise ValueError("no observed entries at this density/seed")

    store = _rowstore.RowStore(
        {"V": rng.random((n, k), dtype=np.float32)},
        table="als_train", n_shards=ps_shards)
    U = rng.random((m, k), dtype=np.float32)

    peak_pull = 0
    rows_pulled = 0
    rows_pushed = 0
    n_pulls = 0

    def pull(rows: np.ndarray) -> np.ndarray:
        nonlocal peak_pull, rows_pulled, n_pulls
        if model_budget_rows is not None \
                and rows.shape[0] > model_budget_rows:
            raise RuntimeError(
                f"a pull needs {rows.shape[0]} V rows at once but the "
                f"model budget is {model_budget_rows} — shrink the "
                f"user blocks, not the honesty of the claim")
        peak_pull = max(peak_pull, int(rows.shape[0]))
        rows_pulled += int(rows.shape[0])
        n_pulls += 1
        vals, _vers = store.pull_rows("V", rows)
        return vals

    def solve(F: np.ndarray, r: np.ndarray) -> np.ndarray:
        # (FᵀF + λ·|obs|·I) x = Fᵀ r — the reference's per-row normal
        # equations, restricted to the OBSERVED entries
        G = F.T @ F + lam * F.shape[0] * np.eye(k, dtype=np.float64)
        return np.linalg.solve(G, F.T @ r)

    errs = []
    for _sweep in range(config.n_iterations):
        # U half-sweep: per user block, pull the union of the block's
        # observed item rows once
        for b0 in range(0, m, user_block):
            users = range(b0, min(b0 + user_block, m))
            need = np.unique(np.concatenate(
                [user_cols[i] for i in users
                 if user_cols[i].size] or [np.empty(0, np.int64)]))
            if not need.size:
                continue
            Vblk = pull(need).astype(np.float64)
            for i in users:
                cols = user_cols[i]
                if not cols.size:
                    continue
                sel = np.searchsorted(need, cols)
                U[i] = solve(Vblk[sel],
                             R[i, cols].astype(np.float64)
                             ).astype(np.float32)
        # V half-sweep: per item block, solve the touched rows from
        # local U and push the per-row deltas (pull old values first —
        # the delta is the wire object, same as every rowstore push);
        # blocked like the U pulls so the budget holds on BOTH halves
        U64 = U.astype(np.float64)
        sq_err = 0.0
        item_blk = (min(user_block * 4, model_budget_rows)
                    if model_budget_rows else user_block * 4)
        for t0 in range(0, touched_items.shape[0], item_blk):
            items = touched_items[t0:t0 + item_blk]
            old = pull(items)
            new = np.empty_like(old)
            for t, j in enumerate(items):
                users = item_users[j]
                new[t] = solve(U64[users],
                               R[users, j].astype(np.float64)
                               ).astype(np.float32)
            store.merge_rows(store.version, [
                (0, {"V": (items, new - old, store.version)})])
            rows_pushed += int(items.shape[0])
            # observed-entry RMSE from the rows already in hand
            mask = observed[:, items]
            pred = np.einsum("ik,tk->it", U, new)[mask]
            sq_err += np.sum((pred - R[:, items][mask]) ** 2)
        errs.append(np.sqrt(sq_err / n_obs))

    dense_rows = n_pulls * n
    return {
        "U": U,
        "V": store.snapshot()["V"],
        "rmse_history": np.asarray(errs, np.float32),
        "peak_pull_rows": peak_pull,
        "sparse_pull_fraction": (rows_pulled / dense_rows
                                 if dense_rows else 0.0),
        "rows_pulled": rows_pulled,
        "rows_pushed": rows_pushed,
        "row_versions": store.row_versions("V"),
    }
