"""ALS matrix factorization (the reference's "matrix decomposition").

Re-design of ``/root/reference/matrix_computation/matrix_decomposition.py``:
the reference broadcasts the FULL dense R, U, V to every task and solves one
row per Spark task (``:46-48,52-62``) — SURVEY.md §2.3 calls this the one
place the broadcast-everything design visibly fails to scale. Here R stays
row-sharded over the mesh ``data`` axis permanently; each half-sweep is a
batched normal-equation solve under GSPMD: the k×k Gram is computed once
(the reference recomputes it in every task), the cross-shard contraction
``Uᵀ·R`` is an XLA-inserted AllReduce over ICI, and factors carry sharding
constraints so nothing dense is ever replicated needlessly.

R's rows are zero-padded to the shard count; padded rows solve to exactly
zero factor rows (zero RHS against a PD Gram), so they contribute nothing to
Grams, RMSE numerator, or the V-update — the RMSE denominator uses the true
m·n (``matrix_decomposition.py:19-21``).

Measured cost attribution at bench scale (4096×16384 rank-64, one v5e,
``scripts/als_profile.py`` — scan-wrapped component benchmarks):
~2.16 ms/sweep total = solves ~1.5-1.7 ms + per-sweep RMSE ~1.4 ms
(overlapped by XLA). The sweep is bound by full passes over the 268 MB
R (two solve right-hand sides + the RMSE diff) with the HIGHEST-
precision multi-pass matmuls adding ~30-40% — and those pins are
load-bearing: DEFAULT-precision right-hand sides or a HIGH (bf16x3)
RMSE save ~0.4 ms each but cost the exact rank-k recovery this module
asserts (final rmse 2e-5). Rejected, measured: a blocked RMSE that
avoids materialising the (m, n) diff runs SLOWER (1.67 vs 1.40 ms —
the scan serialises and the narrow matmuls under-fill the MXU), and
an algebraic RMSE via ‖R‖² − 2·tr((UᵀR)V) + tr((UᵀU)(VᵀV)) dies on
f32 cancellation (resolving rmse 2e-5 against ‖R‖²~1e8 needs ~10
significant digits). The design is at its traffic floor given the
precision contract.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from tpu_distalg.ops import linalg
from tpu_distalg.parallel import (
    DATA_AXIS,
    data_sharding,
    pad_rows,
    replicated_sharding,
)
from tpu_distalg.utils import metrics


@dataclasses.dataclass(frozen=True)
class ALSConfig:
    """Knob names follow ``matrix_decomposition.py:12-17``."""

    lam: float = 0.01
    m: int = 100
    n: int = 500
    k: int = 10
    n_iterations: int = 5
    seed: int = 0


@dataclasses.dataclass
class ALSResult:
    U: jax.Array
    V: jax.Array
    rmse_history: jax.Array  # per-sweep RMSE

    @property
    def final_rmse(self) -> float:
        return float(self.rmse_history[-1])


def synthesize_rank_k(config: ALSConfig) -> np.ndarray:
    """R = U₀·V₀ᵀ with U₀, V₀ ~ U[0,1) — the reference's synthetic
    exactly-rank-k target (``matrix_decomposition.py:42``)."""
    rng = np.random.default_rng(config.seed)
    U0 = rng.random((config.m, config.k), dtype=np.float32)
    V0 = rng.random((config.n, config.k), dtype=np.float32)
    return U0 @ V0.T


def make_fit_fn(mesh: Mesh, config: ALSConfig):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_distalg.parallel import MODEL_AXIS

    denom = config.m * config.n  # true element count, not padded
    rows = data_sharding(mesh, ndim=2)
    # shard the item factor over the model axis when it divides evenly —
    # the model-parallel einsum SURVEY.md §2.3 calls for, replacing the
    # reference's broadcast of full V to every task (:46-48)
    n_model = mesh.shape[MODEL_AXIS]
    v_sharding = (
        NamedSharding(mesh, P(MODEL_AXIS, None))
        if n_model > 1 and config.n % n_model == 0 else None
    )

    def fit(R, U0, V0):
        def sweep(carry, _):
            U, V = carry
            # U-update: (VᵀV + λ·n·I) uᵢ = Vᵀ R[i,:]  (:52-54, :24-33)
            G_v = linalg.gram(V, config.lam, config.n)
            U = linalg.solve_factor_block(G_v, V, R)
            U = lax.with_sharding_constraint(U, rows)
            # V-update against Rᵀ: (UᵀU + λ·m·I) vⱼ = Uᵀ R[:,j]  (:60-62)
            G_u = linalg.gram(U, config.lam, config.m)
            V = linalg.solve_factor_block(G_u, U, R.T)
            if v_sharding is not None:
                V = lax.with_sharding_constraint(V, v_sharding)
            # padded rows are exactly zero on both sides; 'highest'
            # precision keeps the reconstruction error measurement from
            # being floored by TPU bf16 matmul passes
            diff = R - jnp.matmul(U, V.T, precision=lax.Precision.HIGHEST)
            err = jnp.sqrt(jnp.sum(diff * diff) / denom)  # :19-21
            return (U, V), err

        (U, V), errs = jax.lax.scan(
            sweep, (U0, V0), None, length=config.n_iterations
        )
        return U, V, errs

    return jax.jit(fit)


def fit(mesh: Mesh, config: ALSConfig = ALSConfig(),
        R: np.ndarray | None = None,
        *,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 5) -> ALSResult:
    """Fit U·Vᵀ ≈ R; optionally checkpointed per ``checkpoint_every``
    sweeps (carry = the (U, V) factor pair; ALS sweeps are
    deterministic functions of the factors, so segmented and straight
    runs are bitwise-identical)."""
    if R is None:
        R = synthesize_rank_k(config)
    elif R.shape != (config.m, config.n):
        # caller-supplied R wins: m/n drive the RMSE denominator, the
        # Gram regularisation scale, and the U truncation
        config = dataclasses.replace(config, m=R.shape[0], n=R.shape[1])
    n_shards = mesh.shape[DATA_AXIS]
    R_padded, _mask = pad_rows(np.asarray(R, dtype=np.float32), n_shards)

    rng = np.random.default_rng(config.seed + 1)
    # U0 is never read: the first half-sweep recomputes U from (V, R)
    # exactly as the reference's first parallelize(range(m)) pass does
    U0 = np.zeros((R_padded.shape[0], config.k), dtype=np.float32)
    V0 = rng.random((config.n, config.k), dtype=np.float32)

    rows = data_sharding(mesh, ndim=2)
    repl = replicated_sharding(mesh)
    R_dev = jax.device_put(jnp.asarray(R_padded), rows)
    U_dev = jax.device_put(jnp.asarray(U0), rows)
    V_dev = jax.device_put(jnp.asarray(V0), repl)

    if checkpoint_dir is None:
        fn = make_fit_fn(mesh, config)
        U, V, errs = fn(R_dev, U_dev, V_dev)
        metrics.guard_finite(errs, "ALS rmse history")
        return ALSResult(U=U[: config.m], V=V, rmse_history=errs)

    from tpu_distalg.utils import checkpoint as ckpt

    def run_seg(fn, state, t0):
        del t0  # sweeps carry no PRNG; the factors are the whole state
        U, V = state
        U = jax.device_put(jnp.asarray(U), rows)
        V = jax.device_put(jnp.asarray(V), repl)
        U, V, errs = fn(R_dev, U, V)
        return (U, V), errs

    (U, V), errs, _ = ckpt.run_segmented(
        checkpoint_dir, checkpoint_every, config.n_iterations,
        make_seg_fn=lambda seg: make_fit_fn(
            mesh, dataclasses.replace(config, n_iterations=seg)),
        run_seg=run_seg,
        state0=(U_dev, V_dev),
        tag="als",
    )
    return ALSResult(
        U=jnp.asarray(U)[: config.m], V=jnp.asarray(V),
        rmse_history=jnp.asarray(errs),
    )
