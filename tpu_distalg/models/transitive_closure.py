"""Transitive closure by fixpoint iteration.

Re-design of ``/root/reference/graph_computation/transitive_closure.py``:
the reference joins the full path set against reversed edges, unions, dedups
and counts every round until the count stops growing (``:27-40``) — a
shuffle-heavy O(rounds) Spark pipeline with dynamic-size sets. Dynamic set
semantics don't exist under XLA's static shapes (SURVEY.md §7 hard part #3),
so the path set is a dense boolean V×V matrix: one round is a boolean
matmul on the MXU (edge ∘ path composition) + logical-or union, the
``distinct`` is free (idempotent |), and the fixpoint test compares popcounts
inside ``lax.while_loop`` — matching the reference's count-based convergence
(``:38-40``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from tpu_distalg.ops import graph as gops
from tpu_distalg.parallel import DATA_AXIS, data_sharding


@dataclasses.dataclass(frozen=True)
class ClosureConfig:
    max_iterations: int | None = None  # None → V (always enough)


@dataclasses.dataclass
class ClosureResult:
    paths: jax.Array  # (V, V) bool reachability
    n_paths: int      # the reference's final paths.count() (:42)
    n_rounds: int


def run(edges: np.ndarray, mesh: Mesh,
        config: ClosureConfig = ClosureConfig(),
        n_vertices: int | None = None) -> ClosureResult:
    el = gops.prepare_edges(edges, n_vertices)
    n_shards = mesh.shape[DATA_AXIS]
    # pad vertex count so path-matrix rows shard evenly; padded vertices are
    # isolated (no edges) and add no paths
    V = -(-el.n_vertices // n_shards) * n_shards
    cap = config.max_iterations if config.max_iterations is not None else V + 1

    adj = np.zeros((V, V), dtype=bool)
    adj[el.src, el.dst] = True
    rows = data_sharding(mesh, ndim=2)

    @jax.jit
    def fixpoint(edges_bool):
        paths0 = edges_bool  # paths start as the edge set (:18-27)
        cnt0 = gops.path_count(paths0)

        def cond(state):
            _, old_cnt, cnt, it = state
            return (cnt != old_cnt) & (it < cap)

        def body(state):
            paths, _, cnt, it = state
            new_paths = gops.closure_step(paths, edges_bool)
            new_paths = lax.with_sharding_constraint(new_paths, rows)
            return new_paths, cnt, gops.path_count(new_paths), it + 1

        return lax.while_loop(
            cond, body, (paths0, jnp.int32(-1), cnt0, jnp.int32(0))
        )

    paths, _, cnt, rounds = fixpoint(jnp.asarray(adj))
    return ClosureResult(
        paths=paths, n_paths=int(cnt), n_rounds=int(rounds)
    )
