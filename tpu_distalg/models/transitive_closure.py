"""Transitive closure by fixpoint iteration.

Re-design of ``/root/reference/graph_computation/transitive_closure.py``:
the reference joins the full path set against reversed edges, unions, dedups
and counts every round until the count stops growing (``:27-40``) — a
shuffle-heavy O(rounds) Spark pipeline with dynamic-size sets. Dynamic set
semantics don't exist under XLA's static shapes (SURVEY.md §7 hard part #3),
so the path set is a dense boolean V×V matrix: one round is a boolean
matmul on the MXU (edge ∘ path composition) + logical-or union, the
``distinct`` is free (idempotent |), and the fixpoint test compares popcounts
inside ``lax.while_loop`` — matching the reference's count-based convergence
(``:38-40``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from tpu_distalg.ops import graph as gops
from tpu_distalg.parallel import DATA_AXIS, partition


@dataclasses.dataclass(frozen=True)
class ClosureConfig:
    max_iterations: int | None = None  # None → V (always enough)


@dataclasses.dataclass
class ClosureResult:
    paths: jax.Array  # (V, V) bool reachability
    n_paths: int      # the reference's final paths.count() (:42)
    n_rounds: int


@dataclasses.dataclass(frozen=True)
class SparseClosureConfig:
    """Config for :func:`run_sparse` — the O(closure-size) formulation.

    ``capacity`` bounds the number of distinct paths the buffer can hold
    (static shape; auto = 8×edges). ``join_capacity`` bounds the number
    of (path ⋈ edge) candidates one round may produce (auto =
    max(2×capacity, 8×edges)); unlike a per-vertex-degree pad this is a
    bound on the TRUE join size, so skewed degree distributions cost
    nothing extra. ``max_iterations`` caps the fixpoint (auto = longest
    possible path, V)."""

    capacity: int | None = None
    join_capacity: int | None = None
    max_iterations: int | None = None


@dataclasses.dataclass
class SparseClosureResult:
    paths: np.ndarray  # (n_paths, 2) distinct (x, z) pairs
    n_paths: int
    n_rounds: int


def run(edges: np.ndarray, mesh: Mesh,
        config: ClosureConfig = ClosureConfig(),
        n_vertices: int | None = None, *,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 8) -> ClosureResult:
    el = gops.prepare_edges(edges, n_vertices)
    n_shards = mesh.shape[DATA_AXIS]
    # pad vertex count so path-matrix rows shard evenly; padded vertices are
    # isolated (no edges) and add no paths
    V = -(-el.n_vertices // n_shards) * n_shards
    cap = config.max_iterations if config.max_iterations is not None else V + 1

    adj = np.zeros((V, V), dtype=bool)
    adj[el.src, el.dst] = True
    edges_bool = jnp.asarray(adj)

    def make_seg_fn(seg):
        # one compiled segment: up to ``seg`` more rounds from the
        # carried (paths, old_cnt, cnt, it). With seg=cap this IS the
        # straight fixpoint; smaller seg inserts checkpoint boundaries
        # without changing the round sequence (bitwise-identical).
        @jax.jit
        def seg_fix(eb, paths, old_cnt, cnt, it):
            it_hi = jnp.minimum(it + seg, cap)

            def cond(state):
                _, old, c, i = state
                return (c != old) & (i < it_hi)

            def body(state):
                paths, _, c, i = state
                new_paths = gops.closure_step(paths, eb)
                new_paths = partition.constrain(
                    new_paths, "paths", "closure_dense", mesh)
                return new_paths, c, gops.path_count(new_paths), i + 1

            return lax.while_loop(cond, body, (paths, old_cnt, cnt, it))

        return seg_fix

    state0 = (edges_bool, jnp.int32(-1),  # paths start as the edge set
              gops.path_count(edges_bool), jnp.int32(0))

    if checkpoint_dir is None:
        paths, _, cnt, rounds = make_seg_fn(cap)(edges_bool, *state0)
        return ClosureResult(
            paths=paths, n_paths=int(cnt), n_rounds=int(rounds)
        )

    from tpu_distalg.utils import checkpoint as ckpt

    def run_seg(fn, state, t0):
        paths, old, cnt, it = fn(edges_bool, state["paths"],
                                 state["old"], state["cnt"],
                                 state["it"])
        new = {"paths": paths, "old": old, "cnt": cnt, "it": it}
        return new, np.asarray(cnt, np.float32)[None]

    state, _, _ = ckpt.run_segmented(
        checkpoint_dir, checkpoint_every, cap, make_seg_fn, run_seg,
        {"paths": state0[0], "old": state0[1], "cnt": state0[2],
         "it": state0[3]},
        tag="closure_dense",
        stop_when=lambda s: int(s["cnt"]) == int(s["old"]))
    return ClosureResult(paths=jnp.asarray(state["paths"]),
                         n_paths=int(state["cnt"]),
                         n_rounds=int(state["it"]))


def run_sparse(edges: np.ndarray, mesh: Mesh,
               config: SparseClosureConfig = SparseClosureConfig(),
               n_vertices: int | None = None, *,
               checkpoint_dir: str | None = None,
               checkpoint_every: int = 8) -> SparseClosureResult:
    """Transitive closure without the V×V matrix — O(closure size) memory.

    The dense fixpoint (:func:`run`) is the right shape for small/dense
    graphs (boolean matmul rides the MXU) but its V×V path matrix is dead
    at ~100k+ vertices (SURVEY.md §2.2 names the alternative: "sort-based
    dedup for sparse"). Here the path set is what Spark's RDD was — a set
    of (x, z) pairs — mapped to static shapes:

      * a capacity-capped ``(C,)`` pair buffer, valid entries sorted
        first, sentinel (V, V) padding sorting last;
      * one round ≙ the reference's ``join`` + ``union().distinct()``
        (``transitive_closure.py:33-37``): a CSR segmented-expand joins
        every path (x, y) with y's out-edges — per-path counts →
        prefix-sum → scatter-max path markers → ``cummax`` recovers the
        owning path of each candidate slot, so the round's work is
        proportional to the TRUE join size (no per-vertex degree
        padding; skewed graphs cost nothing extra) — then concatenate
        with the known set (union), two-key ``lax.sort`` +
        neighbor-diff mask (distinct), and one more sort to compact
        uniques back into the buffer;
      * fixpoint when ``count`` stops growing — the reference's
        count-based convergence (``:38-40``), inside ``lax.while_loop``.

    Like the reference it re-joins the FULL path set each round (naïve,
    not frontier/semi-naïve — same asymptotics as the original). The
    sort-dedup is the shuffle equivalent and runs as one global XLA sort.

    Raises if ``capacity`` or ``join_capacity`` overflow (closure or
    one round's join bigger than its buffer).
    """
    el = gops.prepare_edges(edges, n_vertices)
    V = el.n_vertices
    E = el.n_edges
    n_shards = mesh.shape[DATA_AXIS]
    C = (config.capacity if config.capacity is not None
         else max(8 * E, 1024))
    C = -(-C // n_shards) * n_shards
    J = (config.join_capacity if config.join_capacity is not None
         else max(2 * C, 8 * E, 1024))
    cap = (config.max_iterations if config.max_iterations is not None
           else V + 1)

    from tpu_distalg import native

    if E > C:
        raise ValueError(f"capacity {C} < edge count {E}")
    # CSR over src (prepare_edges sorts by src); sentinel vertex V has
    # degree 0 so expanding an invalid path yields nothing
    offsets = np.zeros(V + 2, dtype=np.int64)
    if E:
        offsets[: V + 1] = native.csr_offsets(el.src.astype(np.int64), V)
        offsets[V + 1] = offsets[V]
    deg = np.diff(offsets).astype(np.int32)          # (V+1,)
    px0 = np.full(C, V, dtype=np.int32)
    pz0 = np.full(C, V, dtype=np.int32)
    px0[:E] = el.src
    pz0[:E] = el.dst

    # the path buffer stays REPLICATED: the sort-dedup is inherently
    # global, and XLA's partitioned sort on a row-sharded buffer (tested
    # on the 8-device CPU mesh) is orders of magnitude slower than one
    # local sort — the shuffle this replaces was Spark's global shuffle
    # too. Memory is O(closure), not O(V²), so replication is cheap.
    px0 = jnp.asarray(px0)
    pz0 = jnp.asarray(pz0)
    deg_d = jnp.asarray(deg)
    off_d = jnp.asarray(offsets[: V + 1].astype(np.int32))
    dst_d = jnp.asarray(el.dst)                      # src-sorted

    def make_seg_fn(seg):
        # one compiled segment of up to ``seg`` more rounds from the
        # carried fixpoint state; seg=cap is the straight run, smaller
        # seg adds checkpoint boundaries (bitwise-identical rounds)
        @jax.jit
        def fixpoint(px, pz, old_cnt0, cnt0, it0, overflow0,
                     deg, off, dst):
            it_hi = jnp.minimum(it0 + seg, cap)

            def count_valid(x):
                return jnp.sum((x < V).astype(jnp.int32))

            def cond(state):
                _, _, old_cnt, cnt, it, overflow = state
                # ~overflow: fail fast — once a round overflows its
                # buffers the result can never be trusted, so don't pay
                # the remaining rounds
                return (cnt != old_cnt) & (it < it_hi) & ~overflow

            def body(state):
                px, pz, _, cnt, it, overflow = state
                # join (x,y) ⋈ edges(y,·) via segmented expand: path p owns
                # candidate slots [start_p, start_p + deg(pz_p))
                k = deg[pz]                              # (C,)
                start = jnp.cumsum(k) - k                # exclusive prefix
                K = start[-1] + k[-1]                    # true join size
                # K is int32 and can wrap when the true join exceeds 2^31. The
                # exact K > J test catches every non-wrapping overflow; K < 0
                # catches true sizes in (2^31, 2^32); the f32 sum catches
                # >= 2^32 wrap-to-positive. Kf is compared against 2^31 (not J)
                # because the tree-reduction rounding of the f32 sum could
                # otherwise spuriously trip on a valid round with K ~ J.
                Kf = jnp.sum(k.astype(jnp.float32))
                overflow = (overflow | (K > J) | (K < 0)
                            | (Kf > jnp.float32(2**31)))
                # mark slot start_p with p+1 (k>0 paths only), cummax fills
                # the segment; -1 → owning path id
                marks = jnp.zeros((J,), jnp.int32).at[
                    jnp.where(k > 0, start, J)
                ].max(jnp.arange(C, dtype=jnp.int32) + 1, mode="drop")
                pid = jax.lax.cummax(marks) - 1          # (J,)
                slot = jnp.arange(J, dtype=jnp.int32)
                valid = (slot < K) & (pid >= 0)
                pid = jnp.where(valid, pid, 0)
                rank = slot - start[pid]
                eidx = jnp.clip(off[pz[pid]] + rank, 0, max(E - 1, 0))
                cx = jnp.where(valid, px[pid], V)
                cz = jnp.where(valid, dst[eidx], V) if E else jnp.full(
                    (J,), V, jnp.int32)
                ax = jnp.concatenate([px, cx])           # union
                az = jnp.concatenate([pz, cz])
                ax, az = jax.lax.sort((ax, az), num_keys=2)
                dup = jnp.concatenate([
                    jnp.zeros((1,), bool),
                    (ax[1:] == ax[:-1]) & (az[1:] == az[:-1]),
                ])
                uniq = (ax < V) & ~dup                   # distinct
                ax = jnp.where(uniq, ax, V)
                az = jnp.where(uniq, az, V)
                ax, az = jax.lax.sort((ax, az), num_keys=2)  # compact
                new_cnt = count_valid(ax)
                overflow = overflow | (new_cnt > C)
                return (ax[:C], az[:C], cnt, jnp.minimum(new_cnt, C),
                        it + 1, overflow)

            return jax.lax.while_loop(
                cond, body,
                (px, pz, old_cnt0, cnt0, it0, overflow0),
            )

        return fixpoint

    cnt0 = jnp.int32(E)  # every buffer entry < V is a real edge
    state0 = (px0, pz0, jnp.int32(-1), cnt0, jnp.int32(0),
              jnp.bool_(False))

    if checkpoint_dir is None:
        px, pz, _, cnt, rounds, overflow = make_seg_fn(cap)(
            *state0, deg_d, off_d, dst_d)
    else:
        from tpu_distalg.utils import checkpoint as ckpt

        def run_seg(fn, state, t0):
            px, pz, old, cnt, it, ov = fn(
                state["px"], state["pz"], state["old"], state["cnt"],
                state["it"], state["ov"], deg_d, off_d, dst_d)
            new = {"px": px, "pz": pz, "old": old, "cnt": cnt,
                   "it": it, "ov": ov}
            return new, np.asarray(cnt, np.float32)[None]

        state, _, _ = ckpt.run_segmented(
            checkpoint_dir, checkpoint_every, cap, make_seg_fn,
            run_seg,
            {"px": state0[0], "pz": state0[1], "old": state0[2],
             "cnt": state0[3], "it": state0[4], "ov": state0[5]},
            tag="closure_sparse",
            stop_when=lambda s: (bool(s["ov"])
                                 or int(s["cnt"]) == int(s["old"])))
        px, pz = jnp.asarray(state["px"]), jnp.asarray(state["pz"])
        cnt, rounds, overflow = state["cnt"], state["it"], state["ov"]

    n_paths = int(cnt)
    if bool(overflow):
        raise ValueError(
            f"closure overflowed its buffers (capacity {C}, "
            f"join_capacity {J}); rerun with a larger "
            f"SparseClosureConfig.capacity/join_capacity"
        )
    pairs = np.stack(
        [np.asarray(px[:n_paths]), np.asarray(pz[:n_paths])], axis=1
    )
    return SparseClosureResult(
        paths=pairs, n_paths=n_paths, n_rounds=int(rounds)
    )


#: per-path buffer cost of one :func:`run_sparse` fixpoint round:
#: px/pz (2 int32) plus the two-key sort's union copy at C + J slots
#: (J defaults to 2C) — ~8 B/slot across ~4C live slots. The auto-
#: sizer budgets against THIS figure, so its refusal names real bytes.
SPARSE_BYTES_PER_CAPACITY_SLOT = 32


def run_sparse_auto(edges: np.ndarray, mesh: Mesh, *,
                    n_vertices: int | None = None,
                    start_capacity: int | None = None,
                    budget_bytes: int = 4 << 30,
                    max_iterations: int | None = None,
                    checkpoint_dir: str | None = None,
                    checkpoint_every: int = 8) -> SparseClosureResult:
    """:func:`run_sparse` with CAPACITY AUTO-SIZING — the scale story
    (VERDICT advice #8): the closure size is unknown until computed
    (the reference's ``paths.count()`` loop has the same property), so
    the buffer is grown geometrically on overflow — start at
    ``start_capacity`` (default: ``run_sparse``'s 8×edges heuristic),
    DOUBLE on the overflow error, re-run the fixpoint. Each retry pays
    the full fixpoint again (the overflow poisons the buffer, there is
    nothing to resume), which is the honest cost of static shapes;
    the doubling schedule bounds total work at ≤ 2× the final run.

    The DOCUMENTED REFUSAL: a capacity whose working set
    (``capacity × SPARSE_BYTES_PER_CAPACITY_SLOT``) would exceed
    ``budget_bytes`` raises ``ValueError`` naming the budget, the
    capacity it refused, and the remedy (a bigger ``budget_bytes`` or
    the dense path) — it never silently truncates a closure.

    With ``checkpoint_dir``, each capacity attempt owns the directory:
    an overflowed attempt's checkpoints hold the OLD ``(C,)``-shaped
    buffers (and a poisoned fixpoint), so they are pruned before the
    doubled retry — without that, ``run_segmented``'s state-signature
    check would reject the regrown shapes as a foreign workload and
    auto-sizing could never complete a checkpointed run.
    """
    from tpu_distalg.telemetry import events as tevents

    E = int(np.asarray(edges).shape[0]) if len(edges) else 0
    cap = (int(start_capacity) if start_capacity is not None
           else max(8 * E, 1024))
    # the buffer must at least hold the edge set (run_sparse's own
    # precondition) — an undersized explicit start_capacity is a
    # growth starting point, not a hard error
    cap = max(cap, E)
    while True:
        if cap * SPARSE_BYTES_PER_CAPACITY_SLOT > budget_bytes:
            raise ValueError(
                f"sparse closure refused: capacity {cap} needs "
                f"~{cap * SPARSE_BYTES_PER_CAPACITY_SLOT / 1e9:.1f} GB "
                f"working set, over the {budget_bytes / 1e9:.1f} GB "
                f"budget — the closure is larger than the budget "
                f"allows; raise budget_bytes, or use the dense path "
                f"(run) if V×V bits fit")
        try:
            return run_sparse(
                edges, mesh,
                SparseClosureConfig(capacity=cap,
                                    max_iterations=max_iterations),
                n_vertices,
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every)
        except ValueError as e:
            if "overflowed its buffers" not in str(e):
                raise
            if checkpoint_dir is not None:
                from tpu_distalg.utils import checkpoint as ckpt

                ckpt.prune(checkpoint_dir, keep=0)
            tevents.emit("closure_capacity_grow", capacity=cap,
                         next_capacity=cap * 2)
            tevents.counter("closure.capacity_regrows")
            cap *= 2
