"""BMUF — blockwise model update filtering.

MA plus a block-level momentum filter on the averaged update:
``delta_w = μ·delta_w + ζ·(w_avg − w); w += delta_w``
(``/root/reference/optimization/bmuf.py:113-114``, μ=0.9 ζ=0.1 ``:24-25``).
``delta_w`` starts *random* like the reference (``bmuf.py:95``) unless
``random_delta_init=False``.

Inherits the full comm treatment from :mod:`~tpu_distalg.models.local_sgd`:
``comm='int8'``/``'topk'``/... compresses the round-end average on the
native wire, with the bucket-overlap pipeline on by default (``@seq``
disables — bitwise-identical). Likewise the sync discipline:
``sync='ssp[:s]'`` applies the block-momentum filter once per
``s``-round window to the STALENESS-WEIGHTED average (straggled
replicas down-weighted by ``decay^age`` instead of stalling the mesh;
seeded ``shard:straggle``/``shard:leave`` plan rules, bitwise replay).
"""

from __future__ import annotations

import dataclasses

from jax.sharding import Mesh

from tpu_distalg.models import local_sgd
from tpu_distalg.models.local_sgd import TrainResult


@dataclasses.dataclass(frozen=True)
class BMUFConfig(local_sgd.LocalSGDConfig):
    n_iterations: int = 300
    n_local_iterations: int = 5
    global_update: str = "bmuf"
    resync: bool = True
    mu: float = 0.9
    zeta: float = 0.1


def train(X_train, y_train, X_test, y_test, mesh: Mesh,
          config: BMUFConfig = BMUFConfig(), *,
          checkpoint_dir: str | None = None,
          checkpoint_every: int = 100) -> TrainResult:
    return local_sgd.train(X_train, y_train, X_test, y_test, mesh, config,
                           checkpoint_dir=checkpoint_dir,
                           checkpoint_every=checkpoint_every)
