"""Monte-Carlo π estimation.

Re-design of ``/root/reference/randomized_algorithm/monte_carlo.py``: the
reference maps an *unseeded* ``random()`` acceptance test over an RDD of
range(n) and ``reduce(add)``s the hits (``:17-20,28``). Here each mesh
shard draws its darts from a counter-based key (``fold_in(key, shard)``),
counts hits in a fused local reduction (chunked to bound VMEM/HBM), and one
psum produces the global count — deterministic given the seed, unlike the
reference (SURVEY.md appendix).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_distalg.ops import sampling
from tpu_distalg.parallel import (
    DATA_AXIS,
    comms,
    data_parallel,
    replica_index,
)
from tpu_distalg.utils import prng


@dataclasses.dataclass(frozen=True)
class MonteCarloConfig:
    n: int = 400_000  # monte_carlo.py:13-15 (100000 * n_slices)
    seed: int = 42
    chunk: int = 1 << 20


def estimate_pi(mesh: Mesh, config: MonteCarloConfig = MonteCarloConfig()):
    """Returns (pi_estimate, n_used). n is rounded up to a multiple of the
    shard count × chunking, all darts are counted."""
    import numpy as np

    n_shards = mesh.shape[DATA_AXIS]
    per_shard = -(-config.n // n_shards)
    n_chunks, per = sampling.mc_chunk_plan(per_shard, config.chunk)
    n_used = n_shards * n_chunks * per
    key = prng.root_key(config.seed)

    def local(_dummy):
        shard = replica_index()
        k = jax.random.fold_in(key, shard)
        per_chunk = sampling.mc_circle_hits_chunked(
            k, per_shard, config.chunk
        )
        # per-chunk psum stays ≤ 2^20 · n_shards: int32-safe; the final
        # (possibly > 2^31) total is summed in int64 on the host
        return comms.psum(per_chunk, DATA_AXIS)

    fn = data_parallel(
        local, mesh,
        in_specs=(P("data"),),
        out_specs=P(),
    )
    dummy = jnp.zeros((n_shards,), dtype=jnp.int32)
    per_chunk = jax.jit(fn)(dummy)
    hits = int(np.asarray(per_chunk).astype(np.int64).sum())
    return 4.0 * hits / float(n_used), n_used
