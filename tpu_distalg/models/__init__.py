"""Workload entry points — one per reference script (SURVEY.md §2.1).

Each module exposes a config dataclass (same knob names as the reference's
module-level globals, for traceability) and a ``train``/``run`` function
whose whole iteration loop compiles to a single XLA program — the reference
launches one Spark job per iteration (SURVEY.md §2.4); we launch one program
per workload.
"""

from tpu_distalg.models import (
    als,
    bmuf,
    easgd,
    kmeans,
    local_sgd,
    logistic_regression,
    ma,
    monte_carlo,
    pagerank,
    ssgd,
    transitive_closure,
)

__all__ = [
    "als",
    "bmuf",
    "easgd",
    "kmeans",
    "local_sgd",
    "logistic_regression",
    "ma",
    "monte_carlo",
    "pagerank",
    "ssgd",
    "transitive_closure",
]
