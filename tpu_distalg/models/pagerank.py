"""PageRank power iteration.

Re-design of ``/root/reference/graph_computation/pagerank.py``: the
join+flatMap+reduceByKey shuffle pipeline (``:50-57``) becomes an
edge-parallel sweep — edges are sharded over the mesh data axis; each shard
gathers ``ranks[src]``, scatters contributions into a dense rank vector via
``segment_sum``, and one psum combines shards. Ten iterations compile into
a single ``lax.scan``; the reference executes them as one 10-join-deep lazy
lineage at collect time (SURVEY.md §3.4).

Two modes (SURVEY.md §7 hard part #6):
  * ``mode='reference'`` reproduces the reference's semantics exactly: n is
    the number of vertices WITH out-links (``:41-44``), sink vertices keep
    no rank and their mass vanishes (no dangling handling — ranks don't sum
    to 1, see the recorded outputs ``:66-68``), and a vertex only holds a
    rank in round t+1 if it received a contribution in round t.
  * ``mode='standard'`` is textbook PageRank over all vertices with optional
    dangling-mass redistribution — what you actually want at 1M nodes.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpu_distalg.ops import graph as gops
from tpu_distalg.parallel import (
    DATA_AXIS,
    data_parallel,
    data_sharding,
    pad_rows,
    tree_allreduce_sum,
)


@dataclasses.dataclass(frozen=True)
class PageRankConfig:
    """Knob names follow ``pagerank.py:17-19``."""

    n_iterations: int = 10
    q: float = 0.15
    mode: str = "reference"  # 'reference' | 'standard'
    redistribute_dangling: bool = True  # standard mode only


@dataclasses.dataclass
class PageRankResult:
    ranks: jax.Array      # (V,) dense rank vector
    has_rank: jax.Array   # (V,) bool: vertex holds a rank (reference mode)


def _local_sweep(src, dst, emask, ranks, inv_deg, has_rank, n_vertices):
    """Per-shard contribution scatter + cross-shard combine."""
    active = emask * has_rank[src]
    per_edge = ranks[src] * inv_deg[src] * active
    c = gops.scatter_add(per_edge, dst, n_vertices)
    received = gops.scatter_add(active, dst, n_vertices)
    return tree_allreduce_sum((c, received))


def make_run_fn(mesh: Mesh, config: PageRankConfig, n_vertices: int):
    def body(src, dst, emask, ranks, inv_deg, has_rank):
        return _local_sweep(
            src, dst, emask, ranks, inv_deg, has_rank, n_vertices
        )

    sweep_fn = data_parallel(
        body,
        mesh,
        in_specs=(P("data"), P("data"), P("data"), P(), P(), P()),
        out_specs=(P(), P()),
    )

    def run(src, dst, emask, inv_deg, has_out, n_ref):
        q = config.q
        if config.mode == "reference":
            ranks0 = jnp.where(has_out > 0, 1.0 / n_ref, 0.0)  # :47
            has_rank0 = has_out

            def step(carry, _):
                ranks, has_rank = carry
                c, received = sweep_fn(
                    src, dst, emask, ranks, inv_deg, has_rank
                )
                new_has = (received > 0).astype(jnp.float32)
                ranks = jnp.where(
                    received > 0, q / n_ref + (1 - q) * c, 0.0
                )  # :57
                return (ranks, new_has), None

            (ranks, has_rank), _ = jax.lax.scan(
                step, (ranks0, has_rank0), None,
                length=config.n_iterations,
            )
            return ranks, has_rank

        # standard mode: every vertex ranked, Σranks preserved
        V = n_vertices
        ranks0 = jnp.full((V,), 1.0 / V, dtype=jnp.float32)
        all_ranked = jnp.ones((V,), dtype=jnp.float32)

        def step(ranks, _):
            c, _ = sweep_fn(src, dst, emask, ranks, inv_deg, all_ranked)
            if config.redistribute_dangling:
                dangling = jnp.sum(ranks * (1.0 - has_out))
                c = c + dangling / V
            ranks = q / V + (1 - q) * c
            return ranks, None

        ranks, _ = jax.lax.scan(
            step, ranks0, None, length=config.n_iterations
        )
        return ranks, all_ranked

    return jax.jit(run)


def run(edges: np.ndarray, mesh: Mesh,
        config: PageRankConfig = PageRankConfig(),
        n_vertices: int | None = None) -> PageRankResult:
    el = gops.prepare_edges(edges, n_vertices)
    n_shards = mesh.shape[DATA_AXIS]
    V = el.n_vertices

    ev = np.stack([el.src, el.dst], axis=1)
    ev_padded, emask = pad_rows(ev, n_shards)
    shard1 = data_sharding(mesh, 1)
    src = jax.device_put(jnp.asarray(ev_padded[:, 0]), shard1)
    dst = jax.device_put(jnp.asarray(ev_padded[:, 1]), shard1)
    emask_d = jax.device_put(jnp.asarray(emask), shard1)

    deg = el.out_degree.astype(np.float32)
    inv_deg = np.where(deg > 0, 1.0 / np.maximum(deg, 1.0), 0.0)
    has_out = (deg > 0).astype(np.float32)
    n_ref = float(has_out.sum())  # n_vertexes = count with out-links (:41-44)

    fn = make_run_fn(mesh, config, V)
    ranks, has_rank = fn(
        src, dst, emask_d,
        jnp.asarray(inv_deg), jnp.asarray(has_out), n_ref,
    )
    return PageRankResult(ranks=ranks, has_rank=has_rank)
