"""PageRank power iteration.

Re-design of ``/root/reference/graph_computation/pagerank.py``: the
join+flatMap+reduceByKey shuffle pipeline (``:50-57``) becomes an
edge-parallel sweep — edges are sharded over the mesh data axis; each shard
gathers ``ranks[src]``, scatters contributions into a dense rank vector via
``segment_sum``, and one psum combines shards. Ten iterations compile into
a single ``lax.scan``; the reference executes them as one 10-join-deep lazy
lineage at collect time (SURVEY.md §3.4).

TPU layout decisions (random HBM access is the enemy — every random
gather/scatter element costs ~10-15 ns on a v5e through XLA, and that —
not bandwidth — bounds the sweep):

  * ``inv_deg[src]`` never changes across iterations, so it is gathered
    once at prep into a static per-edge weight array — one random gather
    per iteration (``ranks[src]``) instead of three, and standard mode
    skips the ``received`` scatter entirely (together ~2.9× per sweep,
    measured);
  * edges are sorted by ``dst`` ONCE at prep (native C++ counting sort),
    so the contribution scatter is a
    ``segment_sum(indices_are_sorted=True)``; shards are contiguous
    slices of the sorted list, so per-shard sortedness survives
    sharding, and padding uses dst=V-1 (order-preserving, masked out).
    Rejected alternatives, measured no faster: pull/ELL in-edge tables
    (doubles the random accesses) and prefix-sum segmented reduction
    (f32 prefix differences can't resolve 1e-6-scale ranks);
  * standard mode goes one further: dst-sortedness means consecutive
    edges target a narrow band of a (V/128, 128) vertex table, so the
    scatter becomes a Pallas kernel (``ops/pallas_pagerank``) that
    keeps the table VMEM-resident and scatter-adds each 1024-edge
    chunk with ONE one-hot MXU matmul — no random-access engine at
    all. Measured: sweep drops ~17 → ~9.2 ns/edge (13.5 iter/s at
    1M×8M on one v5e). The remaining random op, the ``ranks[src]``
    gather, stays in XLA: a Pallas windowed gather is 4× faster in
    isolation but needs src-sorted edges, and re-crossing the per-edge
    array between sort orders costs exactly the random access it
    saves (full analysis: ``ops/pallas_pagerank`` docstring).

Two modes (SURVEY.md §7 hard part #6):
  * ``mode='reference'`` reproduces the reference's semantics exactly: n is
    the number of vertices WITH out-links (``:41-44``), sink vertices keep
    no rank and their mass vanishes (no dangling handling — ranks don't sum
    to 1, see the recorded outputs ``:66-68``), and a vertex only holds a
    rank in round t+1 if it received a contribution in round t.
  * ``mode='standard'`` is textbook PageRank over all vertices with optional
    dangling-mass redistribution — what you actually want at 1M nodes.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpu_distalg.ops import graph as gops
from tpu_distalg.parallel import (
    DATA_AXIS,
    data_parallel,
    partition,
    tree_allreduce_sum,
)


@dataclasses.dataclass(frozen=True)
class PageRankConfig:
    """Knob names follow ``pagerank.py:17-19``."""

    n_iterations: int = 10
    q: float = 0.15
    mode: str = "reference"  # 'reference' | 'standard'
    redistribute_dangling: bool = True  # standard mode only
    scatter: str = "auto"  # 'auto' | 'pallas' | 'xla' (standard mode)


@dataclasses.dataclass
class PageRankResult:
    ranks: jax.Array      # (V,) dense rank vector
    has_rank: jax.Array   # (V,) bool: vertex holds a rank (reference mode)


@dataclasses.dataclass
class DevicePlan:
    """Device-resident :class:`ops.pallas_pagerank.ScatterPlan` arrays."""

    base: jax.Array   # (NCH,) int32, sharded over data
    row: jax.Array    # (NCH, chunk) int32
    lane: jax.Array   # (NCH, chunk) int32
    w: int
    blk: int
    r8: int
    n_chunks: int


@dataclasses.dataclass
class DeviceSpMV:
    """Device-resident :class:`ops.pallas_pagerank.SpMVPlan` arrays —
    the fully-fused Path E sweep (``scatter='spmv'``)."""

    gbase: jax.Array      # (NCH,) int32, sharded over data
    sbase: jax.Array      # (NCH,) int32
    src_lane: jax.Array   # (NCH*8, 128) int32
    src_row: jax.Array    # (NCH*8, 128) int32
    dst_row: jax.Array    # (NCH*8, 128) int32
    dst_lane: jax.Array   # (NCH*8, 128) int32
    w_e: jax.Array        # (NCH*8, 128) f32
    rg: int
    ws: int
    r8: int
    blk: int
    n_chunks: int


@dataclasses.dataclass
class DeviceEdges:
    """dst-sorted, mesh-sharded edge arrays + static per-edge weights."""

    src: jax.Array     # (E_pad,) int32, shards are dst-sorted slices
    dst: jax.Array     # (E_pad,) int32
    w_e: jax.Array     # (E_pad,) f32: inv_deg[src], 0 on padding
    emask: jax.Array   # (E_pad,) f32 edge validity
    inv_deg: jax.Array  # (V,) f32 (kept for parity introspection)
    has_out: jax.Array  # (V,) f32
    n_vertices: int
    n_ref: float        # reference's n = #vertices with out-links (:41-44)
    plan: DevicePlan | None = None  # Pallas scatter prep (standard mode)
    spmv: DeviceSpMV | None = None  # fused Path E prep (scatter='spmv')


def resident_guard_trips(n_vertices: int) -> bool:
    """True when the fused-SpMV VMEM guard would reject this vertex
    count even at the smallest scatter window — the documented ~12M
    resident ceiling (``ops/pallas_pagerank.SPMV_VMEM_BUDGET``). The
    signal the CLI keys its warn-and-degrade-to-streamed on: past this
    line the resident paths either refuse (spmv) or fall back to
    sweeps that need the whole edge set HBM-resident anyway."""
    from tpu_distalg.ops import pallas_pagerank as ppr

    return ppr.spmv_resident_bytes(n_vertices, ppr.SPMV_RG, 8) \
        > ppr.SPMV_VMEM_BUDGET


def choose_data_backend(requested: str, n_vertices: int,
                        scatter: str = "auto"
                        ) -> tuple[str, str | None]:
    """Resolve the pagerank ``--data-backend`` knob against the
    resident VMEM guard: a resident request past the ceiling degrades
    to streamed WITH a warning instead of dying minutes later in the
    sweep prep (the guard used to just refuse). An EXPLICIT
    ``--scatter xla``/``pallas`` resident request is honored — the
    ceiling is the fused-SpMV kernel's table budget, and those sweeps
    carry their own (HBM/plan) limits with remedy-naming errors.
    Returns ``(backend, warning-or-None)``."""
    if requested == "resident" and scatter in ("auto", "spmv") \
            and resident_guard_trips(n_vertices):
        return "streamed", (
            f"[pagerank] {n_vertices} vertices exceed the resident "
            f"sweep's VMEM guard (~12M ceiling, "
            f"ops/pallas_pagerank.SPMV_VMEM_BUDGET) — degrading to "
            f"--data-backend streamed (tpu_distalg/graphs/: edge "
            f"blocks stream from disk, only O(V) state stays in HBM)")
    return requested, None


def _inv_out_degree(el: gops.EdgeList) -> np.ndarray:
    """Per-vertex 1/out_degree (0 for sinks) — THE per-edge weight
    definition, shared by every sweep path (the graph engine's ingest
    included: ``graphs/ingest.inv_out_degree`` is the one
    implementation) so they cannot diverge."""
    from tpu_distalg.graphs.ingest import inv_out_degree

    return inv_out_degree(el.out_degree)


def prepare_device_spmv(el: gops.EdgeList, mesh: Mesh,
                        rg: int | None = None) -> DeviceSpMV | None:
    """Host prep for the fused Path E sweep: two-key edge sort +
    per-chunk window metadata (``ops/pallas_pagerank.plan_spmv``),
    device_put sharded over the data axis by chunk blocks. ``None``
    when the graph's structure exceeds the window caps — callers fall
    back to the hybrid/XLA sweep.

    With ``rg=None`` the gather window ESCALATES (128 → 256 → 512
    rows) until the within-group scatter span fits: the span grows as
    R²/(rg·E), so larger vertex counts need taller windows — 10M
    vertices / 80M edges plans at rg=512 (ws=184) where rg=128
    overflows. Taller windows cost proportionally more unrolled gather
    rows (and Mosaic compile time: ~3 min at rg=512 vs ~10 s at 128);
    each escalation re-sorts, so the 512 attempt on an 80M-edge graph
    spends ~2-3 minutes of host prep. VMEM bounds the table:
    (r8 + ws + rg) · 512 B must stay under the ~100 MB budget, which
    holds to ~12M vertices — ``plan_spmv`` now enforces that budget
    itself (``spmv_resident_bytes``) BEFORE paying the sorts, so
    oversized graphs degrade here instead of failing the Mosaic
    compile minutes later. Each plan attempt runs in a telemetry span
    (``pagerank:plan_spmv:rgN``) — the sorts are exactly the kind of
    multi-minute host phase a stall report must be able to name."""
    from tpu_distalg.ops import pallas_pagerank as ppr
    from tpu_distalg.telemetry import events as tevents

    inv_deg = _inv_out_degree(el)
    n_shards = mesh.shape[DATA_AXIS]
    plan = None
    for r in ((rg,) if rg is not None else (ppr.SPMV_RG, 256, 512)):
        with tevents.span(f"pagerank:plan_spmv:rg{r}",
                          n_edges=int(el.n_edges),
                          n_vertices=int(el.n_vertices)):
            plan = ppr.plan_spmv(el.src, el.dst, inv_deg[el.src],
                                 el.n_vertices, n_shards=n_shards, rg=r)
        if plan is not None:
            break
        tevents.counter("spmv_plan_rejections")
    if plan is None:
        return None
    put = lambda a, n: partition.put(a, n, "pagerank", mesh)  # noqa: E731
    return DeviceSpMV(
        gbase=put(plan.gbase, "gbase"), sbase=put(plan.sbase, "sbase"),
        src_lane=put(plan.src_lane, "src_lane"),
        src_row=put(plan.src_row, "src_row"),
        dst_row=put(plan.dst_row, "dst_row"),
        dst_lane=put(plan.dst_lane, "dst_lane"),
        w_e=put(plan.w_e, "w_e"), rg=plan.rg, ws=plan.ws, r8=plan.r8,
        blk=plan.blk, n_chunks=plan.n_chunks)


def prepare_device_edges(el: gops.EdgeList, mesh: Mesh,
                         plan_chunk: int | None = None,
                         plan_blk: int | None = None,
                         build_plan: bool = True,
                         light: bool = False) -> DeviceEdges:
    """One-time host prep: dst-sort (native C++ counting sort), per-edge
    weight gather, pad, shard — plus the Pallas-scatter window plan
    (``ops/pallas_pagerank.plan_scatter``) when the graph admits one.

    When the plan succeeds, ALL edge arrays adopt its per-shard padding
    (tail replicates each shard's last dst with zero weight/mask), so
    the XLA fallback path and the Pallas path share the same arrays;
    otherwise the legacy dst=V-1 tail padding is used.
    """
    from tpu_distalg import native
    from tpu_distalg.ops import pallas_pagerank as ppr

    deg = el.out_degree.astype(np.float32)
    inv_deg = _inv_out_degree(el)
    V = el.n_vertices
    n_shards = mesh.shape[DATA_AXIS]
    put = lambda a, n: partition.put(a, n, "pagerank", mesh)  # noqa: E731
    has_out = (deg > 0).astype(np.float32)
    if light:
        # the spmv path deletes src/dst/w_e/emask on its first line —
        # skip the counting sort, per-edge gather, and the ~16 B/edge
        # of device uploads entirely; only has_out/n_ref are consumed
        z = np.zeros(n_shards, np.int32)
        zf = np.zeros(n_shards, np.float32)
        return DeviceEdges(
            src=put(z, "src"), dst=put(z, "dst"), w_e=put(zf, "w_e"),
            emask=put(zf, "emask"),
            inv_deg=jnp.asarray(inv_deg), has_out=jnp.asarray(has_out),
            n_vertices=V, n_ref=float(has_out.sum()), plan=None)

    order = native.counting_sort_perm(el.dst, el.n_vertices)
    src_o = el.src[order].astype(np.int32)
    dst_o = el.dst[order].astype(np.int32)
    w_e = inv_deg[src_o]
    E = len(src_o)

    kw = {}
    if plan_chunk is not None:
        kw["chunk"] = plan_chunk
    if plan_blk is not None:
        kw["blk"] = plan_blk
    plan = (ppr.plan_scatter(dst_o, V, n_shards, **kw)
            if E and build_plan else None)
    if plan is not None:
        # per-shard tail padding, driven by the plan's OWN shard
        # slicing (real_per_shard) so src/w/emask can never desync
        # from the dst encoding in plan.row/plan.lane
        sl = plan.shard_len
        src_p = np.zeros(n_shards * sl, np.int32)
        w_p = np.zeros(n_shards * sl, np.float32)
        emask = np.zeros(n_shards * sl, np.float32)
        lo = 0
        for s, n_real in enumerate(plan.real_per_shard):
            src_p[s * sl:s * sl + n_real] = src_o[lo:lo + n_real]
            w_p[s * sl:s * sl + n_real] = w_e[lo:lo + n_real]
            emask[s * sl:s * sl + n_real] = 1.0
            lo += n_real
        # the padded dst is exactly what the plan encoded
        dst_p = (plan.row.reshape(-1) * 128 + plan.lane.reshape(-1)
                 ).astype(np.int32)
        dplan = DevicePlan(
            base=put(plan.base, "base"),
            row=put(plan.row, "row"),
            lane=put(plan.lane, "lane"),
            w=plan.w, blk=plan.blk, r8=plan.r8, n_chunks=plan.n_chunks,
        )
    else:
        n_pad = (-E) % n_shards
        # padding keeps dst sorted (dst=V-1 ≥ every real id) and carries
        # zero weight/mask, so sorted-segment-sum sees an inert tail
        src_p = np.concatenate([src_o, np.zeros(n_pad, np.int32)])
        dst_p = np.concatenate([dst_o, np.full(n_pad, V - 1, np.int32)])
        w_p = np.concatenate([w_e, np.zeros(n_pad, np.float32)])
        emask = np.ones(E + n_pad, np.float32)
        emask[E:] = 0.0
        dplan = None
    return DeviceEdges(
        src=put(src_p, "src"), dst=put(dst_p, "dst"),
        w_e=put(w_p, "w_e"), emask=put(emask, "emask"),
        inv_deg=jnp.asarray(inv_deg), has_out=jnp.asarray(has_out),
        n_vertices=V, n_ref=float(has_out.sum()), plan=dplan,
    )


def make_run_fn(mesh: Mesh, config: PageRankConfig, n_vertices: int,
                plan: DevicePlan | None = None,
                spmv: DeviceSpMV | None = None):
    """Build the jitted n-iteration sweep.

    PRECONDITION: the edge arrays passed to the returned ``run`` MUST be
    dst-sorted per shard with order-preserving padding — exactly what
    :func:`prepare_device_edges` produces. The segment-sums inside promise
    ``indices_are_sorted=True`` to XLA, which is unchecked: unsorted
    ``dst`` yields silently wrong rank sums, not an error. Construct the
    inputs via :func:`prepare_device_edges` (or :func:`run`, which does).

    Standard-mode path choice: with an ``spmv`` plan (and scatter
    'auto'/'spmv') the fully-fused tiled SpMV runs — gather AND
    scatter in one Pallas kernel, measured ~2.9 ns/edge full-iteration
    at 1M×8M on one v5e. 'auto' PREFERS it; the hybrid sweep (XLA
    ``ranks[src]·w`` gather + the windowed one-hot-MXU scatter
    ``plan``, ~9.2 ns/edge) is the fallback when the spmv windows
    exceed their caps, and the XLA-only sweep (~17 ns/edge) the final
    fallback. ``scatter='pallas'``/'spmv' without their plan raise;
    'xla' forces the legacy path (benchmark A/B).
    """
    V = n_vertices
    q = config.q

    if config.scatter not in ("auto", "pallas", "xla", "spmv"):
        raise ValueError(f"unknown scatter mode {config.scatter!r}")
    if config.mode != "standard" and config.scatter != "auto":
        raise ValueError(
            f"scatter={config.scatter!r} only applies to mode="
            "'standard' — the reference-parity mode always uses the "
            "XLA segment_sum path"
        )
    use_pallas = (config.mode == "standard"
                  and config.scatter in ("auto", "pallas")
                  and plan is not None)
    if config.mode == "standard" and config.scatter == "pallas" \
            and plan is None:
        raise ValueError(
            "scatter='pallas' needs a scatter plan — the graph's dst "
            "distribution was too sparse/skewed for a bounded window "
            "(ops/pallas_pagerank.plan_scatter returned None). For "
            "graphs past the resident ceiling, use the streamed "
            "engine instead: --data-backend streamed "
            "(tpu_distalg/graphs/)"
        )
    if config.mode == "standard" and config.scatter == "spmv" \
            and spmv is None:
        raise ValueError(
            "scatter='spmv' needs the fused-SpMV plan — build the "
            "DeviceSpMV via prepare_device_spmv (None means the "
            "graph's windows exceeded ops/pallas_pagerank caps, or "
            "the kernel-resident VMEM footprint blew "
            "SPMV_VMEM_BUDGET — the ~12M-vertex ceiling). Graphs "
            "past the resident ceiling belong on the out-of-core "
            "engine: --data-backend streamed (tpu_distalg/graphs/ "
            "streams edge blocks from disk; only O(V) state stays "
            "in HBM)"
        )

    if config.mode == "reference":
        def body(src, dst, w_e, emask, ranks, has_rank):
            active = emask * has_rank[src]
            c = gops.contribs(ranks, src, dst, w_e * active,
                              V, indices_sorted=True)
            received = gops.scatter_add(active, dst, V,
                                        indices_sorted=True)
            return tree_allreduce_sum((c, received))

        sweep_fn = data_parallel(
            body, mesh,
            in_specs=(P("data"),) * 4 + (P(), P()),
            out_specs=(P(), P()),
        )

        def run(src, dst, w_e, emask, has_out, n_ref,
                ranks0=None, has_rank0=None):
            # optional carry-in: the checkpointed driver resumes the
            # power iteration mid-schedule (iterations are
            # time-invariant, so segmenting the scan is bitwise-exact)
            if ranks0 is None:
                ranks0 = jnp.where(has_out > 0, 1.0 / n_ref, 0.0)  # :47
            if has_rank0 is None:
                has_rank0 = has_out

            def step(carry, _):
                ranks, has_rank = carry
                c, received = sweep_fn(src, dst, w_e, emask, ranks,
                                       has_rank)
                new_has = (received > 0).astype(jnp.float32)
                ranks = jnp.where(
                    received > 0, q / n_ref + (1 - q) * c, 0.0
                )  # :57
                return (ranks, new_has), None

            (ranks, has_rank), _ = jax.lax.scan(
                step, (ranks0, has_rank0), None,
                length=config.n_iterations,
            )
            return ranks, has_rank

        return jax.jit(run)

    if (config.mode == "standard"
            and config.scatter in ("auto", "spmv")
            and spmv is not None):
        # Path E: the fully-fused tiled SpMV — gather AND scatter in
        # one Pallas kernel, no XLA random-access op in the sweep.
        # 'auto' prefers it (measured 3.7x the hybrid sweep at 1Mx8M)
        from tpu_distalg.ops import pallas_pagerank as ppr

        interpret = next(iter(mesh.devices.flat)).platform != "tpu"
        rg, ws, r8, blk = spmv.rg, spmv.ws, spmv.r8, spmv.blk
        pad = (r8 + rg) * 128 - V

        def body(gb, sb, slane, srow, drow, dlane, we, ranks):
            rt = jnp.pad(ranks, (0, pad)).reshape(r8 + rg, 128)
            acc = ppr.spmv_table(gb, sb, rt, slane, srow, drow, dlane,
                                 we, rg=rg, ws=ws, r8=r8, blk=blk,
                                 interpret=interpret)
            return tree_allreduce_sum(acc)

        sweep_fn = data_parallel(
            body, mesh,
            in_specs=(P("data"), P("data"))
            + (P("data", None),) * 5 + (P(),),
            out_specs=P(),
        )

        def run(src, dst, w_e, emask, has_out, n_ref,
                ranks0=None, has_rank0=None):
            del src, dst, w_e, emask, n_ref, has_rank0  # plan-encoded
            if ranks0 is None:
                ranks0 = jnp.full((V,), 1.0 / V, dtype=jnp.float32)

            def step(ranks, _):
                acc = sweep_fn(spmv.gbase, spmv.sbase, spmv.src_lane,
                               spmv.src_row, spmv.dst_row,
                               spmv.dst_lane, spmv.w_e, ranks)
                c = acc[:r8].reshape(-1)[:V]
                if config.redistribute_dangling:
                    dangling = jnp.sum(ranks * (1.0 - has_out))
                    c = c + dangling / V
                ranks = q / V + (1 - q) * c
                return ranks, None

            ranks, _ = jax.lax.scan(
                step, ranks0, None, length=config.n_iterations
            )
            return ranks, jnp.ones((V,), dtype=jnp.float32)

        return jax.jit(run)

    if use_pallas:
        from tpu_distalg.ops import pallas_pagerank as ppr

        interpret = next(iter(mesh.devices.flat)).platform != "tpu"
        w, r8, blk = plan.w, plan.r8, plan.blk
        nch_local = plan.n_chunks // mesh.shape[DATA_AXIS]
        chunk = plan.row.shape[1]

        def body(src, w_e, base, row, lane, ranks):
            g = (ranks[src] * w_e).reshape(nch_local, chunk)
            acc = ppr.scatter_table(base, g, row, lane, w=w, r8=r8,
                                    blk=blk, interpret=interpret)
            return tree_allreduce_sum(acc)

        sweep_fn = data_parallel(
            body, mesh,
            in_specs=(P("data"), P("data"), P("data"),
                      P("data", None), P("data", None), P()),
            out_specs=P(),
        )

        def run(src, dst, w_e, emask, has_out, n_ref,
                ranks0=None, has_rank0=None):
            del dst, emask, n_ref, has_rank0  # plan encodes padded dst
            if ranks0 is None:
                ranks0 = jnp.full((V,), 1.0 / V, dtype=jnp.float32)

            def step(ranks, _):
                acc = sweep_fn(src, w_e, plan.base, plan.row,
                               plan.lane, ranks)
                c = acc[:r8].reshape(-1)[:V]
                if config.redistribute_dangling:
                    dangling = jnp.sum(ranks * (1.0 - has_out))
                    c = c + dangling / V
                ranks = q / V + (1 - q) * c
                return ranks, None

            ranks, _ = jax.lax.scan(
                step, ranks0, None, length=config.n_iterations
            )
            return ranks, jnp.ones((V,), dtype=jnp.float32)

        return jax.jit(run)

    # standard mode, XLA path: every vertex ranked, Σranks preserved;
    # one gather + one sorted scatter per iteration
    def body(src, dst, w_e, ranks):
        c = gops.contribs(ranks, src, dst, w_e, V, indices_sorted=True)
        return tree_allreduce_sum(c)

    sweep_fn = data_parallel(
        body, mesh,
        in_specs=(P("data"),) * 3 + (P(),),
        out_specs=P(),
    )

    def run(src, dst, w_e, emask, has_out, n_ref,
            ranks0=None, has_rank0=None):
        del emask, n_ref, has_rank0  # padding already carries 0 weight
        if ranks0 is None:
            ranks0 = jnp.full((V,), 1.0 / V, dtype=jnp.float32)

        def step(ranks, _):
            c = sweep_fn(src, dst, w_e, ranks)
            if config.redistribute_dangling:
                dangling = jnp.sum(ranks * (1.0 - has_out))
                c = c + dangling / V
            ranks = q / V + (1 - q) * c
            return ranks, None

        ranks, _ = jax.lax.scan(
            step, ranks0, None, length=config.n_iterations
        )
        return ranks, jnp.ones((V,), dtype=jnp.float32)

    return jax.jit(run)


def run(edges: np.ndarray, mesh: Mesh,
        config: PageRankConfig = PageRankConfig(),
        n_vertices: int | None = None, *,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 5) -> PageRankResult:
    el = gops.prepare_edges(edges, n_vertices)
    if config.mode == "standard" and config.scatter in ("auto", "spmv"):
        spmv = prepare_device_spmv(el, mesh)
    else:
        spmv = None
    de = prepare_device_edges(
        el, mesh,
        # the hybrid plan is only needed when it will actually run:
        # explicit 'pallas', or 'auto' falling back from a failed spmv
        build_plan=(config.mode == "standard"
                    and (config.scatter == "pallas"
                         or (config.scatter == "auto"
                             and spmv is None))),
        # when the spmv path will run, skip the dst-sort prep + edge
        # uploads it deletes anyway
        light=spmv is not None)
    de.spmv = spmv
    if checkpoint_dir is not None:
        return _run_segmented(de, mesh, config, checkpoint_dir,
                              checkpoint_every)
    fn = make_run_fn(mesh, config, de.n_vertices, de.plan, de.spmv)
    ranks, has_rank = fn(
        de.src, de.dst, de.w_e, de.emask, de.has_out, de.n_ref
    )
    return PageRankResult(ranks=ranks, has_rank=has_rank)


def _run_segmented(de: DeviceEdges, mesh: Mesh, config: PageRankConfig,
                   checkpoint_dir: str,
                   checkpoint_every: int) -> PageRankResult:
    """Checkpointed power iteration (state is the (V,) rank vector plus
    the reference mode's has_rank mask). Iterations are time-invariant,
    so resuming a saved carry is bitwise-identical to an uninterrupted
    scan — replacing the Spark task-retry the reference's
    10-join-deep lineage gets for free
    (``graph_computation/pagerank.py:52-57``)."""
    import dataclasses as dc

    from tpu_distalg.utils import checkpoint as ckpt

    V = de.n_vertices
    if config.mode == "reference":
        ranks0 = jnp.where(de.has_out > 0, 1.0 / de.n_ref, 0.0)
        has_rank0 = de.has_out
    else:
        ranks0 = jnp.full((V,), 1.0 / V, dtype=jnp.float32)
        has_rank0 = jnp.ones((V,), dtype=jnp.float32)

    def make_seg_fn(seg):
        return make_run_fn(mesh, dc.replace(config, n_iterations=seg),
                           V, de.plan, de.spmv)

    def run_seg(fn, state, t0):
        ranks, has_rank = fn(de.src, de.dst, de.w_e, de.emask,
                             de.has_out, de.n_ref,
                             state["ranks"], state["has_rank"])
        return ({"ranks": ranks, "has_rank": has_rank},
                np.asarray(jnp.sum(ranks), np.float32)[None])

    state, _, _ = ckpt.run_segmented(
        checkpoint_dir, checkpoint_every, config.n_iterations,
        make_seg_fn, run_seg,
        {"ranks": ranks0, "has_rank": has_rank0},
        # both modes carry the same (V,) f32 pair, so the shape check
        # alone cannot catch a cross-mode resume — encode the mode
        tag=f"pagerank_{config.mode}")
    return PageRankResult(ranks=jnp.asarray(state["ranks"]),
                          has_rank=jnp.asarray(state["has_rank"]))
