"""Shared local-update harness for the periodic-averaging optimizer family.

MA (``/root/reference/optimization/ma.py``), BMUF (``bmuf.py``) and EASGD
(``easgd.py``) share one machinery (SURVEY.md §2.1 rows 3-5): per-replica
local models take minibatch-SGD steps on their own shard, then a global
round combines them. The reference keeps per-replica models as a keyed RDD
joined against sampled points (``ma.py:99-102``) and runs one Spark job per
round; here each replica's local loop is a ``lax.scan`` *inside* a
``shard_map`` body — local steps never touch the interconnect, and only the
round-level combine is a collective, exactly mirroring the reference's
job-per-round boundary (SURVEY.md §3.2).

Semantics quirks reproduced behind flags (SURVEY.md §7 hard part #6):
  * the reference reuses the SAME minibatch for all 5 local steps of a round
    (seed ``42+t`` inside the local loop, ``ma.py:98-99``) — default;
    ``resample_per_local_step=True`` gives each local step a fresh draw;
  * BMUF's block-momentum ``delta_w`` is initialised *random*, not zero
    (``bmuf.py:95``) — ``random_delta_init`` flag;
  * EASGD does NOT resync local models to the center each round
    (``easgd.py:95-106`` has no resync line, unlike ``ma.py:96``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_distalg.ops import logistic, sampling
from tpu_distalg.parallel import (
    DATA_AXIS,
    data_parallel,
    parallelize,
    tree_allreduce_mean,
)
from tpu_distalg.utils import metrics, prng


@dataclasses.dataclass(frozen=True)
class LocalSGDConfig:
    """Knob names follow ``ma.py:19-23`` / ``bmuf.py:19-25`` /
    ``easgd.py:19-25``."""

    n_iterations: int = 300          # global rounds
    n_local_iterations: int = 5      # local steps per round
    eta: float = 0.1
    mini_batch_fraction: float = 0.1
    # round-level combine: 'average' (MA) | 'bmuf' | 'easgd'
    global_update: str = "average"
    resync: bool = True              # broadcast center to replicas each round
    elastic_alpha: float = 0.0       # EASGD α = η·ρ (easgd.py:24)
    mu: float = 0.9                  # BMUF momentum (bmuf.py:24)
    zeta: float = 0.1                # BMUF block learning rate (bmuf.py:25)
    beta: float | None = None        # EASGD center rate; None → n_replicas·α
    resample_per_local_step: bool = False
    random_delta_init: bool = True   # BMUF delta_w ~ U[-1,1) (bmuf.py:95)
    seed: int = 42
    init_seed: int = 7
    eval_test: bool = True
    # TPU perf knobs (not in the reference) — the flagship SSGD treatment
    # applied to the local-update family. 'bernoulli' = XLA mask over all
    # rows (reference sample() semantics); 'fused_gather' = the packed
    # traffic-proportional Pallas kernel: each replica's local step DMAs
    # only its sampled gather_block_rows-row blocks (same grad_sum
    # contract, block-cluster sampling — see ssgd.SSGDConfig.sampler);
    # 'fused_train' = 'fused_gather' with each round's n_local steps
    # fused into ONE megakernel launch per replica (weights in VMEM,
    # update + elastic pull in-kernel). Unlike SSGD's megakernel this
    # composes with dp>1 — local steps touch no interconnect; the
    # round-end pmean is unchanged.
    sampler: str = "bernoulli"
    x_dtype: str = "float32"
    fused_pack: int = 16
    gather_block_rows: int = 1024
    shuffle_seed: int | None = None
    # round-combine sync schedule (parallel/comms.py): 'dense' (bitwise
    # the pre-comms pmean — the default), 'bucketed', 'hier', 'bf16',
    # 'int8' (native int8 wire), 'topk[:frac]' (error-feedback
    # residuals in the scan state). bucketed/int8 run the
    # double-buffered bucket overlap pipeline by default ('@seq'
    # disables — bitwise-identical either way; a no-op for the
    # single-bucket topk/hier). The ONE collective of
    # this family is the round-end model average, so every sampler
    # (megakernel included) composes with it.
    comm: str = "dense"
    # synchronization discipline (parallel/ssp.py): 'bsp' (lock-step
    # round combine — bitwise the pre-SSP trainer, the default) or
    # 'ssp[:s[:decay]]': the combine runs once per s-round window,
    # replicas straggled by the seeded 'shard:straggle' plan skip
    # rounds instead of stalling the mesh, and the merge is a
    # STALENESS-WEIGHTED model average (weight decay**windows-stale)
    # feeding the usual MA/BMUF/EASGD center update. 'shard:leave'
    # plan rules drive elastic membership epochs. Composes with the
    # 'bernoulli' sampler; the fused kernels stay BSP.
    sync: str = "bsp"


@dataclasses.dataclass
class TrainResult:
    w: jax.Array
    ws: jax.Array  # final per-replica models (n_replicas, D)
    accs: jax.Array

    @property
    def final_acc(self) -> float:
        return float(self.accs[-1])


def _make_local_rounds(config: LocalSGDConfig, sync=None):
    """shard_map body: resync (maybe), run L local steps on the local
    shard, then pmean the round's model average across replicas — the
    ``treeAggregate``/n combine (``ma.py:104-106``) as ONE collective
    over the data axis, so the center update needs no gather.

    With ``sync`` (a ``comms.CommSync``) the round-end average runs the
    comm schedule instead of the raw pmean, and the body threads the
    flat error-feedback residual ``res`` + absolute round id ``t``."""

    def local_steps(X, y, masks, ws_local, w):
        # X (rows, D) local block; masks (L, rows); ws_local (1, D); w (D,)
        w_l = w if config.resync else ws_local[0]

        def local_step(w_l, mask):
            g_sum, cnt = logistic.grad_sum(X, y, w_l, mask)
            g_mean = g_sum / jnp.maximum(cnt, 1.0)  # update_local_w ma.py:39-43
            w_l = (
                w_l
                - config.eta * g_mean
                - config.elastic_alpha * (w_l - w)  # easgd.py:41-45
            )
            return w_l, None

        w_l, _ = jax.lax.scan(local_step, w_l, masks)
        return w_l

    if sync is None:
        def local_rounds(X, y, masks, ws_local, w):
            w_l = local_steps(X, y, masks, ws_local, w)
            return w_l[None, :], tree_allreduce_mean(w_l)

        return local_rounds

    def local_rounds_comm(X, y, masks, ws_local, w, t, res):
        w_l = local_steps(X, y, masks, ws_local, w)
        w_avg, res = sync.reduce_mean(w_l, res, t)
        return w_l[None, :], w_avg, res

    return local_rounds_comm


def _derive_beta(config: LocalSGDConfig, n_replicas: int) -> float:
    return (config.beta if config.beta is not None
            else n_replicas * config.elastic_alpha)  # easgd.py:25


def _comm_sync(mesh, config: LocalSGDConfig, d: int):
    """The round combine's CommSync: ONE (D,) leaf — the per-replica
    model being averaged (cf. ssgd's (grad, count) pair)."""
    import jax

    from tpu_distalg.parallel import comms

    return comms.make_sync(
        config.comm, mesh, jax.ShapeDtypeStruct((d,), jnp.float32))


def _make_combine(config: LocalSGDConfig, beta: float):
    """Round-level combine shared by the XLA and fused builders — the
    ONE place the MA/BMUF/EASGD center updates live, so the two sampler
    paths cannot drift apart. Returns ``(w, delta) = combine(w, w_avg,
    delta)``."""

    def combine(w, w_avg, delta):
        if config.global_update == "average":
            return w_avg, delta
        if config.global_update == "bmuf":
            delta = config.mu * delta + config.zeta * (w_avg - w)
            return w + delta, delta  # bmuf.py:113-114
        if config.global_update == "easgd":
            return (1 - beta) * w + beta * w_avg, delta  # easgd.py:106
        raise ValueError(config.global_update)

    return combine


def _check_sync_sampler(config: LocalSGDConfig) -> None:
    from tpu_distalg.parallel import ssp as pssp

    spec = pssp.SyncSpec.parse(config.sync)
    if spec.is_ssp and config.sampler != "bernoulli":
        raise ValueError(
            f"sync={config.sync!r} (stale-synchronous) composes with "
            f"the 'bernoulli' sampler — got sampler="
            f"{config.sampler!r}; the fused kernels stay BSP")


def make_ssp_train_fn(mesh: Mesh, config: LocalSGDConfig,
                      n_padded: int, d: int, *,
                      active: tuple[bool, ...], n_win_seg: int,
                      total_rounds: int):
    """SSP window scan for the local-update family: ``s`` ROUNDS of
    ``L`` local steps each between combines. A replica straggled by the
    seeded schedule skips the round (real interference compute runs
    instead); the window-end merge is a staleness-weighted MODEL
    average — every active replica's model enters with weight
    ``decay**windows_stale`` (0 = it worked this window and was free at
    the boundary) — feeding the usual MA/BMUF/EASGD center update. With
    ``resync``, replicas adopt the fresh center at the window start
    unless straggled there (a busy replica keeps its stale model — that
    IS the staleness being weighted).

    Call as ``fn(X, y, valid, X_test, y_test, w0, ws0, delta0,
    clocks0, stale0, res0, extra_seg, win0)``; returns ``(w, ws,
    delta, clocks, stale, res, win_accs, ages_max, ages_mean,
    gated)``."""
    import numpy as np

    from jax import lax

    from tpu_distalg.parallel import comms
    from tpu_distalg.parallel import ssp as pssp

    spec = pssp.SyncSpec.parse(config.sync)
    s = spec.staleness
    L = config.n_local_iterations
    n_replicas = mesh.shape[DATA_AXIS]
    beta = _derive_beta(config, n_replicas)
    sync = _comm_sync(mesh, config, d)
    combine = _make_combine(config, beta)
    key = prng.root_key(config.seed)
    active_np = np.asarray(active, bool)
    big = jnp.int32(1 << 30)

    def window_body(X, y, masks, w, ws_local, clocks, stale, res,
                    extra, roundv, winid):
        my = lax.axis_index(DATA_AXIS)
        act = jnp.asarray(active_np)
        act_me = act[my]
        w_l = ws_local[0]
        # resync adoption at the window start — a replica straggled at
        # the boundary keeps its old model (the staleness the merge
        # weights); EASGD never resyncs (easgd.py:95-106)
        if config.resync:
            adopt = act & (extra[0] == 0)
        else:
            adopt = jnp.zeros_like(act)
        w_l = jnp.where(adopt[my], w, w_l)
        max_c = jnp.max(jnp.where(act, clocks, -big))
        clocks_adj = jnp.where(adopt, max_c, clocks)
        min_known = jnp.min(jnp.where(act, clocks_adj, big))

        def one_round(carry, xs):
            w_l, my_clock, gated_ct = carry
            masks_r, extra_r, rv = xs
            # pad rounds pay no interference (cf. ssgd's tick body)
            eu = jnp.where(rv, extra_r[my], 0)
            gated = (my_clock - min_known) >= jnp.int32(s)
            do = rv & act_me & (eu == 0) & jnp.logical_not(gated)
            dummy = pssp.straggle_work(eu, 1.0)

            def local_step(w_i, mask):
                g_sum, cnt = logistic.grad_sum(X, y, w_i, mask)
                g_mean = g_sum / jnp.maximum(cnt, 1.0)
                return (w_i - config.eta * g_mean
                        - config.elastic_alpha * (w_i - w)), None

            w_new, _ = jax.lax.scan(local_step, w_l, masks_r)
            w_l = pssp.entangle(
                jnp.where(do, w_new, w_l), dummy)
            my_clock = my_clock + do.astype(clocks.dtype)
            gated_ct = gated_ct + (rv & act_me & gated).astype(
                jnp.int32)
            return (w_l, my_clock, gated_ct), None

        (w_l, my_clock, my_gated), _ = lax.scan(
            one_round, (w_l, clocks_adj[my], jnp.int32(0)),
            (masks, extra, roundv))

        clocks_new = comms.psum(
            jnp.zeros_like(clocks).at[my].set(my_clock))
        gated = comms.psum(my_gated)
        stepped = clocks_new > clocks_adj
        fresh = act & stepped & jnp.logical_not(extra[-1] > 0)
        stale_new = jnp.where(fresh, 0, stale + 1)
        wts = pssp.staleness_weights(
            stale_new, act, act, spec.decay)
        wsum = jnp.sum(wts)
        contrib = wts[my] * w_l
        (contrib,), res_new = sync.reduce((contrib,), res, winid)
        w_avg = contrib / jnp.maximum(wsum, jnp.float32(1e-12))
        ages_obs = jnp.where(act, stale_new, 0)
        n_act = jnp.sum(act.astype(jnp.float32))
        ages_max = jnp.max(ages_obs).astype(jnp.float32)
        ages_mean = (jnp.sum(ages_obs.astype(jnp.float32))
                     / jnp.maximum(n_act, 1.0))
        return (w_l[None], w_avg, clocks_new, stale_new, res_new,
                ages_max, ages_mean, gated)

    window_fn = data_parallel(
        window_body, mesh,
        in_specs=(
            P("data", None),        # X rows
            P("data"),              # y
            P(None, None, "data"),  # masks (s, L, rows)
            P(),                    # center w
            P("data", None),        # per-replica models (R, D)
            P(), P(),               # clocks, stale (replicated)
            P("data", None),        # error-feedback residual
            P(), P(), P(),          # extra (s, S), round validity, win
        ),
        out_specs=(P("data", None), P(), P(), P(), P("data", None),
                   P(), P(), P()),
    )

    def round_masks(valid, t):
        if config.resample_per_local_step:
            draws = [
                sampling.bernoulli_mask(
                    key, t * L + li, n_padded,
                    config.mini_batch_fraction, valid)
                for li in range(L)
            ]
            return jnp.stack(draws)
        mask = sampling.bernoulli_mask(
            key, t, n_padded, config.mini_batch_fraction, valid)
        return jnp.broadcast_to(mask, (L, n_padded))

    def train(X, y, valid, X_test, y_test, w0, ws0, delta0, clocks0,
              stale0, res0, extra_seg, win0):
        def win_step(carry, xs):
            w, ws, delta, clocks, stale, res = carry
            i, extra_w = xs
            winid = (win0 + i).astype(jnp.int32)
            ts = winid * s + jnp.arange(s)
            masks = jax.vmap(lambda t: round_masks(valid, t))(ts)
            roundv = ts < total_rounds
            (ws, w_avg, clocks, stale, res, amax, amean,
             gated) = window_fn(X, y, masks, w, ws, clocks, stale,
                                res, extra_w, roundv, winid)
            w, delta = combine(w, w_avg, delta)
            acc = (metrics.binary_accuracy(X_test @ w, y_test)
                   if config.eval_test else jnp.float32(0))
            return ((w, ws, delta, clocks, stale, res),
                    (acc, amax, amean, gated))

        carry, (accs, amax, amean, gated) = jax.lax.scan(
            win_step, (w0, ws0, delta0, clocks0, stale0, res0),
            (jnp.arange(n_win_seg), extra_seg))
        return (*carry, accs, amax, amean, gated)

    return jax.jit(train)


def _train_ssp(
    X_train, y_train, X_test, y_test, mesh: Mesh,
    config: LocalSGDConfig,
    *,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 100,
) -> TrainResult:
    """SSP driver for the local-update family — the ssgd driver's
    shape over (w, ws, delta, clocks, stale, res) state, elastic via
    :func:`membership.run_elastic` (a resume at a different shard
    count re-derives per-replica state from the replicated center)."""
    import numpy as np

    from tpu_distalg.models.ssgd import window_accs_to_ticks
    from tpu_distalg.parallel import comms, membership, partition
    from tpu_distalg.parallel import ssp as pssp

    spec = pssp.SyncSpec.parse(config.sync)
    s = spec.staleness
    T = config.n_iterations
    D = X_train.shape[1]
    n_shards = int(mesh.shape[DATA_AXIS])
    Xs = parallelize(X_train, mesh, dtype=jnp.dtype(config.x_dtype))
    ys = parallelize(y_train, mesh)
    X_te, y_te = jnp.asarray(X_test), jnp.asarray(y_test)
    k_init = prng.root_key(config.init_seed)
    w0 = np.asarray(logistic.init_weights(
        jax.random.fold_in(k_init, 0), D), np.float32)
    ws0 = np.asarray(jax.random.uniform(
        jax.random.fold_in(k_init, 1), (n_shards, D),
        minval=-1.0, maxval=1.0), np.float32)
    if config.global_update == "bmuf" and config.random_delta_init:
        delta0 = np.asarray(jax.random.uniform(
            jax.random.fold_in(k_init, 2), (D,),
            minval=-1.0, maxval=1.0), np.float32)
    else:
        delta0 = np.zeros((D,), np.float32)
    n_win, padded = pssp.window_grid(T, s)
    extra = pssp.compile_straggle_schedule(padded, n_shards)
    extra[T:] = 0  # pad rounds don't exist: no interference, no busy
    extra = extra.reshape(n_win, s, n_shards)
    sync = _comm_sync(mesh, config, D)

    def fresh_shard_state(w_host):
        """Per-replica state derived from the replicated center — the
        renegotiation story: a rejoining replica starts at the center,
        residuals are re-zeroed (flushed into the last merge)."""
        w_host = np.asarray(w_host, np.float32)
        return (np.tile(w_host, (n_shards, 1)),
                np.asarray(sync.init_state()))

    def renegotiate(saved_leaves, saved_shards, start_win):
        del saved_shards, start_win
        w = np.asarray(saved_leaves[0], np.float32)
        ws_new, res_new = fresh_shard_state(w)
        return (w, ws_new,
                np.asarray(saved_leaves[2], np.float32),   # delta
                np.asarray(membership.redistribute_clocks(
                    saved_leaves[3], n_shards), np.int32),
                np.zeros((n_shards,), np.int32),           # stale
                res_new)

    def make_seg_fn(active, n_win_seg):
        return make_ssp_train_fn(
            mesh, config, Xs.n_padded, D, active=active,
            n_win_seg=n_win_seg, total_rounds=T)

    def on_epoch(state, prev, cur):
        """A shard re-entering the active set is CURRENT, not a
        straggler: its clock froze while it was away (history, not
        staleness), and for EASGD (resync=False) no in-program adopt
        exists to bump it — left alone, the frozen clock would become
        min_known and the gate would serialize the whole mesh onto the
        rejoiner. Its model's genuine staleness is still carried (and
        merge-weighted) by `stale`, which only resets once it does
        fresh work."""
        w, ws, delta, clocks, stale, res = state
        clocks = np.asarray(clocks, np.int32).copy()
        rejoined = [k for k in range(n_shards)
                    if cur.active[k] and not prev.active[k]]
        if rejoined:
            cont = [k for k in range(n_shards)
                    if cur.active[k] and prev.active[k]]
            top = int(clocks[cont].max()) if cont \
                else int(clocks.max())
            clocks[rejoined] = top
        return (w, ws, delta, clocks, stale, res)

    def run_seg(fn, state, win0, n_win_seg, epoch):
        del epoch
        # idempotent rule-table placement: device-resident state in
        # the table layout passes through untouched (the old
        # np.asarray + device_put spelling paid a host round trip
        # every segment); restored/renegotiated host leaves take one
        # H2D direct to their final layout
        st = partition.ensure(
            {"w": state[0] if isinstance(state[0], jax.Array)
             else np.asarray(state[0], np.float32),
             "ws": state[1],
             "delta": state[2] if isinstance(state[2], jax.Array)
             else np.asarray(state[2], np.float32),
             "clocks": state[3], "stale": state[4], "res": state[5]},
            "local_sgd", mesh)
        out = fn(Xs.data, ys.data, Xs.mask, X_te, y_te,
                 st["w"], st["ws"], st["delta"], st["clocks"],
                 st["stale"], st["res"],
                 jnp.asarray(extra[win0:win0 + n_win_seg]),
                 jnp.int32(win0))
        return out[:6], out[6:]

    # state layout: (w, ws, delta, clocks, stale, res)
    state0 = (w0, ws0, delta0, np.zeros((n_shards,), np.int32),
              np.zeros((n_shards,), np.int32),
              np.asarray(sync.init_state()))

    state, outs, start, epochs = membership.run_elastic(
        checkpoint_dir, max(1, checkpoint_every // s), n_win,
        n_shards, make_seg_fn=make_seg_fn, run_seg=run_seg,
        state0=state0, renegotiate=renegotiate, on_epoch=on_epoch,
        # spec.spec() in the tag: window indexing and merge weights
        # depend on (s, decay) — a different --sync must reject, not
        # silently reinterpret the saved window progress
        tag=(f"local_sgd:{spec.spec()}:{config.global_update}"
             f":comm={config.comm}"),
        ticks_per_window=s)

    w = jnp.asarray(np.asarray(state[0], np.float32))
    ws = jnp.asarray(np.asarray(state[1], np.float32))
    metrics.guard_finite((w, ws), "local-SGD (ssp) models")
    accs = window_accs_to_ticks(outs[0], s, T) if outs \
        else np.zeros((T,), np.float32)
    stats = pssp.observed_staleness(
        outs[1] if outs else [], outs[2] if outs else [])
    pssp.emit_ssp_counters(
        spec, stats,
        straggle_ticks=int(np.count_nonzero(extra)),
        gated_ticks=int(np.asarray(outs[3]).sum()) if outs else 0,
        epochs=len(epochs))
    comms.emit_sync_counters(sync, n_win - start)
    return TrainResult(w=w, ws=ws, accs=jnp.asarray(accs))


def make_train_fn(mesh: Mesh, config: LocalSGDConfig, n_padded: int,
                  *, d: int | None = None):
    """Build the jitted round scan. With ``config.comm != 'dense'``
    pass ``d`` (model width); the returned fn is then called as
    ``fn(X, y, valid, X_test, y_test, w0, ws0, delta0, res0, t0=0)`` →
    ``(w, ws, delta, res, accs)``."""
    n_replicas = mesh.shape[DATA_AXIS]
    beta = _derive_beta(config, n_replicas)
    L = config.n_local_iterations
    key = prng.root_key(config.seed)

    sync = None
    if config.comm != "dense":
        if d is None:
            raise ValueError(
                f"comm={config.comm!r} needs the model width: call "
                "make_train_fn(mesh, config, n_padded, d=D) "
                "(local_sgd.train does this for you)"
            )
        sync = _comm_sync(mesh, config, d)
        local_fn = data_parallel(
            _make_local_rounds(config, sync),
            mesh,
            in_specs=(
                P("data", None),   # X rows
                P("data"),         # y
                P(None, "data"),   # masks (L, rows)
                P("data", None),   # per-replica models (R, D)
                P(),               # center w
                P(),               # absolute round id
                P("data", None),   # error-feedback residual (R, E)
            ),
            out_specs=(P("data", None), P(), P("data", None)),
        )
    else:
        local_fn = data_parallel(
            _make_local_rounds(config),
            mesh,
            in_specs=(
                P("data", None),   # X rows
                P("data"),         # y
                P(None, "data"),   # masks (L, rows)
                P("data", None),   # per-replica models (R, D) → (1, D) local
                P(),               # center w
            ),
            out_specs=(P("data", None), P()),
        )

    def round_masks(valid, t):
        if config.resample_per_local_step:
            draws = [
                sampling.bernoulli_mask(
                    key, t * L + l, n_padded,
                    config.mini_batch_fraction, valid,
                )
                for l in range(L)
            ]
            return jnp.stack(draws)
        # reference parity: one draw per round, reused by every local step
        # (sample(False, frac, 42+t) inside the local loop, ma.py:98-99)
        mask = sampling.bernoulli_mask(
            key, t, n_padded, config.mini_batch_fraction, valid
        )
        return jnp.broadcast_to(mask, (L, n_padded))

    combine = _make_combine(config, beta)

    if sync is not None:
        def train(X, y, valid, X_test, y_test, w0, ws0, delta0, res0,
                  t0=0):
            def round_step(carry, t):
                w, ws, delta, res = carry
                masks = round_masks(valid, t)
                ws, w_avg, res = local_fn(X, y, masks, ws, w, t, res)
                w, delta = combine(w, w_avg, delta)
                acc = (
                    metrics.binary_accuracy(X_test @ w, y_test)
                    if config.eval_test
                    else jnp.float32(0)
                )
                return (w, ws, delta, res), acc

            (w, ws, delta, res), accs = jax.lax.scan(
                round_step, (w0, ws0, delta0, res0),
                jnp.arange(config.n_iterations) + t0,
            )
            return w, ws, delta, res, accs

        return jax.jit(train)

    def train(X, y, valid, X_test, y_test, w0, ws0, delta0, t0=0):
        def round_step(carry, t):
            w, ws, delta = carry
            masks = round_masks(valid, t)
            ws, w_avg = local_fn(X, y, masks, ws, w)
            w, delta = combine(w, w_avg, delta)
            acc = (
                metrics.binary_accuracy(X_test @ w, y_test)
                if config.eval_test
                else jnp.float32(0)
            )
            return (w, ws, delta), acc

        # absolute round ids (t0 offset): segmented checkpoint/resume
        # draws identical minibatch masks to a straight-through run
        (w, ws, delta), accs = jax.lax.scan(
            round_step, (w0, ws0, delta0),
            jnp.arange(config.n_iterations) + t0,
        )
        return w, ws, delta, accs

    return jax.jit(train)


def make_train_fn_fused(mesh: Mesh, config: LocalSGDConfig, meta: dict):
    """Fused-kernel local rounds: every replica's local step runs the
    traffic-proportional gathered Pallas kernel on ITS OWN packed shard
    (``pallas_kernels.fused_grad_sum_gathered`` — the same one-HBM-pass
    kernel as SSGD's flagship sampler; the local step's ``grad_sum``
    contract is identical, only the combine differs). Local steps touch
    no interconnect; the round-end pmean is the only collective —
    exactly the reference's job-per-round boundary (``ma.py:98-106``).

    All (round, local-step, shard) block draws happen in one batched
    threefry before the scan; the id array is sharded over the data axis
    so each replica carries only its own draw column.
    """
    import functools

    from tpu_distalg.models.ssgd import fused_gather_geometry
    from tpu_distalg.ops import pallas_kernels

    on_tpu = next(iter(mesh.devices.flat)).platform == "tpu"
    d_t = meta["d_total"]
    col_keep = (jnp.arange(d_t) < meta["y_col"]).astype(jnp.float32)
    n_shards = mesh.shape[DATA_AXIS]
    n_blocks, n_sampled = fused_gather_geometry(config, meta, n_shards)
    L = config.n_local_iterations
    beta = _derive_beta(config, n_replicas=n_shards)
    key = prng.root_key(config.seed)
    sync = (_comm_sync(mesh, config, d_t)
            if config.comm != "dense" else None)
    kern = functools.partial(
        pallas_kernels.fused_grad_sum_gathered,
        pack=meta["pack"], d_total=d_t, y_col=meta["y_col"],
        v_col=meta["v_col"],
        gather_block_rows=config.gather_block_rows,
        interpret=not on_tpu,
    )

    def prep_idx(ts):
        """(T, L, S, ns) sampled block ids via the shared
        without-replacement draw (``sampling.sample_block_ids``), keyed
        on (absolute round id, local-step index, shard); without
        resampling the one per-round draw is broadcast over L (reference
        parity: the same minibatch serves every local step of a round,
        ``ma.py:98-99``)."""
        from tpu_distalg.ops import sampling

        n_draws = L if config.resample_per_local_step else 1

        def draw_round(t):
            return jax.vmap(
                lambda l: sampling.sample_block_ids(
                    jax.random.fold_in(jax.random.fold_in(key, t), l),
                    n_shards, n_blocks, n_sampled,
                )
            )(jnp.arange(n_draws))

        idx = jax.vmap(draw_round)(ts)
        return jnp.broadcast_to(
            idx, (ts.shape[0], L, n_shards, n_sampled))

    if config.sampler == "fused_train":
        mega_kern = functools.partial(
            pallas_kernels.fused_train_gathered,
            pack=meta["pack"], d_total=d_t, y_col=meta["y_col"],
            v_col=meta["v_col"],
            gather_block_rows=config.gather_block_rows,
            eta=config.eta, alpha=config.elastic_alpha,
            interpret=not on_tpu,
        )

        def _local_models(X2, idx_round, ws_local, w):
            # X2 (n2_local, P·D); idx_round (L, 1, ns) — this shard's
            # draws. The whole L-step local loop is ONE megakernel
            # launch: weights live in VMEM, the SGD update and the
            # elastic pull run in-kernel (fused_train_gathered); the
            # center is fixed for the round, exactly easgd.py:41-45 /
            # ma.py:98-102 semantics
            w_l = w if config.resync else ws_local[0]
            pk = meta["pack"]
            wt = mega_kern(
                X2, jnp.tile(w_l, (pk,))[:, None], idx_round[:, 0, :],
                center_tile=jnp.tile(w, (pk,))[:, None],
            )
            return wt[:d_t, 0]
    else:
        def _local_models(X2, idx_round, ws_local, w):
            # X2 (n2_local, P·D); idx_round (L, 1, ns) — this shard's
            # draws
            w_l = w if config.resync else ws_local[0]

            def local_step(w_l, idx_l):
                g, cnt = kern(X2, w_l, idx_l[0])
                g_mean = (g * col_keep) / jnp.maximum(cnt, 1.0)
                w_l = (
                    w_l
                    - config.eta * g_mean
                    - config.elastic_alpha * (w_l - w)  # easgd.py:41-45
                )
                return w_l, None

            w_l, _ = jax.lax.scan(local_step, w_l, idx_round)
            return w_l

    if sync is not None:
        def local_rounds(X2, idx_round, ws_local, w, t, res):
            w_l = _local_models(X2, idx_round, ws_local, w)
            # the one collective of this family: the round-end average,
            # under the comm schedule with the residual threaded
            w_avg, res = sync.reduce_mean(w_l, res, t)
            return w_l[None, :], w_avg, res

        local_fn = data_parallel(
            local_rounds, mesh,
            in_specs=(
                P("data", None),          # packed rows
                P(None, "data", None),    # (L, S, ns) draws → (L, 1, ns)
                P("data", None),          # per-replica models
                P(),                      # center w
                P(),                      # absolute round id
                P("data", None),          # error-feedback residual
            ),
            out_specs=(P("data", None), P(), P("data", None)),
        )
    else:
        def local_rounds(X2, idx_round, ws_local, w):
            w_l = _local_models(X2, idx_round, ws_local, w)
            return w_l[None, :], tree_allreduce_mean(w_l)

        local_fn = data_parallel(
            local_rounds, mesh,
            in_specs=(
                P("data", None),          # packed rows
                P(None, "data", None),    # (L, S, ns) draws → (L, 1, ns)
                P("data", None),          # per-replica models
                P(),                      # center w
            ),
            out_specs=(P("data", None), P()),
        )

    combine = _make_combine(config, beta)

    if sync is not None:
        def train(X2, X_test, y_test, w0, ws0, delta0, res0, t0=0):
            ts = jnp.arange(config.n_iterations) + t0
            idx_all = prep_idx(ts)                # (T, L, S, ns)

            def round_step(carry, x):
                t, idx_round = x
                w, ws, delta, res = carry
                ws, w_avg, res = local_fn(X2, idx_round, ws, w, t, res)
                w, delta = combine(w, w_avg, delta)
                acc = (
                    metrics.binary_accuracy(X_test @ w, y_test)
                    if config.eval_test
                    else jnp.float32(0)
                )
                return (w, ws, delta, res), acc

            (w, ws, delta, res), accs = jax.lax.scan(
                round_step, (w0, ws0, delta0, res0), (ts, idx_all)
            )
            return w, ws, delta, res, accs

        return jax.jit(train)

    def train(X2, X_test, y_test, w0, ws0, delta0, t0=0):
        ts = jnp.arange(config.n_iterations) + t0
        idx_all = prep_idx(ts)                    # (T, L, S, ns)

        def round_step(carry, idx_round):
            w, ws, delta = carry
            ws, w_avg = local_fn(X2, idx_round, ws, w)
            w, delta = combine(w, w_avg, delta)
            acc = (
                metrics.binary_accuracy(X_test @ w, y_test)
                if config.eval_test
                else jnp.float32(0)
            )
            return (w, ws, delta), acc

        (w, ws, delta), accs = jax.lax.scan(
            round_step, (w0, ws0, delta0), idx_all
        )
        return w, ws, delta, accs

    return jax.jit(train)


def prepare_fused(X_train, y_train, mesh: Mesh, config: LocalSGDConfig):
    """One-time setup for the fused sampler (mirrors
    ``ssgd.prepare_fused``): pack (X, y, validity) into the kernel
    layout, shard over the data axis, build augmented initial state and
    the jitted round scan. Returns ``(fn, X2, w0, ws0, delta0, meta)``;
    call as ``fn(X2, X_test_padded, y_test, w0, ws0, delta0)``."""
    import numpy as np

    from tpu_distalg.ops import pallas_kernels
    from tpu_distalg.parallel import partition

    n_shards = mesh.shape[DATA_AXIS]
    D = X_train.shape[1]
    n = X_train.shape[0]
    X2, meta = pallas_kernels.pack_augmented(
        np.asarray(X_train), np.asarray(y_train), np.ones(n, np.float32),
        dtype=jnp.dtype(config.x_dtype),
        pack=config.fused_pack,
        block_rows=config.gather_block_rows * n_shards,
        shuffle_seed=config.shuffle_seed,
    )
    X2 = partition.put(X2, "X2", "local_sgd", mesh)
    d_t = meta["d_total"]
    n_replicas = n_shards
    k_init = prng.root_key(config.init_seed)
    w0 = jnp.zeros((d_t,), jnp.float32).at[:D].set(
        logistic.init_weights(jax.random.fold_in(k_init, 0), D)
    )
    # per-replica init ~ U[-1,1) in the true columns (ma.py:86); the
    # y/v/pad columns stay zero forever (zeroed grad, zero elastic pull)
    ws0 = jnp.zeros((n_replicas, d_t), jnp.float32).at[:, :D].set(
        jax.random.uniform(
            jax.random.fold_in(k_init, 1), (n_replicas, D),
            minval=-1.0, maxval=1.0,
        )
    )
    if config.global_update == "bmuf" and config.random_delta_init:
        delta0 = jnp.zeros((d_t,), jnp.float32).at[:D].set(
            jax.random.uniform(
                jax.random.fold_in(k_init, 2), (D,),
                minval=-1.0, maxval=1.0,
            )
        )
    else:
        delta0 = jnp.zeros((d_t,))
    fn = make_train_fn_fused(mesh, config, meta)
    return fn, X2, w0, ws0, delta0, meta


def _train_comm(mesh, config: LocalSGDConfig, d, data_args, w0, ws0,
                delta0, *, make_fn, checkpoint_dir, checkpoint_every,
                tag, crop, fn=None):
    """Comm-schedule round driver shared by the XLA and fused paths:
    the carry/checkpoint state is ``(w, ws, delta, residual)`` — the
    error-feedback residual is per-replica like ``ws`` and persists
    across segments for bitwise resume."""
    from tpu_distalg.parallel import comms, partition
    from tpu_distalg.utils import metrics as _metrics

    sync = _comm_sync(mesh, config, d)
    res0 = partition.put(sync.init_state(), "res", "local_sgd", mesh)

    if checkpoint_dir is None:
        fn = fn if fn is not None else make_fn(config.n_iterations)
        w, ws, _, _, accs = fn(*data_args, w0, ws0, delta0, res0)
        comms.emit_sync_counters(sync, config.n_iterations)
        _metrics.guard_finite((w, ws), "local-SGD models")
        return TrainResult(w=w[:crop], ws=ws[:, :crop], accs=accs)

    from tpu_distalg.utils import checkpoint as ckpt

    def run_seg(seg_fn, state, t0):
        w, ws, delta, res = state
        ws = partition.put(ws, "ws", "local_sgd", mesh)
        res = partition.put(res, "res", "local_sgd", mesh)
        w, ws, delta, res, accs = seg_fn(
            *data_args, jnp.asarray(w), ws, jnp.asarray(delta), res,
            t0=t0)
        return (w, ws, delta, res), accs

    (w, ws, delta, res), accs, start = ckpt.run_segmented(
        checkpoint_dir, checkpoint_every, config.n_iterations,
        make_seg_fn=make_fn, run_seg=run_seg,
        state0=(w0, ws0, delta0, res0),
        tag=f"{tag}:comm={config.comm}",
    )
    # only the rounds THIS process ran (resume skips the rest)
    comms.emit_sync_counters(sync, config.n_iterations - start)
    return TrainResult(
        w=jnp.asarray(w)[:crop], ws=jnp.asarray(ws)[:, :crop],
        accs=jnp.asarray(accs),
    )


def _train_fused(
    X_train, y_train, X_test, y_test, mesh: Mesh,
    config: LocalSGDConfig,
    *,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 100,
) -> TrainResult:
    import numpy as np

    D = X_train.shape[1]
    fn, X2, w0, ws0, delta0, meta = prepare_fused(
        X_train, y_train, mesh, config)
    X_te = jnp.asarray(
        np.pad(np.asarray(X_test, np.float32),
               ((0, 0), (0, meta["d_total"] - D)))
    )
    y_te = jnp.asarray(y_test)

    if config.comm != "dense":
        return _train_comm(
            mesh, config, meta["d_total"], (X2, X_te, y_te),
            w0, ws0, delta0,
            make_fn=lambda seg: make_train_fn_fused(
                mesh, dataclasses.replace(config, n_iterations=seg),
                meta),
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            tag=f"local_sgd:{config.global_update}:{config.sampler}",
            crop=D, fn=fn,
        )

    if checkpoint_dir is None:
        w, ws, _, accs = fn(X2, X_te, y_te, w0, ws0, delta0)
        metrics.guard_finite((w, ws), "local-SGD (fused) models")
        return TrainResult(w=w[:D], ws=ws[:, :D], accs=accs)

    from tpu_distalg.parallel import partition
    from tpu_distalg.utils import checkpoint as ckpt

    def run_seg(seg_fn, state, t0):
        w, ws, delta = state
        ws = partition.put(ws, "ws", "local_sgd", mesh)
        w, ws, delta, accs = seg_fn(
            X2, X_te, y_te, jnp.asarray(w), ws, jnp.asarray(delta),
            t0=t0,
        )
        return (w, ws, delta), accs

    (w, ws, delta), accs, _ = ckpt.run_segmented(
        checkpoint_dir, checkpoint_every, config.n_iterations,
        make_seg_fn=lambda seg: make_train_fn_fused(
            mesh, dataclasses.replace(config, n_iterations=seg), meta),
        run_seg=run_seg,
        state0=(w0, ws0, delta0),
        tag=f"local_sgd:{config.global_update}:{config.sampler}",
    )
    return TrainResult(
        w=jnp.asarray(w)[:D], ws=jnp.asarray(ws)[:, :D],
        accs=jnp.asarray(accs),
    )


def train(
    X_train, y_train, X_test, y_test, mesh: Mesh,
    config: LocalSGDConfig = LocalSGDConfig(),
    *,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 100,
) -> TrainResult:
    """End-to-end local-update training; optionally checkpointed.

    With ``checkpoint_dir``, rounds run in compiled segments and the
    full carry ``(w, ws, delta)`` — center model, per-replica models and
    the BMUF momentum — is saved after each (same machinery as SSGD,
    ``utils.checkpoint.run_segmented``); segmented and straight-through
    runs are bitwise-identical because round PRNG keys use absolute
    round ids.
    """
    from tpu_distalg.telemetry import events as tevents

    # progress mark: the heartbeat names this phase if a round wedges
    # (checkpointed runs also mark per segment inside run_segmented)
    tevents.mark(f"local_sgd:{config.global_update}", emit_event=False)
    _check_sync_sampler(config)
    from tpu_distalg.parallel import ssp as _pssp

    if _pssp.SyncSpec.parse(config.sync).is_ssp:
        return _train_ssp(
            X_train, y_train, X_test, y_test, mesh, config,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every)
    if config.sampler in ("fused_gather", "fused_train"):
        return _train_fused(
            X_train, y_train, X_test, y_test, mesh, config,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
        )
    if config.sampler != "bernoulli":
        raise ValueError(f"unknown sampler {config.sampler!r}")
    Xs = parallelize(X_train, mesh, dtype=jnp.dtype(config.x_dtype))
    ys = parallelize(y_train, mesh)
    D = X_train.shape[1]
    n_replicas = mesh.shape[DATA_AXIS]
    k_init = prng.root_key(config.init_seed)
    w0 = logistic.init_weights(jax.random.fold_in(k_init, 0), D)
    # per-replica init ~ U[-1,1): ma.py:86 parallelize(2*ranf((n_slices,D+1))-1)
    ws0 = jax.random.uniform(
        jax.random.fold_in(k_init, 1), (n_replicas, D), minval=-1.0, maxval=1.0
    )
    if config.global_update == "bmuf" and config.random_delta_init:
        delta0 = jax.random.uniform(
            jax.random.fold_in(k_init, 2), (D,), minval=-1.0, maxval=1.0
        )
    else:
        delta0 = jnp.zeros((D,))
    X_te, y_te = jnp.asarray(X_test), jnp.asarray(y_test)

    if config.comm != "dense":
        return _train_comm(
            mesh, config, D,
            (Xs.data, ys.data, Xs.mask, X_te, y_te), w0, ws0, delta0,
            make_fn=lambda seg: make_train_fn(
                mesh, dataclasses.replace(config, n_iterations=seg),
                Xs.n_padded, d=D),
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            tag=f"local_sgd:{config.global_update}",
            crop=D,
        )

    if checkpoint_dir is None:
        fn = make_train_fn(mesh, config, Xs.n_padded)
        w, ws, _, accs = fn(
            Xs.data, ys.data, Xs.mask, X_te, y_te, w0, ws0, delta0,
        )
        metrics.guard_finite((w, ws), "local-SGD models")
        return TrainResult(w=w, ws=ws, accs=accs)

    from tpu_distalg.parallel import partition
    from tpu_distalg.utils import checkpoint as ckpt

    def run_seg(fn, state, t0):
        w, ws, delta = state
        # restored per-replica models arrive as host arrays — the
        # rule table re-shards them (one H2D direct to final layout)
        ws = partition.put(ws, "ws", "local_sgd", mesh)
        w, ws, delta, accs = fn(
            Xs.data, ys.data, Xs.mask, X_te, y_te,
            jnp.asarray(w), ws, jnp.asarray(delta), t0=t0,
        )
        return (w, ws, delta), accs

    (w, ws, delta), accs, _ = ckpt.run_segmented(
        checkpoint_dir, checkpoint_every, config.n_iterations,
        make_seg_fn=lambda seg: make_train_fn(
            mesh, dataclasses.replace(config, n_iterations=seg),
            Xs.n_padded),
        run_seg=run_seg,
        state0=(w0, ws0, delta0),
        tag=f"local_sgd:{config.global_update}",
    )
    return TrainResult(
        w=jnp.asarray(w), ws=jnp.asarray(ws), accs=jnp.asarray(accs)
    )
