"""Full-batch distributed logistic regression.

Re-design of ``/root/reference/machine_learning/logistic_regression.py``:
the 1500-iteration driver loop that launched one Spark job per step
(broadcast w → map gradient → treeAggregate → driver update, ``:75-92``)
becomes a single ``lax.scan`` compiled once — model state never leaves HBM.
Update rule is the reference's (unaveraged!) ``w -= η · Σ grad`` (``:84``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_distalg.ops import logistic
from tpu_distalg.parallel import data_parallel, parallelize, tree_allreduce_sum
from tpu_distalg.utils import metrics, prng


@dataclasses.dataclass(frozen=True)
class LRConfig:
    """Knob names follow ``logistic_regression.py:17-19``."""

    n_iterations: int = 1500
    eta: float = 0.1
    seed: int = 42
    init_seed: int = 7
    # gradient-sync schedule (parallel/comms.py): 'dense' (bitwise the
    # pre-comms psum), 'bucketed', 'hier', 'bf16', 'int8' (native int8
    # wire), 'topk[:frac]' (error-feedback residuals in the scan
    # state); bucketed/int8 overlap their bucket exchange by
    # default ('@seq' for the bitwise-identical sequential reference)
    comm: str = "dense"


@dataclasses.dataclass
class TrainResult:
    w: jax.Array
    accs: jax.Array  # per-iteration test accuracy

    @property
    def final_acc(self) -> float:
        return float(self.accs[-1])


def _local_grad(X, y, mask, w):
    """shard_map body: local masked gradient sum + one AllReduce."""
    g, cnt = logistic.grad_sum(X, y, w, mask)
    return tree_allreduce_sum((g, cnt))


def _comm_sync(mesh, config: LRConfig, d: int):
    from tpu_distalg.parallel import comms

    example = (jax.ShapeDtypeStruct((d,), jnp.float32),
               jax.ShapeDtypeStruct((), jnp.float32))
    return comms.make_sync(config.comm, mesh, example)


def make_train_fn(mesh: Mesh, config: LRConfig, *, d: int | None = None):
    """Build the jitted whole-training function (scan over iterations).

    With ``config.comm != 'dense'`` pass ``d`` (feature width); the
    returned fn is then ``fn(X, y, valid, X_test, y_test, w0, res0,
    t0=0)`` → ``(w, accs, res)`` with the comm residual threaded."""
    if config.comm != "dense":
        if d is None:
            raise ValueError(
                f"comm={config.comm!r} needs the feature width: call "
                "make_train_fn(mesh, config, d=X.shape[1]) "
                "(lr.train does this for you)")
        sync = _comm_sync(mesh, config, d)

        def _local_grad_comm(X, y, mask, w, t, res):
            g, cnt = logistic.grad_sum(X, y, w, mask)
            (g, cnt), res = sync.reduce((g, cnt), res, t)
            return g, cnt, res

        grad_fn = data_parallel(
            _local_grad_comm,
            mesh,
            in_specs=(P("data", None), P("data"), P("data"), P(), P(),
                      P("data", None)),
            out_specs=(P(), P(), P("data", None)),
        )

        def train(X, y, valid, X_test, y_test, w0, res0, t0=0):
            # absolute step ids: the int8 schedule's rounding key folds
            # t in, so segmented resume replays identical noise
            def step(carry, t):
                w, res = carry
                g, _, res = grad_fn(X, y, valid, w, t, res)
                w = w - config.eta * g
                acc = metrics.binary_accuracy(X_test @ w, y_test)
                return (w, res), acc

            (w, res), accs = jax.lax.scan(
                step, (w0, res0),
                jnp.arange(config.n_iterations) + t0,
            )
            return w, accs, res

        return jax.jit(train)

    grad_fn = data_parallel(
        _local_grad,
        mesh,
        in_specs=(P("data", None), P("data"), P("data"), P()),
        out_specs=(P(), P()),
    )

    def train(X, y, valid, X_test, y_test, w0, t0=0):
        del t0  # full-batch GD is PRNG-free; kept for segment symmetry

        def step(w, _t):
            g, _ = grad_fn(X, y, valid, w)
            w = w - config.eta * g  # logistic_regression.py:84 — raw sum
            acc = metrics.binary_accuracy(X_test @ w, y_test)
            return w, acc

        w, accs = jax.lax.scan(
            step, w0, jnp.arange(config.n_iterations)
        )
        return w, accs

    return jax.jit(train)


def train(
    X_train, y_train, X_test, y_test, mesh: Mesh,
    config: LRConfig = LRConfig(),
    *,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 500,
) -> TrainResult:
    """End-to-end: shard data, compile the loop, run, return weights + accs.

    ``checkpoint_dir`` enables segmented resume (carry = w; full-batch
    GD is deterministic, so segmented ≡ straight bitwise)."""
    Xs = parallelize(X_train, mesh)
    ys = parallelize(y_train, mesh)
    w0 = logistic.init_weights(
        prng.root_key(config.init_seed), X_train.shape[1]
    )
    X_te, y_te = jnp.asarray(X_test), jnp.asarray(y_test)

    if config.comm != "dense":
        from tpu_distalg.parallel import comms, partition

        d = X_train.shape[1]
        sync = _comm_sync(mesh, config, d)
        res0 = partition.put(sync.init_state(), "res", "lr", mesh)
        if checkpoint_dir is None:
            fn = make_train_fn(mesh, config, d=d)
            w, accs, _ = fn(
                Xs.data, ys.data, Xs.mask, X_te, y_te, w0, res0)
            comms.emit_sync_counters(sync, config.n_iterations)
            metrics.guard_finite(w, "LR weights")
            return TrainResult(w=w, accs=accs)

        from tpu_distalg.utils import checkpoint as ckpt

        def run_seg(fn, state, t0):
            w, res = state
            res = partition.put(res, "res", "lr", mesh)
            w, accs, res = fn(Xs.data, ys.data, Xs.mask, X_te, y_te,
                              jnp.asarray(w), res, t0=t0)
            return (w, res), accs

        (w, _), accs, start = ckpt.run_segmented(
            checkpoint_dir, checkpoint_every, config.n_iterations,
            make_seg_fn=lambda seg: make_train_fn(
                mesh, dataclasses.replace(config, n_iterations=seg),
                d=d),
            run_seg=run_seg,
            state0=(w0, res0),
            tag=f"lr:comm={config.comm}",
        )
        # only the syncs THIS process ran (resume skips the rest)
        comms.emit_sync_counters(sync, config.n_iterations - start)
        return TrainResult(w=jnp.asarray(w), accs=jnp.asarray(accs))

    if checkpoint_dir is None:
        fn = make_train_fn(mesh, config)
        w, accs = fn(Xs.data, ys.data, Xs.mask, X_te, y_te, w0)
        metrics.guard_finite(w, "LR weights")
        return TrainResult(w=w, accs=accs)

    from tpu_distalg.utils import checkpoint as ckpt

    w, accs, _ = ckpt.run_segmented(
        checkpoint_dir, checkpoint_every, config.n_iterations,
        make_seg_fn=lambda seg: make_train_fn(
            mesh, dataclasses.replace(config, n_iterations=seg)),
        run_seg=lambda fn, w, t0: fn(
            Xs.data, ys.data, Xs.mask, X_te, y_te, jnp.asarray(w), t0=t0),
        state0=w0,
        tag="lr",
    )
    return TrainResult(w=jnp.asarray(w), accs=jnp.asarray(accs))
