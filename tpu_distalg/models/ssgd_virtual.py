"""SSGD over a VIRTUAL dataset — logical size unbounded by HBM.

The reference leans on Spark to make datasets bigger than memory a
non-problem: RDD partitions spill to executor disk and lineage
recomputes lost blocks (`/root/reference/optimization/ssgd.py:86`'s
``.cache()`` is a hint, not a requirement). The resident-``X2`` fused
samplers (``models/ssgd.py``) cap the dataset at HBM — 100M rows is
8 GB of a 16 GB v5e chip, so the 1B-row north star would need chips.

This module removes the cap the TPU-native way: rows are never stored.
The counter-based generators (``utils/datasets.synthetic_two_class_rows``)
define row content purely by global row id, so each step REGENERATES
exactly the sampled blocks on device — sampling identical to
'fused_gather' (same ``sampling.sample_block_ids`` draw keyed on the
absolute step id, so runs are deterministic and resumable), gradient
identical to the 'bernoulli' XLA path (``ops/logistic.grad_sum``), and
HBM holds only the current step's minibatch. Dataset "size" becomes a
pure integer: 400M rows (≈2× HBM if materialised bf16-packed), 1B, any
n — same program, same convergence, host RAM O(1).

Cost model: a regenerated row costs threefry bits + the normal/logistic
transforms instead of an HBM DMA — compute-bound where 'fused_gather'
is bandwidth-bound, so steps/s is lower per sampled row, but unbounded
in n_rows. The flagship resident-HBM numbers remain the headline for
datasets that fit; this is the >HBM story (bench:
``ssgd_lr_virtual_*``).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_distalg.models.ssgd import SSGDConfig, TrainResult, \
    _build_scan, warn_quantized_fraction
from tpu_distalg.ops import logistic, sampling
from tpu_distalg.parallel import DATA_AXIS, data_parallel, \
    tree_allreduce_sum
from tpu_distalg.utils import prng


@dataclasses.dataclass(frozen=True)
class VirtualData:
    """Geometry of a generated-on-the-fly two-class LR dataset."""

    n_rows: int                 # logical rows (any size)
    n_features: int = 30        # generated features (bias appended)
    data_seed: int = 0
    separation: float = 2.0

    @property
    def d(self) -> int:
        return self.n_features + 1


def _geometry(config: SSGDConfig, data: VirtualData, n_shards: int):
    """Blocks per shard and blocks sampled per shard per step — the
    'fused_gather' block-cluster sampling on a virtual row space padded
    up to a whole number of blocks per shard (padding rows carry zero
    mask via ``row_id >= n_rows``). The grid itself is the data
    subsystem's shared ``block_geometry`` (every out-of-core path —
    virtual, streamed, minibatch k-means — samples the same grid)."""
    from tpu_distalg.data import block_geometry

    rows_per_shard, n_blocks, n_sampled = block_geometry(
        data.n_rows, config.gather_block_rows, n_shards,
        config.mini_batch_fraction)
    warn_quantized_fraction(
        "virtual", n_blocks, n_sampled, config.mini_batch_fraction,
        "lower gather_block_rows for a finer grid")
    return rows_per_shard, n_blocks, n_sampled


def make_train_fn(mesh: Mesh, config: SSGDConfig, data: VirtualData):
    """Scan builder, same contract as the other SSGD builders: the
    returned ``train(X, y, valid, X_test, y_test, w0, t0=0, acc0=0.0)``
    ignores X/y/valid (pass dummies — there is no resident dataset) and
    evaluates on the given test matrix (generate one with
    :func:`heldout_set`)."""
    if config.sampler != "virtual":
        raise ValueError(
            f"make_train_fn(virtual) got sampler={config.sampler!r}")
    n_shards = mesh.shape[DATA_AXIS]
    rows_per_shard, n_blocks, n_sampled = _geometry(
        config, data, n_shards)
    # row ids are int32 on device (jax_enable_x64 is off): past 2^31-1
    # they would wrap NEGATIVE, pass the (ids < n_rows) mask, and train
    # on rows from outside the logical dataset with no error — refuse
    # instead (the held-out anchor at 2^31-1 reserves the top ids too)
    if n_shards * rows_per_shard >= 2 ** 31 - 1 - 2 ** 20:
        raise ValueError(
            f"virtual dataset of {n_shards * rows_per_shard} padded "
            "rows exceeds the int32 row-id space (~2.1B); shard over "
            "more hosts or split the id space into epochs"
        )
    br = config.gather_block_rows
    make_rows = _make_rows(data)
    key = prng.root_key(config.seed)

    def prep_xs(ts):
        # all (step, shard) block draws in one batched threefry —
        # identical to 'fused_gather' (models/ssgd.py)
        return jax.vmap(
            lambda t: sampling.sample_block_ids(
                jax.random.fold_in(key, t), n_shards, n_blocks,
                n_sampled,
            )
        )(ts)                                           # (T, S, ns)

    def _local_grad(w, idx_shards):
        shard = lax.axis_index(DATA_AXIS)
        idx = lax.dynamic_index_in_dim(idx_shards, shard, keepdims=False)
        ids = (shard * rows_per_shard + idx[:, None] * br
               + jnp.arange(br)[None, :]).reshape(-1)   # (ns*br,)
        X, y = make_rows(ids)
        Xb = jnp.concatenate(
            [X, jnp.ones((X.shape[0], 1), X.dtype)], axis=1)
        mask = (ids < data.n_rows).astype(jnp.float32)
        g, cnt = logistic.grad_sum(Xb, y, w, mask)
        return tree_allreduce_sum((g, cnt))

    grad_fn = data_parallel(
        _local_grad, mesh, in_specs=(P(), P()), out_specs=(P(), P()))

    def sample_and_grad(X, y, valid, w, idx_shards):
        del X, y, valid  # virtual: nothing resident
        return grad_fn(w, idx_shards)

    return _build_scan(config, sample_and_grad, prep_xs=prep_xs)


def _make_rows(data: VirtualData):
    from tpu_distalg.utils import datasets

    return datasets.synthetic_two_class_rows(
        data.n_features, seed=data.data_seed,
        separation=data.separation)


def heldout_set(data: VirtualData, n_test: int = 4096):
    """Fresh rows from the same generator, ids beyond every shard's
    padded training range — the convergence check's test matrix (with
    bias column), never seen by any sampled block."""
    make_rows = _make_rows(data)
    # any id >= n_rows is outside the trained (masked) set; use ids
    # far past the padding for clarity
    ids = jnp.arange(n_test, dtype=jnp.int32) + jnp.int32(
        2 ** 31 - 1 - n_test)
    X, y = jax.jit(make_rows)(ids)
    return jnp.concatenate(
        [X, jnp.ones((n_test, 1), X.dtype)], axis=1), y


def train(mesh: Mesh, config: SSGDConfig, data: VirtualData,
          n_test: int = 4096) -> TrainResult:
    """End-to-end: build, init (reference ``2·ranf−1``), run, evaluate
    on a held-out generated set."""
    fn = make_train_fn(mesh, config, data)
    X_test, y_test = heldout_set(data, n_test)
    w0 = logistic.init_weights(prng.root_key(config.init_seed), data.d)
    dummy = jnp.zeros((1,), jnp.float32)
    w, accs = fn(dummy, dummy, dummy, X_test, y_test, w0)
    return TrainResult(w=w, accs=accs)
