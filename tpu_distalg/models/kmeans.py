"""K-means (Lloyd's algorithm).

Re-design of ``/root/reference/machine_learning/k-means.py``: the per-point
``closest_center`` Python loop (``:20-28``) becomes a batched distance
argmin on the MXU; the ``reduceByKey`` cluster statistics (``:62-63``)
become a local ``segment_sum`` plus one psum of the (k, dim)+ (k,) stats
across shards; the driver center update (``:70-71``) happens replicated
on-device. The reference runs 5 fixed iterations and never uses its
``convergeDist`` constant (``:16``, SURVEY.md §2.1 row 6) — we default to
fixed iterations for parity and offer a real convergence check behind
``converge_dist``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpu_distalg.ops import kmeans as kops
from tpu_distalg.parallel import data_parallel, parallelize, tree_allreduce_sum


@dataclasses.dataclass(frozen=True)
class KMeansConfig:
    """Knob names follow ``k-means.py:14-17``."""

    k: int = 2
    n_iterations: int = 5
    converge_dist: float | None = None  # None → fixed iters (parity)
    max_iterations: int = 1000          # safety cap in converge mode
    seed: int = 42
    # scale-path init: 'sample' = k random rows (takeSample parity,
    # k-means.py:53); 'farthest' = greedy max-min over an oversample
    # (immune to the merged-cluster local optimum at larger k)
    init: str = "sample"


@dataclasses.dataclass
class KMeansResult:
    centers: jax.Array            # (k, dim)
    assignments: jax.Array        # (n_padded,) final cluster per point
    n_iterations_run: int


def _local_stats(points, mask, centers):
    assign = kops.assign_clusters(points, centers)
    sums, counts = kops.cluster_stats(points, mask, assign, centers.shape[0])
    sums, counts = tree_allreduce_sum((sums, counts))
    return sums, counts, assign


def init_centers(points: np.ndarray, k: int, seed: int) -> np.ndarray:
    """Seeded k-point sample without replacement — ``takeSample(False, k,
    42)`` (``k-means.py:53``)."""
    rng = np.random.default_rng(seed)
    idx = rng.choice(points.shape[0], size=k, replace=False)
    return np.asarray(points)[idx].astype(np.float32)


def _seg_loop(one_iter, config: KMeansConfig, seg: int,
              centers0, shift0, n_run0):
    """THE Lloyd loop — both the straight driver (one full-length
    segment) and every checkpoint segment run this exact code, so the
    segmented==straight bitwise contract cannot drift. Fixed-iteration
    mode runs exactly ``seg``; converge mode caps the while_loop at
    ``seg`` more iterations, and because the carried ``shift``
    re-enters the loop condition, post-convergence segments are
    no-ops. Returns ``(centers, shift, n_run)``."""
    if config.converge_dist is None:
        centers, _ = jax.lax.scan(
            lambda c, _: (one_iter(c), None), centers0, None,
            length=seg,
        )
        return centers, shift0, n_run0 + seg

    def cond(state):
        _, shift, it = state
        return (shift > config.converge_dist) & (it < seg)

    def body(state):
        centers, _, it = state
        new = one_iter(centers)
        shift = jnp.sum(jnp.sqrt(jnp.sum((new - centers) ** 2, axis=1)))
        return new, shift, it + 1

    centers, shift, it = jax.lax.while_loop(
        cond, body, (centers0, shift0, jnp.int32(0))
    )
    return centers, shift, n_run0 + it


def _lloyd_loop(one_iter, config: KMeansConfig, centers0):
    """Straight Lloyd driver = one full-length segment of
    :func:`_seg_loop`; ``one_iter(centers) -> centers``. Returns
    (final centers, iterations run)."""
    n_total = (config.n_iterations if config.converge_dist is None
               else config.max_iterations)
    centers, _, n_run = _seg_loop(
        one_iter, config, n_total, centers0,
        jnp.float32(jnp.inf), jnp.int32(0))
    return centers, n_run


def make_fit_fn(mesh: Mesh, config: KMeansConfig):
    stats_fn = data_parallel(
        _local_stats,
        mesh,
        in_specs=(P("data", None), P("data"), P()),
        out_specs=(P(), P(), P("data")),
    )

    def fit(points, mask, centers0):
        def one_iter(centers):
            sums, counts, _assign = stats_fn(points, mask, centers)
            return kops.update_centers(sums, counts, centers)

        centers, n_run = _lloyd_loop(one_iter, config, centers0)
        # final assignment under the final centers (the reference's closing
        # display re-evaluates with updated centers, k-means.py:57-58,76)
        _, _, assign = stats_fn(points, mask, centers)
        return centers, assign, n_run

    return jax.jit(fit)


def pack_device(mesh: Mesh, points, mask, *, dim: int, k: int,
                block_rows: int = 4096):
    """Device-side re-layout of sharded (n, dim) points into the fused
    kernel's packed rows (``ops.pallas_kmeans.pack_points`` semantics,
    but each shard packs its own slice — no host materialization, so it
    composes with ``build_sharded``'s O(1)-host scale path). Appended
    padding rows carry mask 0 and are inert."""
    from tpu_distalg.ops import pallas_kmeans as pk

    dpad, pp, _ = pk.packed_geometry(dim, k)

    def body(p, m):
        n_l = p.shape[0]
        pad = (-n_l) % pp  # ragged tail rows pad with mask 0, like the
        #                    host-side pack_points
        p = jnp.pad(p, ((0, pad), (0, dpad - dim)))
        m = jnp.pad(m, ((0, pad),))
        n2 = (n_l + pad) // pp
        n2p = n2 + (-n2) % block_rows
        X2 = p.reshape(n2, pp * dpad)
        return (jnp.pad(X2, ((0, n2p - n2), (0, 0))),
                jnp.pad(m.reshape(n2, pp), ((0, n2p - n2), (0, 0))))

    f = data_parallel(
        body, mesh,
        in_specs=(P("data", None), P("data")),
        out_specs=(P("data", None), P("data", None)),
    )
    return jax.jit(f)(points, mask)


def make_fit_fn_fused(mesh: Mesh, config: KMeansConfig, dim: int, *,
                      block_rows: int = 4096):
    """Lloyd iterations through the single-pass Pallas kernel
    (``ops.pallas_kmeans.fused_cluster_stats``): one HBM pass per
    iteration. NOTE: measured SLOWER than :func:`make_fit_fn` at bench
    scale (0.64× — see the ``ops/pallas_kmeans`` module docstring for
    the recorded A/B); kept as a tested alternative, not the default.
    Call with :func:`pack_device` outputs. Centers and
    n_iterations_run match :func:`make_fit_fn`; ASSIGNMENTS are in
    PACKED order with per-shard padding rows interleaved — filter by
    the flattened packed mask (``mask2.reshape(-1) > 0``) to recover
    the shard-contiguous input-row order."""
    from tpu_distalg.ops import pallas_kmeans as pk

    on_tpu = next(iter(mesh.devices.flat)).platform == "tpu"
    dpad, pp, _ = pk.packed_geometry(dim, config.k)

    def _local_stats2(X2, m2, centers):
        sums, counts = pk.fused_cluster_stats(
            X2, m2, centers, dim=dim, k=config.k,
            block_rows=block_rows, interpret=not on_tpu)
        return tree_allreduce_sum((sums, counts))

    stats_fn = data_parallel(
        _local_stats2, mesh,
        in_specs=(P("data", None), P("data", None), P()),
        out_specs=(P(), P()),
    )

    def fit(X2, m2, centers0):
        def one_iter(centers):
            sums, counts = stats_fn(X2, m2, centers)
            return kops.update_centers(sums, counts, centers)

        centers, n_run = _lloyd_loop(one_iter, config, centers0)
        # final assignment from the packed view (free reshape) under the
        # final centers — reference display parity (k-means.py:57-58,76)
        pts = X2.reshape(-1, dpad)[:, :dim]
        assign = kops.assign_clusters(pts, centers)
        return centers, assign, n_run

    return jax.jit(fit)


def init_centers_from_rows(make_rows, n_rows: int, k: int,
                           seed: int) -> jax.Array:
    """Device-side seeded init for the scale path: draw k DISTINCT
    global row ids host-side (O(k) memory — the ids, never the data)
    and REGENERATE exactly those rows with the counter-based generator.
    Because row content depends only on the row id, this equals
    ``takeSample(False, k, seed)`` over the materialized dataset
    (``k-means.py:53``) without a host copy or a cross-shard gather."""
    if k > n_rows:
        raise ValueError(
            f"cannot sample k={k} distinct rows from n_rows={n_rows}"
        )
    rng = np.random.default_rng(seed)
    chosen: list[int] = []
    seen: set[int] = set()
    while len(chosen) < k:
        for i in rng.integers(0, n_rows, size=k).tolist():
            if i not in seen and len(chosen) < k:
                seen.add(i)
                chosen.append(i)
    ids = jnp.asarray(np.array(chosen), jnp.int32)
    return jnp.asarray(jax.jit(make_rows)(ids), jnp.float32)


def init_centers_farthest(make_rows, n_rows: int, k: int, seed: int,
                          oversample: int = 32) -> jax.Array:
    """Farthest-point init for the scale path: regenerate ``oversample·k``
    candidate rows (still O(k) in ``n_rows``) and greedily pick k by
    max-min distance. Random-row init (``init_centers_from_rows``, the
    reference's ``takeSample`` parity) merges clusters with probability
    ≈1−k!/kᵏ on a balanced mixture; farthest-point avoids that Lloyd
    local optimum while staying a one-shot init, no extra data pass."""
    rng = np.random.default_rng(seed)
    m = oversample * k
    ids = jnp.asarray(
        rng.integers(0, n_rows, size=m, dtype=np.int64), jnp.int32)
    cand = np.asarray(jax.jit(make_rows)(ids), np.float32)  # (m, dim)
    chosen = [int(rng.integers(0, m))]
    d = np.linalg.norm(cand - cand[chosen[0]], axis=1)
    while len(chosen) < k:
        nxt = int(d.argmax())
        chosen.append(nxt)
        d = np.minimum(d, np.linalg.norm(cand - cand[nxt], axis=1))
    return jnp.asarray(cand[chosen])


def make_fit_seg_fn(mesh: Mesh, config: KMeansConfig, seg: int):
    """One compiled checkpoint segment: up to ``seg`` Lloyd iterations
    continuing from ``(centers, shift, n_run)`` — the same
    :func:`_seg_loop` the straight driver runs (the checkpoint/resume
    bitwise contract every optimizer workload has)."""
    stats_fn = data_parallel(
        _local_stats, mesh,
        in_specs=(P("data", None), P("data"), P()),
        out_specs=(P(), P(), P("data")),
    )

    def seg_run(points, mask, centers0, shift0, n_run0):
        def one_iter(centers):
            sums, counts, _ = stats_fn(points, mask, centers)
            return kops.update_centers(sums, counts, centers)

        return _seg_loop(one_iter, config, seg, centers0, shift0,
                         n_run0)

    return jax.jit(seg_run)


def _fit_segmented(data, mask, mesh, config: KMeansConfig, centers0,
                   checkpoint_dir: str, checkpoint_every: int):
    """Checkpointed Lloyd driver (state is tiny: the (k, dim) centers
    plus the convergence carry) — the task-retry capability Spark gives
    the reference's k-means for free (SURVEY.md §5)."""
    from tpu_distalg.utils import checkpoint as ckpt

    converge = config.converge_dist is not None
    n_total = config.max_iterations if converge else config.n_iterations
    stop_when = (
        (lambda s: float(s["shift"]) <= config.converge_dist)
        if converge else None)

    def run_seg(fn, state, t0):
        centers, shift, n_run = fn(
            data, mask, state["centers"], state["shift"],
            state["n_run"])
        new = {"centers": centers, "shift": shift, "n_run": n_run}
        return new, np.asarray(shift, np.float32)[None]

    state0 = {
        "centers": jnp.asarray(centers0),
        # fixed mode never updates shift — keep it finite for the
        # segment-boundary non-finite guard; converge mode starts at
        # inf exactly like the straight while_loop
        "shift": jnp.float32(np.inf if converge else 0.0),
        "n_run": jnp.int32(0),
    }
    state, _, _ = ckpt.run_segmented(
        checkpoint_dir, checkpoint_every, n_total,
        lambda seg: make_fit_seg_fn(mesh, config, seg),
        run_seg, state0,
        # the two modes share the state signature but fixed mode's
        # shift=0.0 sentinel would alias "converged" on a cross-mode
        # resume — encode the mode in the tag
        tag="kmeans_converge" if converge else "kmeans_fixed",
        stop_when=stop_when)

    assign_fn = jax.jit(data_parallel(
        lambda p, m, c: kops.assign_clusters(p, c), mesh,
        in_specs=(P("data", None), P("data"), P()),
        out_specs=P("data")))
    centers = state["centers"]
    return KMeansResult(
        centers=centers, assignments=assign_fn(data, mask, centers),
        n_iterations_run=int(state["n_run"]),
    )


def fit(points: np.ndarray, mesh: Mesh,
        config: KMeansConfig = KMeansConfig(), *,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 100) -> KMeansResult:
    ps = parallelize(points, mesh)
    centers0 = init_centers(points, config.k, config.seed)
    if checkpoint_dir is not None:
        return _fit_segmented(ps.data, ps.mask, mesh, config, centers0,
                              checkpoint_dir, checkpoint_every)
    fn = make_fit_fn(mesh, config)
    centers, assign, n_run = fn(ps.data, ps.mask, jnp.asarray(centers0))
    return KMeansResult(
        centers=centers, assignments=assign, n_iterations_run=int(n_run)
    )


def make_minibatch_step_fn(mesh: Mesh, k: int, dim: int):
    """Jitted minibatch-k-means step over one STAGED batch from a
    ``ShardedDataset`` in the ``points_valid_f32`` layout
    (``data/builders.py``): per shard, assign + masked cluster stats
    over the staged rows, one psum, then the Sculley (2010) web-scale
    update — per-center learning rate ``count_c / n_seen_c`` so each
    center converges as the harmonic mean of its minibatch means.
    ``step(staged, centers, n_seen) -> (centers, n_seen)``; arithmetic
    is identical whichever backend staged the batch, so trajectories
    are bitwise-equal across resident/virtual/streamed
    (tests/test_data.py)."""
    from jax.sharding import PartitionSpec as P

    from tpu_distalg.ops import kmeans as kops

    def _local(staged, centers):
        rows = staged[0]
        pts, m = rows[:, :dim], rows[:, dim]
        assign = kops.assign_clusters(pts, centers)
        sums, counts = kops.cluster_stats(pts, m, assign, k)
        return tree_allreduce_sum((sums, counts))

    stats_fn = data_parallel(
        _local, mesh,
        in_specs=(P("data", None, None), P()),
        out_specs=(P(), P()),
    )

    def step(staged, centers, n_seen):
        sums, counts = stats_fn(staged, centers)
        n_seen = n_seen + counts
        eta = jnp.where(n_seen > 0, counts / jnp.maximum(n_seen, 1.0),
                        0.0)
        means = sums / jnp.maximum(counts, 1.0)[:, None]
        centers = jnp.where(counts[:, None] > 0,
                            centers + eta[:, None] * (means - centers),
                            centers)
        return centers, n_seen

    return jax.jit(step)


def init_centers_from_dataset(dataset, k: int, seed: int) -> jax.Array:
    """Greedy farthest-point init over the dataset's FIRST block
    (shard 0) — O(block) host cost, identical whichever backend holds
    the bytes (the staged block is bitwise-equal across backends).
    Farthest-point, not a random k-sample: random init merges clusters
    with probability ≈1−k!/kᵏ (98.5% at k=6) and the minibatch update
    cannot split a merged pair — the same Lloyd local optimum
    :func:`init_centers_farthest` documents for the resident scale
    path."""
    block0 = np.asarray(
        dataset.stage(np.zeros((dataset.n_shards, 1), np.int64)))[0]
    dim = block0.shape[1] - 1
    valid = block0[:, dim] > 0
    pts = block0[valid][:, :dim]
    if k > pts.shape[0]:
        raise ValueError(
            f"cannot sample k={k} centers from a {pts.shape[0]}-row "
            "first block; raise block_rows")
    rng = np.random.default_rng(seed)
    chosen = [int(rng.integers(0, pts.shape[0]))]
    d = np.linalg.norm(pts - pts[chosen[0]], axis=1)
    while len(chosen) < k:
        nxt = int(d.argmax())
        chosen.append(nxt)
        d = np.minimum(d, np.linalg.norm(pts - pts[nxt], axis=1))
    return jnp.asarray(pts[chosen], jnp.float32)


def fit_minibatch(dataset, config: KMeansConfig, *, n_steps: int,
                  mini_batch_blocks: int = 4,
                  centers0=None) -> KMeansResult:
    """Minibatch k-means over a :class:`~tpu_distalg.data.ShardedDataset`
    — the >HBM Lloyd replacement this repo previously had only for SSGD
    (VERDICT "what's missing" #3): per step, ``mini_batch_blocks``
    blocks per shard are drawn with the SAME host-side threefry sampler
    the streamed SSGD trainer uses (keyed on the absolute step id, so
    runs are deterministic), staged through the prefetch pipeline
    (gather ∥ H2D ∥ compute for host backends), and folded into the
    centers with the Sculley update. The dataset must be in the
    ``points_valid_f32`` layout (``data/builders.py``); padding rows
    carry valid 0 and are inert."""
    from tpu_distalg.data import make_host_block_sampler

    import contextlib

    dim = int(dataset.meta.get("dim", dataset.pd - 1))
    ns = min(mini_batch_blocks, dataset.n_blocks)
    draw = make_host_block_sampler(
        config.seed, dataset.n_shards, dataset.n_blocks, ns)
    ids = draw(np.arange(n_steps))
    if centers0 is None:
        centers0 = init_centers_from_dataset(
            dataset, config.k, config.seed)
    step = make_minibatch_step_fn(dataset.mesh, config.k, dim)
    centers = jnp.asarray(centers0, jnp.float32)
    n_seen = jnp.zeros((config.k,), jnp.float32)
    serialize = not dataset.on_tpu
    with contextlib.closing(dataset.stream(ids)) as batches:
        for staged in batches:
            centers, n_seen = step(staged, centers, n_seen)
            if serialize:
                jax.block_until_ready(centers)
    from tpu_distalg.utils import metrics

    metrics.guard_finite(centers, "minibatch k-means centers")
    return KMeansResult(centers=centers,
                        assignments=jnp.zeros((0,), jnp.int32),
                        n_iterations_run=n_steps)


def init_centers_scaled(make_rows, n_rows: int,
                        config: KMeansConfig) -> jax.Array:
    """The scale path's ``config.init`` dispatch — one place, shared by
    :func:`fit_scaled` and bench.py (which times the fit separately)."""
    if config.init == "farthest":
        return init_centers_farthest(
            make_rows, n_rows, config.k, config.seed)
    if config.init == "sample":
        return init_centers_from_rows(
            make_rows, n_rows, config.k, config.seed)
    raise ValueError(f"unknown init {config.init!r}")


def fit_scaled(mesh: Mesh, n_rows: int, make_rows,
               config: KMeansConfig = KMeansConfig(), *,
               checkpoint_dir: str | None = None,
               checkpoint_every: int = 100) -> KMeansResult:
    """Scale-out fit: the dataset is synthesized ON DEVICE, shard by
    shard (``parallel.build_sharded``), and the init centers are
    regenerated from k row ids — host memory is O(k) in ``n_rows``,
    unlike :func:`fit`, which (like the reference's driver-side
    ``np.concatenate`` + ``parallelize``, ``k-means.py:49-53``) tops
    out at host RAM. ``make_rows(row_ids) -> (n, dim)`` must be
    jittable and counter-based (e.g.
    ``datasets.gaussian_mixture_rows``)."""
    from tpu_distalg.parallel import build_sharded

    ps = build_sharded(mesh, n_rows, make_rows)
    centers0 = init_centers_scaled(make_rows, n_rows, config)
    if checkpoint_dir is not None:
        return _fit_segmented(ps.data, ps.mask, mesh, config, centers0,
                              checkpoint_dir, checkpoint_every)
    fn = make_fit_fn(mesh, config)
    centers, assign, n_run = fn(ps.data, ps.mask, centers0)
    return KMeansResult(
        centers=centers, assignments=assign, n_iterations_run=int(n_run)
    )
