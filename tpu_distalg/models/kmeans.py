"""K-means (Lloyd's algorithm).

Re-design of ``/root/reference/machine_learning/k-means.py``: the per-point
``closest_center`` Python loop (``:20-28``) becomes a batched distance
argmin on the MXU; the ``reduceByKey`` cluster statistics (``:62-63``)
become a local ``segment_sum`` plus one psum of the (k, dim)+ (k,) stats
across shards; the driver center update (``:70-71``) happens replicated
on-device. The reference runs 5 fixed iterations and never uses its
``convergeDist`` constant (``:16``, SURVEY.md §2.1 row 6) — we default to
fixed iterations for parity and offer a real convergence check behind
``converge_dist``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpu_distalg.ops import kmeans as kops
from tpu_distalg.parallel import data_parallel, parallelize, tree_allreduce_sum


@dataclasses.dataclass(frozen=True)
class KMeansConfig:
    """Knob names follow ``k-means.py:14-17``."""

    k: int = 2
    n_iterations: int = 5
    converge_dist: float | None = None  # None → fixed iters (parity)
    max_iterations: int = 1000          # safety cap in converge mode
    seed: int = 42


@dataclasses.dataclass
class KMeansResult:
    centers: jax.Array            # (k, dim)
    assignments: jax.Array        # (n_padded,) final cluster per point
    n_iterations_run: int


def _local_stats(points, mask, centers):
    assign = kops.assign_clusters(points, centers)
    sums, counts = kops.cluster_stats(points, mask, assign, centers.shape[0])
    sums, counts = tree_allreduce_sum((sums, counts))
    return sums, counts, assign


def init_centers(points: np.ndarray, k: int, seed: int) -> np.ndarray:
    """Seeded k-point sample without replacement — ``takeSample(False, k,
    42)`` (``k-means.py:53``)."""
    rng = np.random.default_rng(seed)
    idx = rng.choice(points.shape[0], size=k, replace=False)
    return np.asarray(points)[idx].astype(np.float32)


def make_fit_fn(mesh: Mesh, config: KMeansConfig):
    stats_fn = data_parallel(
        _local_stats,
        mesh,
        in_specs=(P("data", None), P("data"), P()),
        out_specs=(P(), P(), P("data")),
    )

    def one_iter(points, mask, centers):
        sums, counts, assign = stats_fn(points, mask, centers)
        return kops.update_centers(sums, counts, centers), assign

    def fit(points, mask, centers0):
        if config.converge_dist is None:
            def body(centers, _):
                centers, _assign = one_iter(points, mask, centers)
                return centers, None

            centers, _ = jax.lax.scan(
                body, centers0, None, length=config.n_iterations
            )
            n_run = config.n_iterations
        else:
            def cond(state):
                _, shift, it = state
                return (shift > config.converge_dist) & (
                    it < config.max_iterations
                )

            def body(state):
                centers, _, it = state
                new, _assign = one_iter(points, mask, centers)
                shift = jnp.sum(
                    jnp.sqrt(jnp.sum((new - centers) ** 2, axis=1))
                )
                return new, shift, it + 1

            centers, _, n_run = jax.lax.while_loop(
                cond, body, (centers0, jnp.float32(jnp.inf), 0)
            )
        # final assignment under the final centers (the reference's closing
        # display re-evaluates with updated centers, k-means.py:57-58,76)
        _, _, assign = stats_fn(points, mask, centers)
        return centers, assign, n_run

    return jax.jit(fit)


def fit(points: np.ndarray, mesh: Mesh,
        config: KMeansConfig = KMeansConfig()) -> KMeansResult:
    ps = parallelize(points, mesh)
    centers0 = init_centers(points, config.k, config.seed)
    fn = make_fit_fn(mesh, config)
    centers, assign, n_run = fn(ps.data, ps.mask, jnp.asarray(centers0))
    return KMeansResult(
        centers=centers, assignments=assign, n_iterations_run=int(n_run)
    )
