"""Checkpoint / resume.

The reference has none (SURVEY.md §5): training state lives only in driver
RAM and the only artifacts are PNG plots. Here any pytree of arrays (model,
optimizer state, step counter) can be saved per-N-steps and restored as one
msgpack file per step (flax serialization, atomic rename). Note ``save``
gathers every leaf to this host via ``np.asarray`` — fine for the replicated
model/optimizer state these workloads carry; use orbax directly for
multi-host sharded checkpoints of device-resident datasets.

Durability contract (chaos-tested, tests/test_faults.py):

  * ``save`` appends a CRC32 footer, fsyncs the tmp file before the
    atomic ``os.replace`` and the directory after it — a torn write
    that still happens to msgpack-parse is DETECTED on restore as
    :class:`CorruptCheckpointError` instead of silently resuming from
    garbage, and a power cut cannot lose the rename;
  * transient ``OSError`` during the write is retried in place via
    :func:`telemetry.supervisor.supervised` before it becomes anyone
    else's problem;
  * ``run_segmented``'s resume quarantines a corrupt NEWEST checkpoint
    and falls back to the next-older step in-process — recovery does
    not require spending a ``run_with_restarts`` cycle;
  * a preemption request (SIGTERM/SIGINT via ``faults.preempt``) exits
    at the next segment boundary, AFTER that segment's checkpoint is
    durably saved, with the distinct ``PREEMPTED_RC`` — the resumed run
    is bitwise-identical to an uninterrupted one.

Fault-injection points: ``ckpt:write`` (the payload bytes about to hit
disk), ``ckpt:read`` (the bytes just read), ``segment:run`` (before
each compiled segment) — see ``tpu_distalg/faults/registry.py``.
"""

from __future__ import annotations

import os
import re
import struct
import zlib
from typing import Any

import jax
import numpy as np

from tpu_distalg import faults
from tpu_distalg.faults import preempt
from tpu_distalg.telemetry import events as tevents

_STEP_RE = re.compile(r"^step_(\d+)\.msgpack$")

# footer = magic + little-endian CRC32 of the payload bytes. The magic
# starts with NUL so no legacy msgpack stream ends with it by accident
# (msgpack never emits a bare trailing NUL run of this shape).
_CRC_MAGIC = b"\x00TDACRC1"
_CRC_FOOTER_LEN = len(_CRC_MAGIC) + 4

# transient-disk-fault retry schedule for the write path: short and
# fixed — a real outage longer than this is run_with_restarts' job
SAVE_RETRIES = 2
SAVE_BACKOFF_SECONDS = 0.05


class CorruptCheckpointError(ValueError):
    """A checkpoint file exists but will not deserialize or fails its
    CRC — e.g. it was half-written by the same crash the watchdog
    exists to survive (the atomic rename + fsync in :func:`save`
    prevents this for clean process deaths, but not for disk faults).
    Carries the offending ``path`` so the resume fallback (and
    :func:`run_with_restarts`) can quarantine it and resume from the
    previous step instead of dying on a retryable condition."""

    def __init__(self, path: str, msg: str):
        super().__init__(msg)
        self.path = path


def _fsync_dir(directory: str) -> None:
    """fsync the directory so the rename itself is durable (an atomic
    replace whose dirent update is lost to a power cut resumes from the
    WRONG step). Best-effort: some filesystems refuse directory fds."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save(ckpt_dir: str, tree: Any, step: int) -> str:
    """Write ``tree`` at ``ckpt_dir/step_<step>.msgpack``: CRC32 footer,
    fsync, atomic rename, directory fsync — with transient ``OSError``
    retried (:data:`SAVE_RETRIES` attempts, fixed backoff)."""
    from flax import serialization

    from tpu_distalg.telemetry.supervisor import supervised

    os.makedirs(ckpt_dir, exist_ok=True)
    host_tree = jax.tree.map(np.asarray, tree)
    payload = serialization.msgpack_serialize(host_tree)
    # footer CRC is of the TRUE payload: an injected/real torn write
    # corrupts the body after this point and the mismatch is caught on
    # restore — the exact silent-resume-from-garbage hole being closed
    footer = _CRC_MAGIC + struct.pack("<I", zlib.crc32(payload) & 0xFFFFFFFF)
    path = os.path.join(ckpt_dir, f"step_{step}.msgpack")
    tmp = path + ".tmp"

    def write_once():
        body = faults.inject("ckpt:write", payload=payload)
        with open(tmp, "wb") as f:
            f.write(body)
            f.write(footer)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(ckpt_dir)

    supervised(write_once, phase="ckpt:write", retries=SAVE_RETRIES,
               backoff=SAVE_BACKOFF_SECONDS,
               backoff_cap=SAVE_BACKOFF_SECONDS, jitter=0.0,
               retry_on=(OSError,), failure_counter="ckpt.write_failures",
               log=lambda m: None)
    return path


def _strip_crc_footer(path: str, raw: bytes) -> bytes:
    """Validate + strip the CRC footer; legacy footerless files pass
    through (their only guard is msgpack parseability, as before)."""
    if len(raw) >= _CRC_FOOTER_LEN and \
            raw[-_CRC_FOOTER_LEN:-4] == _CRC_MAGIC:
        body = raw[:-_CRC_FOOTER_LEN]
        (want,) = struct.unpack("<I", raw[-4:])
        got = zlib.crc32(body) & 0xFFFFFFFF
        if got != want:
            raise CorruptCheckpointError(
                path,
                f"corrupt checkpoint {path}: CRC32 mismatch "
                f"(stored {want:#010x}, computed {got:#010x}) — the "
                f"file was torn or bit-rotted after writing; delete or "
                f"quarantine it to resume from an earlier step")
        return body
    return raw


def list_steps(ckpt_dir: str) -> list[int]:
    """Every on-disk checkpoint step, ascending (public: the cluster
    coordinator's WAL truncation keeps segments for exactly the kept
    checkpoints, so fallback-to-older-step can still roll forward)."""
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(
        int(m.group(1))
        for name in os.listdir(ckpt_dir)
        if (m := _STEP_RE.match(name))
    )


def latest_step(ckpt_dir: str) -> int | None:
    steps = list_steps(ckpt_dir)
    return max(steps) if steps else None


def restore(ckpt_dir: str, step: int | None = None) -> tuple[Any, int]:
    """Load (tree, step); ``step=None`` loads the newest checkpoint.
    The CRC footer (when present) is verified BEFORE parsing, so a torn
    write that still happens to msgpack-parse cannot slip through."""
    from flax import serialization

    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(ckpt_dir, f"step_{step}.msgpack")
    with open(path, "rb") as f:
        raw = f.read()
    # injected read-side corruption lands BEFORE the CRC check, so the
    # detection path is the one being exercised
    raw = faults.inject("ckpt:read", payload=raw)
    payload = _strip_crc_footer(path, raw)
    try:
        tree = serialization.msgpack_restore(payload)
    except Exception as e:
        raise CorruptCheckpointError(
            path,
            f"corrupt checkpoint {path} ({type(e).__name__}: {e}); delete "
            f"it to resume from an earlier step"
        ) from e
    return tree, step


def quarantine(path: str, *, logger=None) -> bool:
    """Rename a corrupt checkpoint to ``<path>.corrupt`` so the next
    resume sees the previous step. Tolerates the concurrent-process
    race (another restart already quarantined or pruned it —
    ``FileNotFoundError`` counts as done). Returns False only when the
    rename fails for a reason that needs a human."""
    try:
        # tda: ignore[TDA030] -- recovery rename of an ALREADY-corrupt
        # file, not a durable publish: a failure here is caught below
        # and reported, and injecting at it would shift the ckpt:write
        # hit counts every recorded chaos plan replays against
        os.replace(path, path + ".corrupt")
    except FileNotFoundError:
        return True  # a concurrent process beat us to it
    except OSError as os_err:
        (logger or print)(
            f"could not quarantine corrupt checkpoint {path} "
            f"({os_err}); manual cleanup required")
        return False
    tevents.emit("quarantine", path=path)
    tevents.counter("quarantines")
    return True


def restore_newest_with_fallback(ckpt_dir: str, *, logger=None):
    """The resume read path: try the newest checkpoint; a corrupt one is
    quarantined IN-PROCESS and the next-older step is tried — recovery
    from the crash-corrupts-newest-checkpoint scenario costs zero
    restart budget. Returns ``(payload, step)`` or ``None`` when no
    restorable checkpoint remains (fresh start). Public: the serving
    layer's artifact loader degrades through the same path
    (``serve/artifacts.py``)."""
    while True:
        step = latest_step(ckpt_dir)
        if step is None:
            return None
        try:
            return restore(ckpt_dir, step)
        except CorruptCheckpointError as e:
            if not quarantine(e.path, logger=logger):
                raise
            (logger or print)(
                f"[quarantine] corrupt checkpoint {e.path} -> .corrupt; "
                f"falling back to the previous step in-process")
        except FileNotFoundError:
            # pruned/quarantined under us by a concurrent process
            # between the listing and the open — re-list and retry
            continue


def encode_tag(tag: str) -> np.ndarray:
    """msgpack round-trips arrays, not str — the byte-encoded workload
    tag every segmented loop (here and ``membership.run_elastic``)
    stores and compares. One codec, so the tag contract cannot drift
    between the tick-indexed and window-indexed loops."""
    return np.frombuffer(tag.encode(), dtype=np.uint8)


def decode_tag(payload, default: str) -> str:
    """Inverse of :func:`encode_tag`; ``default`` for legacy payloads
    written before tags existed."""
    if "tag" in payload:
        return np.asarray(
            payload["tag"]).tobytes().decode(errors="replace")
    return default


def preempt_boundary_exit(step: int, tag: str) -> None:
    """The shared preemption contract of every segmented loop: once a
    request is pending, exit at the boundary AFTER the durable save —
    emit the record here (the signal handler only sets a flag) and
    raise :class:`~tpu_distalg.faults.Preempted` (rc 75, never caught
    by the restart budget). No-op without a pending request."""
    if not preempt.requested():
        return
    tevents.emit("preempted", step=step, tag=tag,
                 signals=list(preempt.signals_seen()))
    tevents.counter("preemptions")
    raise preempt.Preempted(step=step)


def run_segmented(
    checkpoint_dir: str,
    checkpoint_every: int,
    n_iterations: int,
    make_seg_fn,
    run_seg,
    state0,
    *,
    tag: str = "",
    keep: int = 3,
    stop_when=None,
):
    """Generic segmented/resumable training loop — the machinery behind
    every workload's ``checkpoint_dir`` option.

    Runs ``n_iterations`` total steps as compiled segments of
    ``checkpoint_every``; after each segment the (state, accs-so-far) is
    saved and a non-finite guard trips with a clear error. An existing
    checkpoint resumes from its absolute step; because every builder
    threads the absolute step offset into its PRNG (``t0``), segmented
    and straight-through runs are bitwise-identical. A corrupt newest
    checkpoint is quarantined and the next-older step resumes instead
    (see :func:`restore_newest_with_fallback`).

    ``make_seg_fn(seg_len)`` builds (and caches per distinct length) the
    compiled segment; ``run_seg(fn, state, t0)`` executes it and returns
    ``(new_state, accs)``; ``state0`` is the initial carry pytree.
    ``tag`` names the workload — stored in every checkpoint and compared
    on resume (along with the state leaves' shapes/dtypes), so resuming
    the wrong workload's directory fails loudly instead of silently
    continuing from foreign weights. ``stop_when(state)`` (optional) is
    checked after every segment AND on resume: fixpoint workloads
    (k-means converge mode, closure, ALS-to-tolerance) stop as soon as
    their convergence predicate holds instead of burning no-op segments
    to ``n_iterations`` — the segment bodies must make post-convergence
    segments no-ops (carry their convergence signal in ``state``) so
    segmented and straight runs stay bitwise-identical. Returns
    ``(state, accs_concat, start_step)``.

    Preemption: once ``faults.preempt`` has a pending request (SIGTERM/
    SIGINT), the loop raises :class:`~tpu_distalg.faults.Preempted` at
    the NEXT segment boundary — after that segment's checkpoint is
    durably on disk — so the process exits with the distinct
    ``PREEMPTED_RC`` and a re-run resumes bitwise-identically.
    """
    if checkpoint_every < 1:
        raise ValueError(
            f"checkpoint_every must be >= 1, got {checkpoint_every}"
        )
    leaves0, treedef = jax.tree.flatten(state0)
    start = 0
    accs_parts = []
    state = state0
    restored = restore_newest_with_fallback(checkpoint_dir)
    if restored is not None:
        payload, start = restored
        if start > n_iterations:
            raise ValueError(
                f"checkpoint in {checkpoint_dir} is at step {start}, "
                f"past n_iterations={n_iterations}; use a fresh "
                f"directory or raise n_iterations"
            )
        # legacy pre-tag payloads ({'w','accs'}) also lack 'state', so
        # the check below always rejects them: old checkpoints need a
        # fresh directory, not a silent cross-format resume
        saved_tag = decode_tag(payload, tag)
        sig = [(tuple(np.asarray(v).shape), str(np.asarray(v).dtype))
               for v in payload.get("state", [])]
        want = [(tuple(np.asarray(x).shape), str(np.asarray(x).dtype))
                for x in leaves0]
        if "state" not in payload or saved_tag != tag or sig != want:
            raise ValueError(
                f"checkpoint in {checkpoint_dir} is incompatible: it "
                f"holds workload {saved_tag!r} with state {sig}, but "
                f"this run is {tag!r} with state {want} — it was "
                f"written by a different workload, config, or framework "
                f"version; use a fresh directory"
            )
        state = jax.tree.unflatten(
            treedef, [np.asarray(v) for v in payload["state"]]
        )
        accs_parts = [np.asarray(payload["accs"])]

    from tpu_distalg.utils import metrics

    seg_fns = {}
    t = start
    while t < n_iterations:
        if stop_when is not None and stop_when(state):
            break
        seg = min(checkpoint_every, n_iterations - t)
        # progress mark per segment: the telemetry heartbeat flags this
        # phase if a segment wedges (device hang) instead of staying mute
        tevents.mark(f"segment:{tag or 'train'}@{t}", emit_event=False)
        faults.inject("segment:run")
        if seg not in seg_fns:
            seg_fns[seg] = make_seg_fn(seg)
        state, accs = run_seg(seg_fns[seg], state, t)
        metrics.guard_finite(
            state, f"training state after step {t + seg}"
        )
        t += seg
        accs_parts.append(np.asarray(accs))
        save(
            checkpoint_dir,
            {"tag": encode_tag(tag),
             "state": [np.asarray(x) for x in jax.tree.leaves(state)],
             "accs": np.concatenate(accs_parts)},
            step=t,
        )
        prune(checkpoint_dir, keep=keep)
        tevents.emit("checkpoint_saved", step=t, tag=tag)
        tevents.counter("checkpoints_saved")
        if t < n_iterations:
            # boundary exit AFTER the durable save (the helper no-ops
            # without a pending request; a finished run never fakes a
            # preemption)
            preempt_boundary_exit(t, tag)
    accs = (np.concatenate(accs_parts) if accs_parts
            else np.zeros((0,), np.float32))
    return state, accs, start


def run_with_restarts(run_once, max_restarts: int = 0, *, logger=None):
    """Job-level auto-restart: the task-retry analogue of what Spark
    gives the reference silently (task retry + lineage recomputation —
    e.g. the cached RDD at ``/root/reference/optimization/ssgd.py:86``
    is rebuilt by lineage if an executor dies; SURVEY.md §5 "failure
    detection").

    ``run_once()`` is invoked up to ``1 + max_restarts`` times; any
    ``Exception`` (a device/tunnel crash, or :func:`run_segmented`'s
    non-finite-state guard trip) triggers a retry. Recovery comes from
    pairing with a ``checkpoint_dir``: every workload's segmented
    runner resumes from the newest checkpoint on disk, so a retry
    replays only the failed segment — and because segment sampling is
    keyed on absolute step ids, the recovered run is bitwise-identical
    to an uninterrupted one. Without a checkpoint dir each retry
    starts from step 0 (still useful for transient device faults).
    Deterministic failures (a genuine NaN the guard keeps re-hitting)
    exhaust the retries and re-raise the LAST error. Configuration
    errors (``ValueError``/``TypeError``/``FileNotFoundError`` — e.g.
    an incompatible checkpoint directory) fail identically every time,
    so they are never retried; ``KeyboardInterrupt``/``SystemExit``
    (which includes a graceful :class:`~tpu_distalg.faults.Preempted`
    boundary exit — preemption must not burn the restart budget) are
    never caught. The one retryable ``ValueError`` is
    :class:`CorruptCheckpointError`: the offending file is quarantined
    (renamed ``*.corrupt``) and the retry resumes from the previous
    step — a checkpoint corrupted by the very crash being survived must
    not kill the watchdog. (``run_segmented``'s own resume already
    falls back in-process; this path covers corruption detected by
    DIRECT ``restore`` callers and explicit-step loads.)
    """
    if max_restarts < 0:
        raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
    attempt = 0
    while True:
        try:
            return run_once()
        except CorruptCheckpointError as e:
            # quarantine retries do NOT consume the restart budget: a
            # crash that also corrupts the newest checkpoint would
            # otherwise spend attempt 1 on the crash and die on the
            # corrupt file at max_restarts=1 — the exact scenario this
            # path exists for. The loop still terminates: each pass
            # renames one distinct on-disk file, and restore() can only
            # trip on files that exist. max_restarts=0 means "no
            # recovery of any kind" and still raises.
            if max_restarts == 0:
                raise
            if not quarantine(e.path, logger=logger):
                raise
            (logger or print)(
                f"[quarantine] corrupt checkpoint {e.path} -> .corrupt; "
                f"resuming from the previous step (restart budget "
                f"untouched: {attempt}/{max_restarts} used)"
            )
        except (ValueError, TypeError, FileNotFoundError):
            raise  # deterministic config error — retrying cannot help
        except Exception as e:  # noqa: BLE001 — anything restartable
            attempt += 1
            if attempt > max_restarts:
                tevents.emit("restart_budget_exhausted",
                             attempts=attempt - 1, of=max_restarts,
                             error=f"{type(e).__name__}: {e}")
                raise
            tevents.emit("restart", attempt=attempt, of=max_restarts,
                         error=f"{type(e).__name__}: {e}")
            tevents.counter("restarts")
            (logger or print)(
                f"[restart {attempt}/{max_restarts}] "
                f"{type(e).__name__}: {e} — re-running (resumes from "
                f"the latest checkpoint if one exists)"
            )


def prune(ckpt_dir: str, keep: int = 3) -> None:
    """Delete all but the newest ``keep`` checkpoints. Tolerates a
    concurrent restart's prune racing this one (``FileNotFoundError``
    means the file is already gone — the desired state)."""
    if not os.path.isdir(ckpt_dir):
        return
    steps = sorted(
        int(m.group(1))
        for name in os.listdir(ckpt_dir)
        if (m := _STEP_RE.match(name))
    )
    for s in steps[:-keep] if keep else steps:
        try:
            os.remove(os.path.join(ckpt_dir, f"step_{s}.msgpack"))
        except FileNotFoundError:
            pass
