"""Checkpoint / resume.

The reference has none (SURVEY.md §5): training state lives only in driver
RAM and the only artifacts are PNG plots. Here any pytree of arrays (model,
optimizer state, step counter) can be saved per-N-steps and restored as one
msgpack file per step (flax serialization, atomic rename). Note ``save``
gathers every leaf to this host via ``np.asarray`` — fine for the replicated
model/optimizer state these workloads carry; use orbax directly for
multi-host sharded checkpoints of device-resident datasets.
"""

from __future__ import annotations

import os
import re
from typing import Any

import jax
import numpy as np

_STEP_RE = re.compile(r"^step_(\d+)\.msgpack$")


def save(ckpt_dir: str, tree: Any, step: int) -> str:
    """Write ``tree`` at ``ckpt_dir/step_<step>.msgpack`` (atomic rename)."""
    from flax import serialization

    os.makedirs(ckpt_dir, exist_ok=True)
    host_tree = jax.tree.map(np.asarray, tree)
    payload = serialization.msgpack_serialize(host_tree)
    path = os.path.join(ckpt_dir, f"step_{step}.msgpack")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)
    return path


def latest_step(ckpt_dir: str) -> int | None:
    if not os.path.isdir(ckpt_dir):
        return None
    steps = [
        int(m.group(1))
        for name in os.listdir(ckpt_dir)
        if (m := _STEP_RE.match(name))
    ]
    return max(steps) if steps else None


def restore(ckpt_dir: str, step: int | None = None) -> tuple[Any, int]:
    """Load (tree, step); ``step=None`` loads the newest checkpoint."""
    from flax import serialization

    if step is None:
        step = latest_step(ckpt_dir)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(ckpt_dir, f"step_{step}.msgpack")
    with open(path, "rb") as f:
        payload = f.read()
    try:
        tree = serialization.msgpack_restore(payload)
    except Exception as e:
        raise ValueError(
            f"corrupt checkpoint {path} ({type(e).__name__}: {e}); delete "
            f"it to resume from an earlier step"
        ) from e
    return tree, step


def prune(ckpt_dir: str, keep: int = 3) -> None:
    """Delete all but the newest ``keep`` checkpoints."""
    if not os.path.isdir(ckpt_dir):
        return
    steps = sorted(
        int(m.group(1))
        for name in os.listdir(ckpt_dir)
        if (m := _STEP_RE.match(name))
    )
    for s in steps[:-keep] if keep else steps:
        os.remove(os.path.join(ckpt_dir, f"step_{s}.msgpack"))
