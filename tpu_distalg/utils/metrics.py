"""Metrics: accuracy, EWMA smoothing, and convergence-plot rendering.

Reproduces the reference's observability surface — per-iteration test
accuracy and the EWMA accuracy plot (``/root/reference/optimization/
ssgd.py:50-66`` ``draw_acc_plot``, α=0.9) — plus step-timing helpers the
reference lacks (SURVEY.md §5: build adds steps/sec metric emission).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def binary_accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Accuracy with the reference's decision rule: predict 1 iff p >= 0.5
    (``ssgd.py:110`` uses ``where(y_pred < 0.5, 0, 1)``)."""
    pred = jnp.where(logits < 0.0, 0.0, 1.0)  # sigmoid(z) < .5  <=>  z < 0
    return jnp.mean((pred == labels).astype(jnp.float32))


def guard_finite(tree, context: str):
    """Raise FloatingPointError if any floating leaf holds NaN/Inf — the
    guard the reference lacks entirely (its unstable sigmoid can NaN
    silently, SURVEY.md §5). Called on final model state by every
    trainer; the checkpointed paths additionally guard every segment."""
    import jax.numpy as jnp

    for leaf in jax.tree.leaves(tree):
        leaf = jnp.asarray(leaf)
        if (jnp.issubdtype(leaf.dtype, jnp.floating)
                and not bool(jnp.all(jnp.isfinite(leaf)))):
            raise FloatingPointError(
                f"non-finite values in {context} — check eta/"
                f"regularisation/input data (guard absent in the "
                f"reference)"
            )
    return tree


def ewma(values: np.ndarray, alpha: float = 0.9) -> np.ndarray:
    """EWMA with the reference's recurrence s[t] = α·s[t-1] + (1-α)·v[t],
    s[0] = v[0] (``ssgd.py:51-59``)."""
    values = np.asarray(values, dtype=np.float64)
    out = np.empty_like(values)
    if len(values) == 0:
        return out
    out[0] = values[0]
    for i in range(1, len(values)):
        out[i] = alpha * out[i - 1] + (1 - alpha) * values[i]
    return out


def draw_acc_plot(accs, path: str, alpha: float = 0.9, title: str =
                  "Accuracy on test dataset") -> None:
    """Raw + EWMA accuracy curves, saved to ``path`` (≙ ``draw_acc_plot``)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    accs = np.asarray(accs)
    xs = np.arange(1, len(accs) + 1)
    fig, ax = plt.subplots()
    ax.plot(xs, accs, color="C0", alpha=0.3)
    ax.plot(xs, ewma(accs, alpha), color="C0")
    ax.set_title(title)
    ax.set_xlabel("Round")
    ax.set_ylabel("Accuracy")
    fig.savefig(path)
    plt.close(fig)


def display_clusters(points, assignments, path: str, k: int | None = None):
    """2-D cluster scatter plot — the reference's ``display_clusters``
    (``k-means.py:30-40``), with stable per-cluster colors instead of its
    random hex strings."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    points = np.asarray(points)
    assignments = np.asarray(assignments)
    if points.shape[1] != 2:
        raise ValueError("display_clusters draws 2-D points only")
    k = k if k is not None else int(assignments.max()) + 1
    fig, ax = plt.subplots()
    for c in range(k):
        sel = assignments == c
        ax.scatter(points[sel, 0], points[sel, 1], s=12, label=f"c{c}")
    ax.legend(loc="best", fontsize=8)
    fig.savefig(path)
    plt.close(fig)


class StepTimer:
    """Wall-clock timer for XLA programs. Dispatch is async, so assign the
    program's output to ``.result`` inside the block — ``__exit__`` calls
    ``jax.block_until_ready`` on it before reading the clock::

        with StepTimer() as t:
            t.result = train_fn(...)
        print(t.elapsed)
    """

    def __init__(self):
        self._t0 = None
        self.elapsed = 0.0
        self.result = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if exc[0] is None and self.result is not None:
            jax.block_until_ready(self.result)
        self.elapsed = time.perf_counter() - self._t0
        return False
