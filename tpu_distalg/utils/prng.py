"""Counter-based PRNG plumbing.

Replaces the reference's per-iteration reseeding idiom ``sample(False, frac,
42 + t)`` (``/root/reference/optimization/ssgd.py:97``) and its *unseeded*
``random()`` in Monte Carlo (``randomized_algorithm/monte_carlo.py:18-19``)
with deterministic ``jax.random`` key folding. With JAX's partitionable
threefry, random bits depend only on (key, position) — so sampling decisions
are identical regardless of how many devices the array is sharded over,
which is what makes the n-device ≡ 1-device property tests possible.
"""

from __future__ import annotations

import jax


def root_key(seed: int = 42) -> jax.Array:
    return jax.random.key(seed)


def step_key(key: jax.Array, t) -> jax.Array:
    """Key for iteration t (≙ the reference's ``seed=42 + t``)."""
    return jax.random.fold_in(key, t)
