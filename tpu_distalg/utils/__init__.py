"""PRNG plumbing, datasets, metrics, plotting, checkpointing."""

from tpu_distalg.utils import datasets, metrics, prng

__all__ = ["datasets", "metrics", "prng"]
