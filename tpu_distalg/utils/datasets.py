"""Dataset loading and synthesis.

The reference's optimizers all train on sklearn breast-cancer with a fixed
70/30 split (``/root/reference/optimization/ssgd.py:71-76``); benchmarks
need synthetic data at scale (BASELINE.json: 1B-row two-class LR data,
1M-node Erdős–Rényi graphs). Bias handling follows the reference: a ones
column is appended to X (``ssgd.py:83-84``), so the model has D+1 weights.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def breast_cancer_split(test_size: float = 0.3, random_state: int = 0):
    """Breast-cancer 70/30 split, bias column appended — the reference task.

    Returns (X_train1, y_train, X_test1, y_test) with the ones column already
    concatenated (matching ``ssgd.py:83-84``; test side ``ssgd.py:108-109``).
    """
    from sklearn.datasets import load_breast_cancer
    from sklearn.model_selection import train_test_split

    X, y = load_breast_cancer(return_X_y=True)
    X_train, X_test, y_train, y_test = train_test_split(
        X, y, test_size=test_size, random_state=random_state, shuffle=True
    )
    return (
        add_bias_column(X_train),
        y_train.astype(np.float32),
        add_bias_column(X_test),
        y_test.astype(np.float32),
    )


def add_bias_column(X: np.ndarray) -> np.ndarray:
    return np.concatenate(
        [X, np.ones((X.shape[0], 1))], axis=1
    ).astype(np.float32)


def synthetic_two_class(
    n_rows: int, n_features: int = 30, seed: int = 0, separation: float = 2.0
) -> Tuple[np.ndarray, np.ndarray]:
    """Linearly-separable-ish two-class Gaussian data for LR benchmarks."""
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(n_features,))
    X = rng.normal(size=(n_rows, n_features)).astype(np.float32)
    logits = X @ w_true * separation / np.sqrt(n_features)
    y = (logits + rng.logistic(size=n_rows) > 0).astype(np.float32)
    return X, y


def synthetic_two_class_rows(n_features: int, seed: int = 0,
                             separation: float = 2.0):
    """Jittable per-row generator for ``parallel.build_sharded`` — the
    host-memory-free sibling of :func:`synthetic_two_class` (same
    distribution, counter-based per-row PRNG so content depends only on
    the global row id, not the shard topology). Returns
    ``make_rows(row_ids) -> (X_rows, y_rows)``; the bias column is NOT
    appended (compose with a column of ones like ``add_bias_column``).
    """
    import jax
    import jax.numpy as jnp

    from tpu_distalg.utils import prng

    key = prng.root_key(seed)
    k_w, k_rows = jax.random.fold_in(key, 0), jax.random.fold_in(key, 1)

    def make_rows(ids):
        w_true = jax.random.normal(k_w, (n_features,))
        row_keys = jax.vmap(lambda i: jax.random.fold_in(k_rows, i))(ids)
        X = jax.vmap(
            lambda k: jax.random.normal(k, (n_features,))
        )(row_keys)
        logits = X @ w_true * (separation / jnp.sqrt(n_features))
        noise = jax.vmap(
            lambda k: jax.random.logistic(jax.random.fold_in(k, 7))
        )(row_keys)
        y = (logits + noise > 0).astype(jnp.float32)
        return X, y

    return make_rows


def gaussian_mixture(
    n_rows: int, k: int = 4, dim: int = 2, seed: int = 0, spread: float = 8.0
) -> np.ndarray:
    """Gaussian-mixture points for k-means benchmarks (BASELINE.json config)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, dim)) * spread
    assign = rng.integers(0, k, size=n_rows)
    return (centers[assign] + rng.normal(size=(n_rows, dim))).astype(np.float32)


def gaussian_mixture_rows(k: int = 4, dim: int = 2, seed: int = 0,
                          spread: float = 8.0):
    """Jittable per-row Gaussian-mixture generator for
    ``parallel.build_sharded`` — the host-memory-free sibling of
    :func:`gaussian_mixture` (counter-based per-row PRNG: content
    depends only on the global row id, not the shard topology).
    Returns ``(make_rows, true_centers_fn)``: ``make_rows(row_ids) ->
    (n, dim) points``; ``true_centers_fn()`` the mixture means, for
    recovery checks."""
    import jax
    import jax.numpy as jnp

    from tpu_distalg.utils import prng

    key = prng.root_key(seed)
    k_c, k_rows = jax.random.fold_in(key, 0), jax.random.fold_in(key, 1)

    def true_centers():
        return jax.random.normal(k_c, (k, dim)) * spread

    def make_rows(ids):
        centers = true_centers()
        row_keys = jax.vmap(lambda i: jax.random.fold_in(k_rows, i))(ids)
        assign = jax.vmap(
            lambda rk: jax.random.randint(rk, (), 0, k)
        )(row_keys)
        noise = jax.vmap(
            lambda rk: jax.random.normal(
                jax.random.fold_in(rk, 1), (dim,))
        )(row_keys)
        return centers[assign] + noise

    return make_rows, true_centers


def erdos_renyi_edges(
    n_vertices: int, avg_degree: float = 8.0, seed: int = 0
) -> np.ndarray:
    """Uniform-random directed edge list (src, dst), shape (E, 2), no
    self-loops — the 1M-node PageRank benchmark graph (BASELINE.json)."""
    rng = np.random.default_rng(seed)
    n_edges = int(n_vertices * avg_degree)
    src = rng.integers(0, n_vertices, size=n_edges, dtype=np.int64)
    dst = rng.integers(0, n_vertices - 1, size=n_edges, dtype=np.int64)
    dst = np.where(dst >= src, dst + 1, dst)  # avoid self-loops
    return np.stack([src, dst], axis=1)


def chain_forest_edges(n_vertices: int, chain_len: int = 8) -> np.ndarray:
    """Disjoint directed chains — a bounded-closure benchmark graph (the
    closure of an ER graph in the supercritical regime is Θ(V²) pairs, an
    inherently quadratic OUTPUT no sparse representation can avoid; chains
    give closure = (V/L)·C(L,2), linear in V)."""
    chain_len = max(2, min(chain_len, n_vertices))
    if n_vertices < 2:
        return np.zeros((0, 2), dtype=np.int64)
    starts = np.arange(0, n_vertices - chain_len + 1, chain_len)
    src = np.concatenate([s + np.arange(chain_len - 1) for s in starts])
    return np.stack([src, src + 1], axis=1).astype(np.int64)


def toy_graph_edges() -> np.ndarray:
    """The reference's 4-edge toy graph (``pagerank.py:35-38``,
    ``transitive_closure.py:18``), 0-indexed."""
    return np.array([[0, 1], [0, 2], [1, 2], [2, 0]], dtype=np.int64)


def toy_kmeans_matrix() -> np.ndarray:
    """The reference's hard-coded 6x2 k-means input (``k-means.py:49-50``)."""
    return np.array(
        [[1, 2], [1, 4], [1, 0], [10, 2], [10, 4], [10, 0]], dtype=np.float32
    )


def streamed_packed_cache(path: str, n_rows: int, n_features: int, *,
                          n_shards: int, pack: int = 16,
                          gather_block_rows: int = 8192, seed: int = 0,
                          x_dtype="bfloat16", chunk_rows: int = 1 << 21,
                          n_test: int = 8192):
    """Create-or-open a DISK-backed packed two-class dataset for the
    streamed >HBM trainer (``models/ssgd_stream``): ``<path>.bin`` is a
    memmap in the exact ``pack_augmented`` layout, ``<path>.meta.json``
    its geometry, ``<path>.test.npz`` a held-out split from the same
    teacher. Rows are a noisy linear-teacher task (uniform features,
    Bernoulli labels at the teacher's sigmoid) generated ONCE in
    streaming chunks — after that the bytes on disk are opaque data the
    trainer must move, exactly the situation Spark's spill/stream
    handles for the reference (``ssgd.py:86``). Returns
    ``(memmap X2, meta, (X_test, y_test))``; an existing cache with
    matching geometry is reopened read-only at O(ms).

    The disk format and publish protocol are the data subsystem's
    generalized packed cache (``tpu_distalg/data/cache.py`` — the
    engine was lifted OUT of this function in PR 2): versioned header,
    atomic aux→bin→meta publish, PID/uuid tmp names with a stale-orphan
    sweep. Caches written before the versioned header (flat geometry
    dict as the whole meta.json) reopen unchanged via the legacy path —
    a rig's multi-GB cache survives the format promotion."""
    import jax.numpy as jnp

    from tpu_distalg.data import cache as dcache
    from tpu_distalg.ops import pallas_kernels

    d = n_features + 1  # + bias, like the resident flagship task
    d_t, y_col, v_col = pallas_kernels.packed_dims(d, pack)
    mult = pack * gather_block_rows * n_shards
    if n_rows % mult:
        raise ValueError(
            f"n_rows={n_rows} must be a multiple of pack×block×shards="
            f"{mult} (no padding rows in a memmap dataset)")
    n2 = n_rows // pack
    pd = pack * d_t
    np_dtype = np.dtype(jnp.dtype(x_dtype))
    geom = dict(n_rows=n_rows, n_features=n_features, pack=pack,
                d_total=d_t, y_col=y_col, v_col=v_col, seed=seed,
                x_dtype=str(x_dtype), n_test=n_test)
    meta = dict(pack=pack, d_total=d_t, y_col=y_col, v_col=v_col,
                n_padded=n_rows)
    test_path = path + ".test.npz"

    if np_dtype.itemsize != 2:
        raise ValueError(
            f"streamed cache generates bf16 bit-packed rows; "
            f"x_dtype={x_dtype} is not 2-byte")
    rng = np.random.default_rng(seed)
    # features are EXACT bf16 values 1 + m/128, m ~ uniform{0..127}:
    # generated as raw bf16 BIT patterns (exponent fixed at 127, the 7
    # mantissa bits random) so the 32 GB is produced at integer-RNG +
    # bit-op speed — the f32-uniform + astype(bf16) formulation
    # measured ~25 min on this 1-core host, this one ~3 min. The value
    # is affine in m, so a linear teacher on m stays a linear-logit
    # task on the stored features. Var(m/128) = 1/12; teacher scaled
    # for logit std ≈ 2 → its own held-out accuracy ≈ 0.76 (saved in
    # .test.npz as the ceiling).
    wf = rng.standard_normal(d - 1).astype(np.float32)
    # features are ±(1 + m/128): sign-symmetric (mean 0 — uncentered
    # [1,2) features condition the logistic Hessian ~1000:1 worse and
    # SGD crawls), per-feature variance E[(1+u)²] ≈ 2.32. Teacher
    # scaled for logit std ≈ 2; its value-space vector is exactly
    # [wf…, 0] (no intercept needed), saved as the accuracy ceiling.
    VAR_X = 1.0 + 2 * (63.5 / 128.0) + float(
        np.mean((np.arange(128) / 128.0) ** 2))
    wf *= 2.0 / np.sqrt(np.sum(wf ** 2) * VAR_X)
    w_true = np.concatenate([wf, [0.0]]).astype(np.float32)
    EXP0 = np.uint16(127 << 7)   # exponent field for [1, 2)
    ONE = np.uint16(0x3F80)      # bf16 bit pattern of 1.0

    def _values(m, sgn):
        return ((1.0 + m.astype(np.float32) / 128.0)
                * (1.0 - 2.0 * sgn.astype(np.float32)))

    def gen_bits(n, g):
        """(n, d) bf16 bit patterns + labels; column d-1 is the bias."""
        m = g.integers(0, 128, size=(n, d), dtype=np.uint16)
        sgn = g.integers(0, 2, size=(n, d), dtype=np.uint16)
        m[:, -1] = 0
        sgn[:, -1] = 0                    # bias column = exactly +1.0
        logits = _values(m[:, :-1], sgn[:, :-1]) @ wf
        p = 1.0 / (1.0 + np.exp(-logits))
        y = (g.random(n, dtype=np.float32) < p)
        return (EXP0 | m | (sgn << np.uint16(15))), y

    def write_bin(mm):
        # bf16 memmap viewed as its uint16 bit patterns — the generator
        # works in raw bits (the f32 + astype path measured ~8x slower).
        # NOTE: `rng` is the OUTER stream, continued after the teacher
        # draw above — recreating it here would change the bytes vs
        # every cache generated before the engine extraction.
        X2u = mm.view(np.uint16)
        chunk = chunk_rows - (chunk_rows % pack)
        out = np.zeros((chunk, d_t), np.uint16)
        from tpu_distalg.telemetry import events as tevents

        for lo in range(0, n_rows, chunk):
            # per-chunk progress mark: a cold 32 GB generation runs
            # ~15 min and must read as progress, not as a stall, to
            # the heartbeat
            tevents.mark(f"streamed_cache:gen@{lo}/{n_rows}",
                         emit_event=False)
            n_c = min(chunk, n_rows - lo)
            bits, yc = gen_bits(n_c, rng)
            out[:n_c, :d] = bits
            out[:n_c, y_col] = np.where(yc, ONE, np.uint16(0))
            out[:n_c, v_col] = ONE
            X2u[lo // pack:(lo + n_c) // pack] = out[:n_c].reshape(
                n_c // pack, pd)

    def write_test(tmp_path):
        g2 = np.random.default_rng(seed + 1)
        bits_t, y_test = gen_bits(n_test, g2)
        # feature VALUES as the device sees them: ±(1 + m/128)
        X_test = _values(bits_t & np.uint16(0x7F),
                         bits_t >> np.uint16(15))
        # a FILE handle: np.savez on a path appends '.npz', which would
        # break the engine's tmp→final rename
        # tda: ignore[TDA030] -- aux writer invoked INSIDE
        # cache.build_cache's cache:write seam (tmp→rename publish and
        # injection both happen there); single-file analysis cannot
        # see the callback edge
        with open(tmp_path, "wb") as f:
            np.savez(f, X=X_test, y=y_test.astype(np.float32),
                     w_true=w_true)

    header = dcache.make_header(layout="packed_augmented",
                                dtype=str(x_dtype), shape=(n2, pd),
                                geom=geom)
    X2, _hdr = dcache.open_or_build(
        path, header=header, write_bin=write_bin,
        aux=[("test.npz", write_test)], legacy_geom=geom)
    if X2 is None:  # pre-versioned cache (flat geom meta.json)
        X2 = np.memmap(dcache.bin_path(path), dtype=np_dtype, mode="r",
                       shape=(n2, pd))
    t = np.load(test_path)
    return X2, meta, (t["X"], t["y"])
