"""Profiling and tracing helpers.

The reference's only observability is ``print`` per iteration plus Spark's
(unused) web UI (SURVEY.md §5). Here: a TensorBoard/Perfetto trace context
(``jax.profiler``) and an honest steps/sec measurement that blocks on
device completion.
"""

from __future__ import annotations

import contextlib
import time

import jax


def maybe_trace(logdir):
    """``trace(logdir)`` when a directory is given, else a no-op context —
    the one-liner behind every ``--profile DIR`` flag."""
    return trace(logdir) if logdir else contextlib.nullcontext()


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace viewable in TensorBoard / Perfetto:

        with profiling.trace("/tmp/trace"):
            out = train_fn(...)
            jax.block_until_ready(out)
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def steps_per_sec(fn, *args, steps: int, repeats: int = 3,
                  warmup: bool = True) -> float:
    """Best-of-``repeats`` throughput of ``fn(*args)``, where one call runs
    ``steps`` device-side steps (e.g. a scan segment). Blocks on the result
    each repeat, so dispatch-async bias is excluded."""
    if warmup:
        jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return steps / best
