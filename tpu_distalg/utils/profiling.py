"""Profiling and tracing helpers.

The reference's only observability is ``print`` per iteration plus Spark's
(unused) web UI (SURVEY.md §5). Here: a TensorBoard/Perfetto trace context
(``jax.profiler``) and an honest steps/sec measurement that blocks on
device completion.
"""

from __future__ import annotations

import contextlib
import time

import jax


def maybe_trace(logdir):
    """``trace(logdir)`` when a directory is given, else a no-op context —
    the one-liner behind every ``--profile DIR`` flag."""
    return trace(logdir) if logdir else contextlib.nullcontext()


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace viewable in TensorBoard / Perfetto:

        with profiling.trace("/tmp/trace"):
            out = train_fn(...)
            jax.block_until_ready(out)
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def steps_per_sec(fn, *args, steps: int, repeats: int = 3,
                  warmup: bool = True, with_output: bool = False,
                  with_stats: bool = False, chain: int = 1):
    """Best-of-``repeats`` throughput of ``fn(*args)``, where one call runs
    ``steps`` device-side steps (e.g. a scan segment) as ONE compiled
    program. Completion is observed by fetching the program's first
    output leaf to the host — on tunneled TPU backends
    ``block_until_ready`` can return before execution finishes, which
    silently turns a throughput number into a dispatch number, and every
    host round-trip costs ~100 ms there, so exactly one small fetch is
    made (one jit execution produces all outputs, so one leaf proves
    completion of all of them). Huge leaves fetch a single element
    instead (stays addressable on multi-host meshes).

    ``chain`` enqueues that many back-to-back calls per timed repeat and
    fetches once at the end. Dispatch is async, so the device runs call
    k while call k+1 is in flight and the single ~100 ms tunnel
    round-trip amortizes over ``chain × steps`` steps instead of
    ``steps`` (measured on this rig: a TRIVIAL 1500-step scan "measures"
    63 µs/step at chain=1 and 4.5 µs/step at chain=16 — the difference
    is pure host round-trip, not device time). The result still charges
    1/chain of the round-trip, so it remains a conservative
    underestimate of device throughput. Calls are independent repeats of
    ``fn(*args)``; the device executes them in order on one stream.

    ``with_output=True`` appends the last output (e.g. trained weights
    for a convergence check — no re-run needed). ``with_stats=True``
    appends a ``{"repeats", "chain", "best", "median", "min"}`` dict of
    the per-repeat rates: on shared chips run-to-run throughput varies
    (±40% observed), so a single best-of number is not comparable
    across sessions without the spread next to it."""
    import numpy as np

    def fetch(n_calls=chain):
        for _ in range(n_calls):
            out = fn(*args)
        leaf = jax.numpy.asarray(jax.tree.leaves(out)[0])
        if leaf.size <= (1 << 20):
            np.asarray(leaf)     # small: one plain D2H, no dispatch
        else:
            # large/sharded: fetch one element — the extra tiny dispatch
            # beats shipping the whole buffer to the host
            np.asarray(leaf[(0,) * leaf.ndim])
        return out

    # ONE call compiles and primes the path; warming the whole chain
    # would burn chain-1 redundant full executions
    out = fetch(1) if warmup else None
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fetch()
        rates.append(chain * steps / (time.perf_counter() - t0))
    stats = {
        "repeats": repeats,
        "chain": chain,
        "best": round(max(rates), 2),
        "median": round(float(np.median(rates)), 2),
        "min": round(min(rates), 2),
    }
    result = (max(rates),)
    if with_stats:
        result += (stats,)
    if with_output:
        result += (out,)
    return result[0] if len(result) == 1 else result
