"""Profiling and tracing helpers.

The reference's only observability is ``print`` per iteration plus Spark's
(unused) web UI (SURVEY.md §5). Here: a TensorBoard/Perfetto trace context
(``jax.profiler``) and an honest steps/sec measurement that blocks on
device completion.
"""

from __future__ import annotations

import contextlib
import time

import jax


def maybe_trace(logdir):
    """``trace(logdir)`` when a directory is given, else a no-op context —
    the one-liner behind every ``--profile DIR`` flag."""
    return trace(logdir) if logdir else contextlib.nullcontext()


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace viewable in TensorBoard / Perfetto:

        with profiling.trace("/tmp/trace"):
            out = train_fn(...)
            jax.block_until_ready(out)
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def steps_per_sec(fn, *args, steps: int, repeats: int = 3,
                  warmup: bool = True, with_output: bool = False):
    """Best-of-``repeats`` throughput of ``fn(*args)``, where one call runs
    ``steps`` device-side steps (e.g. a scan segment) as ONE compiled
    program. Completion is observed by fetching the program's first
    output leaf to the host — on tunneled TPU backends
    ``block_until_ready`` can return before execution finishes, which
    silently turns a throughput number into a dispatch number, and every
    host round-trip costs ~100 ms there, so exactly one small fetch is
    made (one jit execution produces all outputs, so one leaf proves
    completion of all of them). Huge leaves fetch a single element
    instead (stays addressable on multi-host meshes).

    ``with_output=True`` returns ``(steps_per_sec, last_output)`` so a
    caller that also wants the computed result (e.g. trained weights for
    a convergence check) need not re-run the program."""
    import numpy as np

    def fetch():
        out = fn(*args)
        leaf = jax.numpy.asarray(jax.tree.leaves(out)[0])
        if leaf.size <= (1 << 20):
            np.asarray(leaf)     # small: one plain D2H, no dispatch
        else:
            # large/sharded: fetch one element — the extra tiny dispatch
            # beats shipping the whole buffer to the host
            np.asarray(leaf[(0,) * leaf.ndim])
        return out

    out = fetch() if warmup else None
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fetch()
        best = min(best, time.perf_counter() - t0)
    rate = steps / best
    return (rate, out) if with_output else rate
