"""Comms-layer coverage — raw collectives in model code (TDA050) and
wire-dtype discipline in the comms layer itself (TDA051).

PR 5 built ``tpu_distalg/parallel/comms.py`` as the single instrumented
choke point for cross-shard gradient/parameter traffic: every sync
routes through a :class:`CommSpec`-selected schedule and is accounted
in the ``comm.bytes_wire``/``bytes_logical``/``rounds`` telemetry
counters. A raw ``lax.psum`` added to a model afterwards is traffic the
knob cannot re-schedule and the counters never see — the byte
accounting rots silently as models grow. TDA050 keeps the choke point
exhaustive: model code calls the comms layer (``comms.psum`` /
``comms.pmean`` / a ``CommSync`` / the ``collectives`` tree wrappers),
never ``lax.psum``-family ops directly.

TDA051 polices the layer's round-11 headline: the compressed payloads
move NATIVELY on the wire. PR 5's honest caveat was exactly the
pattern this rule flags — a quantized buffer widened back to int32/f32
*as it entered the collective* (``lax.psum(q.astype(jnp.int32))``),
which moved 4 bytes/elem over the interconnect while the accounting
claimed 1. Widening a received buffer AFTER the collective (the exact
int32 accumulation of the native ring) is fine and unflagged; the
regression is the widening cast between quantize and the wire.
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import Rule, call_name, dotted_name

#: the raw collective-reduction ops being policed (ppermute/all_gather
#: pipelines are algorithm structure, not gradient sync — the ring
#: kernels in parallel/ own those)
_RAW_OPS = ("psum", "pmean", "psum_scatter", "pmax", "pmin")

#: call roots that mean "the raw jax op" rather than a blessed wrapper
_RAW_ROOTS = ("lax", "jax")


class RawCollectiveInModels(Rule):
    code = "TDA050"
    name = "raw cross-shard collective outside the comms layer"
    invariant = ("every cross-shard reduction in tpu_distalg/models/ "
                 "routes through parallel/comms (comms.psum, a "
                 "CommSync schedule) or the collectives tree wrappers, "
                 "so all gradient/parameter traffic stays behind the "
                 "one instrumented, --comm-schedulable choke point")

    def applies(self, ctx):
        return "tpu_distalg/models/" in ctx.path

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None or "." not in name:
                continue
            parts = name.split(".")
            if parts[-1] in _RAW_OPS and parts[0] in _RAW_ROOTS:
                yield self.violation(
                    ctx, node,
                    f"raw {name}() in model code — route the "
                    f"reduction through tpu_distalg.parallel.comms "
                    f"(comms.{parts[-1]} for a verbatim psum, or a "
                    f"CommSync for schedulable gradient sync) so the "
                    f"--comm knob and the comm.bytes_wire accounting "
                    f"cover it")


#: collective ops whose ARGUMENTS must stay at wire precision — a
#: widening cast feeding any of these re-inflates the payload
_WIRE_OPS = ("psum", "pmean", "pmax", "pmin", "psum_scatter",
             "ppermute", "all_to_all", "all_gather")

#: the CLUSTER tier's wire entry points (tpu_distalg/cluster/): a
#: frame handed to any of these goes byte-for-byte onto the TCP
#: socket, so a quantized buffer widened on its way in is the same
#: regression at the process boundary — the host codec's int8/pair
#: payload silently re-inflated to f32/int32 while
#: cluster_wire_reduction_vs_dense claims the compressed size.
#: Matched by call TAIL under any root (``transport.send_frame``, a
#: bare imported ``send_frame``, ``sock.sendall``/``sendmsg``).
_CLUSTER_WIRE_OPS = ("send_frame", "encode_frame",
                     "encode_frame_parts", "request", "sendall",
                     "sendmsg")

#: dtypes wider than int8 — casting a quantized buffer to any of these
#: before the collective silently reintroduces the int32-psum wire
_WIDER_THAN_INT8 = frozenset((
    "int16", "int32", "int64", "uint16", "uint32", "uint64",
    "float16", "bfloat16", "float32", "float64"))


def _dtype_token(node) -> str | None:
    """The dtype a cast names: ``jnp.int32`` → 'int32', ``'int32'`` →
    'int32', ``np.dtype('int32')``-style left unresolved (None)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    name = dotted_name(node)
    if name:
        return name.rsplit(".", 1)[-1]
    return None


def _is_quantize_expr(node) -> bool:
    """Does this expression produce a quantized buffer? Either spelling
    counts: an ``.astype(int8)`` cast anywhere in the subtree, or the
    clip-of-floor/round idiom (the PR 5 code quantized into an f32
    buffer — ``clip(floor(x/scale + u))`` — and THAT buffer took the
    widening cast on its way into the psum)."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        name = call_name(sub)
        if name is None:
            continue
        tail = name.rsplit(".", 1)[-1]
        if tail == "astype" and sub.args \
                and _dtype_token(sub.args[0]) in ("int8", "uint8"):
            return True
        if tail in ("clip", "clamp"):
            inner = any(
                isinstance(s, ast.Call)
                and (call_name(s) or "").rsplit(".", 1)[-1]
                in ("floor", "round", "rint")
                for s in ast.walk(sub))
            if inner:
                return True
    return False


class WideningCastOntoWire(Rule):
    code = "TDA051"
    name = "quantized buffer widened on its way into a collective"
    invariant = ("in tpu_distalg/parallel/ a buffer produced by "
                 "quantization (astype(int8) or the clip(floor(...)) "
                 "idiom) enters collectives at wire precision, and in "
                 "tpu_distalg/cluster/ it enters the framed TCP "
                 "transport (send_frame/encode_frame/request/sendall) "
                 "at wire precision — a dtype-widening .astype() "
                 "between the quantize and the wire call re-inflates "
                 "the payload to int32/f32 while the byte accounting "
                 "still claims the compressed size (the PR 5 "
                 "int32-psum regression, and its cluster-wire twin)")

    def applies(self, ctx):
        return ("tpu_distalg/parallel/" in ctx.path
                or "tpu_distalg/cluster/" in ctx.path)

    @staticmethod
    def _is_wire_call(ctx, name: str) -> bool:
        """A call that puts its arguments on a wire: the raw jax
        collectives (parallel/ and cluster/ alike), plus — in
        cluster/ files — the transport's framing/send entry points
        under any root."""
        parts = name.split(".")
        if parts[-1] in _WIRE_OPS and parts[0] in _RAW_ROOTS:
            return True
        return ("tpu_distalg/cluster/" in ctx.path
                and parts[-1] in _CLUSTER_WIRE_OPS)

    def check(self, ctx):
        # outermost defs only: _check_function walks nested closures
        # itself (the native ring's `exchange` shape), so visiting them
        # again here would double-report every violation inside one
        nested = set()
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(fn):
                    if sub is not fn and isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        nested.add(sub)
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    or fn in nested:
                continue
            yield from self._check_function(ctx, fn)

    def _check_function(self, ctx, fn):
        # taint pass to fixpoint: names assigned from a quantize
        # expression, or from an expression that reads a tainted name
        # (the buffer may be renamed/reshaped/relayed before the wire)
        tainted: set[str] = set()
        assigns = []

        def _collect(target, value):
            if isinstance(target, ast.Name):
                assigns.append(([target.id], value))
            elif isinstance(target, (ast.Tuple, ast.List)):
                # `q, s = quantize(b), scale`: pair element-wise when
                # the shapes line up, so the sibling name is not
                # over-tainted; otherwise taint every Name in the
                # target (a starred/mismatched unpack of a quantize
                # expr still must not escape the rule)
                if isinstance(value, (ast.Tuple, ast.List)) \
                        and len(value.elts) == len(target.elts):
                    for t, v in zip(target.elts, value.elts):
                        _collect(t, v)
                else:
                    names = [n.id for n in ast.walk(target)
                             if isinstance(n, ast.Name)]
                    assigns.append((names, value))

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    _collect(t, node.value)
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name):
                assigns.append(([node.target.id], node.value))
        changed = True
        while changed:
            changed = False
            for targets, value in assigns:
                if not targets or set(targets) <= tainted:
                    continue
                reads = {n.id for n in ast.walk(value)
                         if isinstance(n, ast.Name)
                         and isinstance(n.ctx, ast.Load)}
                if _is_quantize_expr(value) or (reads & tainted):
                    tainted.update(targets)
                    changed = True

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None or not self._is_wire_call(ctx, name):
                continue
            for arg in [*node.args,
                        *(kw.value for kw in node.keywords)]:
                yield from self._widened_args(ctx, arg, tainted)

    def _widened_args(self, ctx, arg, tainted):
        """Widening .astype() on a tainted (quantized) buffer anywhere
        inside this collective argument."""
        for sub in ast.walk(arg):
            if not isinstance(sub, ast.Call) \
                    or not isinstance(sub.func, ast.Attribute) \
                    or sub.func.attr != "astype" or not sub.args:
                continue
            dt = _dtype_token(sub.args[0])
            if dt not in _WIDER_THAN_INT8:
                continue
            recv = sub.func.value
            reads = {n.id for n in ast.walk(recv)
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, ast.Load)}
            quantized = bool(reads & tainted) or _is_quantize_expr(recv)
            if quantized:
                yield self.violation(
                    ctx, sub,
                    f"quantized buffer cast to {dt} as it enters the "
                    f"collective — this re-inflates the wire payload "
                    f"the byte accounting claims is compressed "
                    f"(int8 must ride the wire natively; widen AFTER "
                    f"the exchange, like the native ring's local "
                    f"int32 accumulation)")


RULES = (RawCollectiveInModels(), WideningCastOntoWire())
