"""Comms-layer coverage — raw collectives in model code (TDA050).

PR 5 built ``tpu_distalg/parallel/comms.py`` as the single instrumented
choke point for cross-shard gradient/parameter traffic: every sync
routes through a :class:`CommSpec`-selected schedule and is accounted
in the ``comm.bytes_wire``/``bytes_logical``/``rounds`` telemetry
counters. A raw ``lax.psum`` added to a model afterwards is traffic the
knob cannot re-schedule and the counters never see — the byte
accounting rots silently as models grow. This rule keeps the choke
point exhaustive: model code calls the comms layer (``comms.psum`` /
``comms.pmean`` / a ``CommSync`` / the ``collectives`` tree wrappers),
never ``lax.psum``-family ops directly.
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import Rule, call_name

#: the raw collective-reduction ops being policed (ppermute/all_gather
#: pipelines are algorithm structure, not gradient sync — the ring
#: kernels in parallel/ own those)
_RAW_OPS = ("psum", "pmean", "psum_scatter", "pmax", "pmin")

#: call roots that mean "the raw jax op" rather than a blessed wrapper
_RAW_ROOTS = ("lax", "jax")


class RawCollectiveInModels(Rule):
    code = "TDA050"
    name = "raw cross-shard collective outside the comms layer"
    invariant = ("every cross-shard reduction in tpu_distalg/models/ "
                 "routes through parallel/comms (comms.psum, a "
                 "CommSync schedule) or the collectives tree wrappers, "
                 "so all gradient/parameter traffic stays behind the "
                 "one instrumented, --comm-schedulable choke point")

    def applies(self, ctx):
        return "tpu_distalg/models/" in ctx.path

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None or "." not in name:
                continue
            parts = name.split(".")
            if parts[-1] in _RAW_OPS and parts[0] in _RAW_ROOTS:
                yield self.violation(
                    ctx, node,
                    f"raw {name}() in model code — route the "
                    f"reduction through tpu_distalg.parallel.comms "
                    f"(comms.{parts[-1]} for a verbatim psum, or a "
                    f"CommSync for schedulable gradient sync) so the "
                    f"--comm knob and the comm.bytes_wire accounting "
                    f"cover it")


RULES = (RawCollectiveInModels(),)
