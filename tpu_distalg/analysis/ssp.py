"""Stale-synchronous discipline (TDA070).

The SSP layer's determinism and liveness contracts are structural:

  * STRAGGLE/MEMBERSHIP SCHEDULES ARE SEEDED. The bitwise-replay
    acceptance ("same plan → same trajectory") holds because every
    schedule is a pure function of the seeded fault plan
    (``ssp.compile_straggle_schedule`` / ``membership.compile_epochs``
    probe a plan-pure registry). One ad-hoc ``np.random.default_rng()``
    or ``random.Random()`` — constructed UNSEEDED — feeding a
    staleness, straggle, membership or epoch schedule voids the replay
    contract silently: the run still looks deterministic until the day
    two replays disagree. (TDA001 bans unseeded RNG in library code
    broadly; TDA070 additionally catches the seeded-module spellings
    ``np.random.rand/random/randint`` that a schedule sketch typically
    reaches for, when their product is schedule-named.)

  * NO UNBOUNDED WAITS ON THE CLOCK VECTOR. The SSP gate is
    compiled-in (a masked no-op tick); host-side coordination code
    must never spin ``while clock...:`` without a deadline — a
    departed shard's frozen clock would wedge the waiter forever, the
    exact stall class the heartbeat/Prefetcher guards exist to make
    impossible. A bounded wait names its bound: the loop's condition
    or body references a ``deadline``/``timeout``/``budget``/``max_*``
    name, or the loop carries a ``break``-with-raise shape via those.

Flagged shapes::

    sched = np.random.default_rng().integers(...)     # unseeded rng →
    straggle_plan = random.Random().random()          #   schedule name
    np.random.rand(n_ticks)  # module-global RNG feeding a schedule
    while clocks.min() < t:                           # unbounded wait
        time.sleep(0.1)

Fine::

    rng = np.random.default_rng(seed)                 # seeded
    extra = compile_straggle_schedule(T, S)           # plan-pure
    deadline = time.monotonic() + budget
    while clocks.min() < t and time.monotonic() < deadline:
        ...
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import Rule, call_name

#: RNG constructors that are unseeded exactly when called with no args
_SEEDABLE_CTORS = ("np.random.default_rng", "numpy.random.default_rng",
                   "random.Random")
#: module-global RNG draws — never seedable at the call site
_GLOBAL_DRAWS = ("np.random.rand", "np.random.random",
                 "np.random.randint", "np.random.randn",
                 "numpy.random.rand", "numpy.random.random",
                 "numpy.random.randint", "numpy.random.randn",
                 "random.random", "random.randint", "random.randrange")

#: names that mark a value as an SSP schedule product
_SCHEDULE_TOKENS = ("straggle", "stalen", "member", "epoch", "schedule")

#: names that mark a wait as bounded
_BOUND_TOKENS = ("deadline", "timeout", "budget", "max_")


def _is_schedule_name(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in _SCHEDULE_TOKENS)


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)} \
        | {n.attr for n in ast.walk(node)
           if isinstance(n, ast.Attribute)}


class SSPScheduleDiscipline(Rule):
    code = "TDA070"
    name = "unseeded SSP schedule / unbounded clock-vector wait"
    invariant = ("stale-synchronous schedules (straggle, staleness, "
                 "membership, epochs) are pure functions of the seeded "
                 "fault plan — ad-hoc unseeded RNG voids the bitwise-"
                 "replay acceptance — and no host code waits on the "
                 "clock vector without a deadline (a departed shard's "
                 "frozen clock must surface as a timeout, not a wedge)")

    def applies(self, ctx):
        return "tpu_distalg/parallel/" in ctx.path

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                yield from self._check_schedule_assign(ctx, node)
            elif isinstance(node, ast.While):
                yield from self._check_clock_wait(ctx, node)

    def _check_schedule_assign(self, ctx, node: ast.Assign):
        targets = [t.id for t in node.targets
                   if isinstance(t, ast.Name)]
        if not any(_is_schedule_name(t) for t in targets):
            return
        for sub in ast.walk(node.value):
            if not isinstance(sub, ast.Call):
                continue
            name = call_name(sub)
            if name is None:
                continue
            unseeded_ctor = (name in _SEEDABLE_CTORS
                             and not sub.args and not sub.keywords)
            if unseeded_ctor or name in _GLOBAL_DRAWS:
                yield self.violation(
                    ctx, sub,
                    f"{name}() feeding the schedule "
                    f"{'/'.join(targets)!r} is unseeded — an SSP "
                    f"straggle/membership schedule must be a pure "
                    f"function of the seeded fault plan "
                    f"(ssp.compile_straggle_schedule / "
                    f"membership.compile_epochs) or of an explicit "
                    f"seed, or the bitwise-replay contract is void")

    def _check_clock_wait(self, ctx, node: ast.While):
        cond_names = _names_in(node.test)
        if not any("clock" in n.lower() for n in cond_names):
            return
        scope = cond_names | set()
        for sub in node.body:
            scope |= _names_in(sub)
        bounded = any(
            any(tok in n.lower() for tok in _BOUND_TOKENS)
            for n in scope)
        if bounded:
            return
        yield self.violation(
            ctx, node,
            "unbounded wait on the clock vector — a departed or wedged "
            "shard's frozen clock stalls this loop forever; bound it "
            "with a deadline/timeout (and raise on expiry) or move the "
            "gate into the compiled program like ssp.make_*_train_fn's "
            "masked no-op tick")


RULES = (SSPScheduleDiscipline(),)
