"""The project graph — whole-program context for the TDA1xx rules.

The TDA0xx rules each see ONE file, and the bug classes that kept
recurring in review are exactly the ones a single file cannot show: a
carry field that never reaches the checkpoint payload two modules away,
a CLI flag the subprocess launcher forgot to forward, a counter no
report line ever renders, an attribute two thread entries in different
files write under different locks. This module parses every file on the
lint surface ONCE into a JSON-able :func:`extract_summary` (defs,
dataclass fields, imports, string-literal tables, counter emissions,
argv builders, thread-entry writes, suppression markers), assembles
them into a :class:`ProjectContext` with cross-module symbol
resolution, and hands that to :class:`ProjectRule` subclasses — the
``TDA1xx`` family — alongside the unchanged per-file pass.

Summaries are content-addressed: :func:`build_project` caches them
under ``.bench_cache/lint_graph.json`` keyed by source sha1, so
``tda lint --changed`` re-extracts only edited files while the
interprocedural rules still see the WHOLE program.

Layering: stdlib + :mod:`tpu_distalg.analysis.engine` only — same
bare-host contract as the engine (no jax, no numpy).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import time

from tpu_distalg.analysis import engine

#: bump when extract_summary's output shape OR semantics change —
#: stale cache entries from an older extractor must re-extract, not
#: half-parse (2: package-anchored module names; 3: wire-protocol
#: facts for the TDA11x family)
EXTRACT_VERSION = 3

CACHE_NAME = "lint_graph.json"


def module_name(path: str) -> str:
    """Dotted module spelling of a repo-relative path:
    ``tpu_distalg/cluster/local.py`` → ``tpu_distalg.cluster.local``,
    package ``__init__.py`` collapses onto the package. A
    SUBDIRECTORY invocation (``cd tpu_distalg && tda lint analysis``)
    prepends the enclosing package dirs above the cwd, so the name
    still matches absolute-import spellings and cross-module
    resolution does not silently degrade."""
    p = engine.norm_path(path)
    base = p[:-3] if p.endswith(".py") else p
    parts = [seg for seg in base.split("/") if seg not in (".", "")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if not os.path.isabs(p):
        d = os.getcwd()
        while os.path.isfile(os.path.join(d, "__init__.py")):
            parts.insert(0, os.path.basename(d))
            d = os.path.dirname(d)
    return ".".join(parts)


# ---------------------------------------------------------------------
# summary extraction (everything below must stay JSON-serializable)


def _str_consts(node) -> list:
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def _args_dests(node) -> set:
    """argparse dests read as ``args.<dest>`` anywhere under node."""
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Name) and n.value.id == "args":
            out.add(n.attr)
    return out


def _joined_prefix(node: ast.JoinedStr) -> str:
    """The leading constant text of an f-string (empty when it starts
    with a formatted value)."""
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            break
    return "".join(parts)


def _joined_pattern(node: ast.JoinedStr) -> str:
    """Regex matching every instantiation of an f-string name (the
    bench tripwire's template shape)."""
    import re as _re

    return "^" + "".join(
        _re.escape(v.value)
        if isinstance(v, ast.Constant) else ".+"
        for v in node.values) + "$"


def _is_dataclass_def(node: ast.ClassDef) -> bool:
    for d in node.decorator_list:
        target = d.func if isinstance(d, ast.Call) else d
        name = engine.dotted_name(target)
        if name is not None and name.rsplit(".", 1)[-1] == "dataclass":
            return True
    return False


def _fn_locals(fn) -> set:
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            out.update(t.id for t in node.targets
                       if isinstance(t, ast.Name))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
            if isinstance(node.target, ast.Name):
                out.add(node.target.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(node.name)
    args = getattr(fn, "args", None)
    if args is not None:
        out.update(a.arg for a in args.args + args.kwonlyargs)
    return out


def _walk_functions(tree):
    """(qualname, class_name_or_None, node) for every function def,
    depth-first, qualified like ``Class.method``."""
    def rec(node, qual, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                yield q, cls, child
                yield from rec(child, q, cls)
            elif isinstance(child, ast.ClassDef):
                q = f"{qual}.{child.name}" if qual else child.name
                yield from rec(child, q, child.name)
            else:
                yield from rec(child, qual, cls)
    yield from rec(tree, "", None)


def _lock_segments(expr) -> set:
    """Lower-cased name segments containing 'lock' in a with-item —
    the cross-module spelling of concurrency._lockish."""
    out = set()
    for leaf in ast.walk(expr):
        seg = None
        if isinstance(leaf, ast.Name):
            seg = leaf.id
        elif isinstance(leaf, ast.Attribute):
            seg = leaf.attr
        if seg is not None and "lock" in seg.lower():
            out.add(seg.lower())
    return out


def _thread_entries(tree):
    """(class_name_or_None, function_node, how) triples that run ON a
    thread — Thread(target=name), Thread(target=self.meth), and
    ``run`` methods of Thread subclasses — resolved project-file-wide
    (the concurrency.py walker, grown method targets)."""
    plain_targets = set()
    method_targets = set()   # (class, method) via target=self.meth
    for qual, cls, fn in _walk_functions(tree):
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and (engine.call_name(node) or "").rsplit(
                        ".", 1)[-1] == "Thread"):
                continue
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                if isinstance(kw.value, ast.Name):
                    plain_targets.add(kw.value.id)
                elif isinstance(kw.value, ast.Attribute) and \
                        isinstance(kw.value.value, ast.Name) and \
                        kw.value.value.id == "self" and cls:
                    method_targets.add((cls, kw.value.attr))
    for qual, cls, fn in _walk_functions(tree):
        if cls is None and fn.name in plain_targets:
            yield None, fn, f"Thread target {fn.name}"
        elif cls is not None and (cls, fn.name) in method_targets:
            yield cls, fn, f"Thread target {cls}.{fn.name}"
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and any(
                (engine.dotted_name(b) or "").rsplit(".", 1)[-1]
                == "Thread" for b in node.bases):
            for item in node.body:
                if isinstance(item, ast.FunctionDef) \
                        and item.name == "run":
                    yield node.name, item, f"{node.name}.run"


def _scan_thread_writes(cls, fn, how, out):
    local = _fn_locals(fn)

    def rec(node, held):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            now = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                segs = set()
                for item in child.items:
                    segs |= _lock_segments(item.context_expr)
                if segs:
                    now = held | segs
            if isinstance(child, (ast.Assign, ast.AugAssign)):
                targets = (child.targets
                           if isinstance(child, ast.Assign)
                           else [child.target])
                for t in targets:
                    if not isinstance(t, ast.Attribute):
                        continue
                    root = engine.root_name(t)
                    if root is None or (root in local
                                        and root != "self"):
                        continue
                    out.append({
                        "entry": how, "cls": cls, "attr": t.attr,
                        "self": root == "self",
                        "locks": sorted(now), "line": t.lineno})
            rec(child, now)
    rec(fn, frozenset())


def extract_summary(source: str, path: str) -> dict:
    """One file's project-graph contribution. Raises ``SyntaxError``
    for unparseable sources (callers record an ``error`` stub; the
    per-file pass owns the TDA000)."""
    return summarize_context(engine.make_context(source, path))


def summarize_context(ctx: "engine.LintContext") -> dict:
    """The extraction itself, from an already-parsed context —
    ``lint_tree`` hands its per-file contexts in so a cold-cache run
    parses each file once, not twice."""
    tree = ctx.tree
    mod = module_name(ctx.path)
    pkg_parts = mod.split(".")
    # module_name already collapsed __init__ onto its package, so a
    # package module strips one level FEWER for relative imports
    # (level=1 inside a package __init__ means the package itself)
    is_pkg = ctx.path.endswith("/__init__.py") \
        or ctx.path == "__init__.py"

    imports: dict = {}
    import_modules: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    imports[alias.asname] = alias.name
                else:
                    imports[alias.name.split(".")[0]] = \
                        alias.name.split(".")[0]
                import_modules.add(alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # relative import: resolved against this module's
                # package (one level strips the module itself —
                # except in a package __init__, whose dotted name IS
                # the package)
                strip = node.level - 1 if is_pkg else node.level
                base = pkg_parts[:len(pkg_parts) - strip] \
                    if strip else list(pkg_parts)
                base += (node.module or "").split(".") \
                    if node.module else []
                base_mod = ".".join(p for p in base if p)
            else:
                base_mod = node.module or ""
            if base_mod:
                import_modules.add(base_mod)
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                imports[local] = f"{base_mod}.{alias.name}" \
                    if base_mod else alias.name
                import_modules.add(f"{base_mod}.{alias.name}"
                                   if base_mod else alias.name)

    str_tuples: dict = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, (ast.Tuple, ast.List,
                                            ast.Set)):
            elts = stmt.value.elts
            if elts and all(isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                            for e in elts):
                str_tuples[stmt.targets[0].id] = {
                    "values": [e.value for e in elts],
                    "line": stmt.lineno}

    dclasses: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and _is_dataclass_def(node):
            fields = {}
            for item in node.body:
                if isinstance(item, ast.AnnAssign) \
                        and isinstance(item.target, ast.Name):
                    fields[item.target.id] = item.lineno
            dclasses[node.name] = {"line": node.lineno,
                                   "fields": fields}

    attr_writes: list = []
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Attribute):
                attr_writes.append([t.attr, t.lineno])

    payload_builders: list = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        pairs = [(k.value, v) for k, v in zip(node.keys, node.values)
                 if isinstance(k, ast.Constant)
                 and isinstance(k.value, str) and v is not None]
        if len(pairs) < 2:
            continue
        matched = [k for k, v in pairs
                   if any(isinstance(n, ast.Attribute) and n.attr == k
                          for n in ast.walk(v))]
        if len(matched) >= 2:
            payload_builders.append({
                "keys": [k for k, _ in pairs], "matched": matched,
                "line": node.lineno,
                "end_line": node.end_lineno or node.lineno})

    counter_emits: list = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        name = engine.call_name(node)
        kind = (name or "").rsplit(".", 1)[-1]
        if kind not in ("counter", "gauge"):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                        str):
            counter_emits.append({"kind": kind, "name": arg.value,
                                  "prefix": None,
                                  "line": node.lineno})
        elif isinstance(arg, ast.JoinedStr):
            prefix = _joined_prefix(arg)
            if prefix:
                counter_emits.append({"kind": kind, "name": None,
                                      "prefix": prefix,
                                      "line": node.lineno})

    metric_dicts: list = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant)
                    and k.value == "metric"):
                continue
            if isinstance(v, ast.Constant) and isinstance(v.value,
                                                          str):
                metric_dicts.append({"name": v.value,
                                     "pattern": None,
                                     "line": node.lineno})
            elif isinstance(v, ast.JoinedStr):
                metric_dicts.append({"name": None,
                                     "pattern": _joined_pattern(v),
                                     "line": node.lineno})

    argparse_flags: dict = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument" and node.args):
            continue
        arg0 = node.args[0]
        if not (isinstance(arg0, ast.Constant)
                and isinstance(arg0.value, str)
                and arg0.value.startswith("--")):
            continue
        dest = arg0.value[2:].replace("-", "_")
        for kw in node.keywords:
            if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                dest = kw.value.value
        argparse_flags.setdefault(dest, [])
        if arg0.value not in argparse_flags[dest]:
            argparse_flags[dest].append(arg0.value)

    config_calls: list = []
    for qual, cls, fn in _walk_functions(tree):
        # one-level local dataflow, in line order: `spec =
        # SyncSpec.parse(args.sync)` makes `spec` carry dest 'sync'
        local_dests: dict = {}
        assigns = sorted(
            (n for n in ast.walk(fn) if isinstance(n, ast.Assign)
             and len(n.targets) == 1
             and isinstance(n.targets[0], ast.Name)),
            key=lambda n: n.lineno)
        for a in assigns:
            dests = set(_args_dests(a.value))
            for n in ast.walk(a.value):
                if isinstance(n, ast.Name) and n.id in local_dests:
                    dests |= local_dests[n.id]
            if dests:
                local_dests[a.targets[0].id] = dests
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and node.keywords):
                continue
            cname = (engine.call_name(node) or "").rsplit(".", 1)[-1]
            if not cname.endswith("Config"):
                continue
            fields = {}
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                dests = set(_args_dests(kw.value))
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Name) \
                            and n.id in local_dests:
                        dests |= local_dests[n.id]
                if dests:
                    fields[kw.arg] = sorted(dests)
            if fields:
                config_calls.append({"config": cname,
                                     "fields": fields,
                                     "line": node.lineno})

    spawners: list = []
    for qual, cls, fn in _walk_functions(tree):
        consts = _str_consts(fn)
        if "-m" not in consts or not any(".cli" in c or c == "cli"
                                         for c in consts):
            continue
        configs = []
        for a in fn.args.args + fn.args.kwonlyargs:
            ann = a.annotation
            if ann is None:
                continue
            name = engine.dotted_name(ann) or (
                ann.value if isinstance(ann, ast.Constant)
                and isinstance(ann.value, str) else None)
            if name is not None and \
                    name.rsplit(".", 1)[-1].endswith("Config"):
                configs.append(name.rsplit(".", 1)[-1])
        if configs:
            spawners.append({
                "func": qual, "line": fn.lineno,
                "flags": sorted({c for c in consts
                                 if c.startswith("--")}),
                "configs": configs})

    thread_writes: list = []
    for cls, fn, how in _thread_entries(tree):
        _scan_thread_writes(cls, fn, how, thread_writes)

    report_like = any(
        isinstance(n, ast.FunctionDef)
        and n.name in ("render", "summarize") for n in tree.body) \
        or "SUMMARY_ONLY_COUNTERS" in str_tuples \
        or "PER_WORKER_PREFIXES" in str_tuples
    report_strings = sorted({s for s in _str_consts(tree)
                             if len(s) <= 80}) if report_like else []

    # late import: protocol.py builds ON the project graph (ProjectRule
    # base, _walk_functions) while its extractor feeds the summaries
    from tpu_distalg.analysis import protocol as _protocol

    return {
        "version": EXTRACT_VERSION,
        "path": ctx.path,
        "module": mod,
        "is_test": ctx.is_test,
        "is_library": ctx.is_library,
        "imports": imports,
        "import_modules": sorted(import_modules),
        "str_tuples": str_tuples,
        "dataclasses": dclasses,
        "attr_writes": attr_writes,
        "payload_builders": payload_builders,
        "counter_emits": counter_emits,
        "metric_dicts": metric_dicts,
        "argparse_flags": argparse_flags,
        "config_calls": config_calls,
        "spawners": spawners,
        "thread_writes": thread_writes,
        "report_like": report_like,
        "report_strings": report_strings,
        "protocol": _protocol.extract_protocol(tree, imports),
        "suppressions": [
            # tda: ignore[TDA100] -- `used` is per-run matching state
            # (which findings a pin absorbed THIS run), not part of
            # the durable marker; persisting it would be wrong
            {"line": s.line, "comment_line": s.comment_line,
             "codes": sorted(s.codes), "reason": s.reason}
            for s in ctx.markers.suppressions],
    }


# ---------------------------------------------------------------------
# the assembled graph


class ProjectContext:
    """Every summary, indexed by path and dotted module, plus the
    cross-module resolution helpers rules lean on. ``lines(path)``
    lazily (re)reads sources so cached summaries can still mint
    fingerprint snippets."""

    def __init__(self, summaries: dict):
        self.summaries = summaries          # norm path -> summary
        self.by_module = {s["module"]: s for s in summaries.values()
                          if "error" not in s}
        self._lines: dict = {}

    def __iter__(self):
        for path in sorted(self.summaries):
            s = self.summaries[path]
            if "error" not in s:
                yield s

    def library(self):
        """Non-test summaries — where the interprocedural contracts
        live (tests may emit fixture counters, spawn fixture threads)."""
        return (s for s in self if not s["is_test"])

    def lines(self, path: str) -> list:
        if path not in self._lines:
            try:
                with open(path, encoding="utf-8") as f:
                    self._lines[path] = f.read().splitlines()
            except OSError:
                self._lines[path] = []
        return self._lines[path]

    def snippet(self, path: str, line: int) -> str:
        lines = self.lines(path)
        return lines[line - 1].strip() if 1 <= line <= len(lines) \
            else ""

    def resolve_symbol(self, mod: str, sym: str, _depth: int = 0):
        """Follow re-export chains: ``(defining_summary, kind, info)``
        for a dataclass named ``sym`` importable from ``mod``, else
        None. One deliberate limit (documented in ARCHITECTURE): no
        dynamic dispatch, no decorator factories — a symbol is only
        resolved through literal ``import``/``from-import`` spellings."""
        if _depth > 5:
            return None
        s = self.by_module.get(mod)
        if s is None:
            return None
        if sym in s["dataclasses"]:
            return s, "dataclass", s["dataclasses"][sym]
        target = s["imports"].get(sym)
        if target and "." in target:
            m2, s2 = target.rsplit(".", 1)
            return self.resolve_symbol(m2, s2, _depth + 1)
        return None

    def visible_dataclasses(self, summary: dict):
        """(class_name, defining_summary, info) visible from a module:
        defined locally, imported by name, or reachable as an
        attribute of an imported module."""
        seen = {}
        for name, info in summary["dataclasses"].items():
            seen[name] = (summary, info)
        for local, target in summary["imports"].items():
            if target in self.by_module:
                for name, info in \
                        self.by_module[target]["dataclasses"].items():
                    seen.setdefault(name, (self.by_module[target],
                                           info))
            elif "." in target:
                m2, s2 = target.rsplit(".", 1)
                hit = self.resolve_symbol(m2, s2)
                if hit is not None:
                    seen.setdefault(s2, (hit[0], hit[2]))
        return [(name, s, info) for name, (s, info) in seen.items()]

    def connected(self, mod_a: str, mod_b: str) -> bool:
        """Modules share an import edge (either direction)."""
        a = self.by_module.get(mod_a)
        b = self.by_module.get(mod_b)
        if a is None or b is None:
            return False
        return mod_b in a["import_modules"] \
            or mod_a in b["import_modules"] \
            or any(t.startswith(mod_b + ".")
                   for t in a["import_modules"]) \
            or any(t.startswith(mod_a + ".")
                   for t in b["import_modules"])

    def suppressions_for(self, path: str):
        s = self.summaries.get(path)
        if s is None or "error" in s:
            return []
        return [engine.Suppression(
            line=d["line"], comment_line=d["comment_line"],
            codes=frozenset(d["codes"]), reason=d["reason"])
            for d in s["suppressions"]]


class ProjectRule(engine.Rule):
    """A rule that sees the whole program. ``check`` (the per-file
    hook) is a no-op; subclasses implement :meth:`check_project`."""

    def check(self, ctx):
        return ()

    def check_project(self, project: ProjectContext):
        raise NotImplementedError

    def project_violation(self, project, path, line, message,
                          end_line: int = 0):
        return engine.Violation(
            code=self.code, message=message, path=path, line=line,
            col=0, snippet=project.snippet(path, line),
            end_line=end_line or line)


# ---------------------------------------------------------------------
# content-hash cache + builder


def _load_cache(cache_path: str) -> dict:
    try:
        with open(cache_path, encoding="utf-8") as f:
            doc = json.load(f)
        if doc.get("version") == EXTRACT_VERSION:
            return doc.get("files", {})
    except (OSError, ValueError):
        pass
    return {}


def _save_cache(cache_path: str, files: dict) -> None:
    tmp = f"{cache_path}.{os.getpid()}.tmp"
    try:
        os.makedirs(os.path.dirname(cache_path), exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": EXTRACT_VERSION, "files": files}, f)
        os.replace(tmp, cache_path)
    except OSError:
        # cache is a luxury: an unwritable dir must not fail the lint
        try:
            os.unlink(tmp)
        except OSError:
            pass


def build_project(files, *, cache_dir: str | None = None,
                  sources: dict | None = None,
                  contexts: dict | None = None):
    """Extract every file (cache hits skipped), assemble the graph.
    Returns ``(ProjectContext, n_cached)``. ``sources``/``contexts``
    (norm_path-keyed) let the orchestrator share its per-file reads
    and parses so a cold-cache run does each once."""
    sources = sources or {}
    contexts = contexts or {}
    cache_path = os.path.join(cache_dir, CACHE_NAME) \
        if cache_dir else None
    old = _load_cache(cache_path) if cache_path else {}
    # a subset invocation must not evict the rest of the surface from
    # the shared cache — carry forward entries for files still on disk
    new_cache: dict = {p: e for p, e in old.items()
                       if os.path.exists(p)}
    summaries: dict = {}
    n_cached = 0
    for path in files:
        p = engine.norm_path(path)
        source = sources.get(p)
        if source is None:
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
            except OSError as e:
                summaries[p] = {"path": p, "module": module_name(p),
                                "error": str(e)}
                continue
        sha = hashlib.sha1(source.encode()).hexdigest()
        ent = old.get(p)
        if ent and ent.get("sha") == sha:
            summaries[p] = ent["summary"]
            n_cached += 1
        elif p in contexts:
            summaries[p] = summarize_context(contexts[p])
        else:
            try:
                summaries[p] = extract_summary(source, path)
            except SyntaxError as e:
                # the per-file pass reports the TDA000; the graph
                # just records the hole so rules skip it
                summaries[p] = {"path": p, "module": module_name(p),
                                "error": f"syntax: {e.msg}"}
        new_cache[p] = {"sha": sha, "summary": summaries[p]}
    if cache_path:
        _save_cache(cache_path, new_cache)
    return ProjectContext(summaries), n_cached


# ---------------------------------------------------------------------
# the whole-tree orchestrator (per-file pass + project pass + shared
# suppression accounting)


@dataclasses.dataclass
class LintResult:
    violations: list
    n_files: int        # project-graph surface
    n_linted: int       # files the per-file pass ran on
    n_cached: int       # graph summaries served from cache
    graph_seconds: float


def lint_tree(files, rules, project_rules, *, select=None, ignore=None,
              changed_only=None, cache_dir: str | None = None
              ) -> LintResult:
    """Lint ``files``: per-file TDA0xx rules over every file (or just
    ``changed_only`` paths when given — the ``--changed`` incremental
    mode), the TDA1xx project pass over the FULL surface, suppressions
    applied once across both so a pin consumed by either pass counts
    as used — and, on unfiltered runs, unused reasoned suppressions
    reported like stale baseline entries."""
    known = {r.code for r in tuple(rules) + tuple(project_rules)}
    active = engine._select(rules, select, ignore, known=known)
    active_project = engine._select(project_rules, select, ignore,
                                    known=known)
    tda000 = (not select or "TDA000" in select) and \
        (not ignore or "TDA000" not in ignore)

    per_file = list(files) if changed_only is None else [
        f for f in files if engine.norm_path(f) in changed_only]

    # read + parse the per-file targets ONCE; build_project reuses
    # these contexts for its cache misses instead of re-parsing
    sources: dict = {}
    contexts: dict = {}
    extra: list = []          # TDA000 findings minted here
    for path in per_file:
        p = engine.norm_path(path)
        with open(path, encoding="utf-8") as f:
            sources[p] = f.read()
        try:
            contexts[p] = engine.make_context(sources[p], path)
        except SyntaxError as e:
            if tda000:
                extra.append(engine.syntax_violation(path, e))

    t0 = time.monotonic()
    project, n_cached = (build_project(files, cache_dir=cache_dir,
                                       sources=sources,
                                       contexts=contexts)
                         if active_project
                         else (ProjectContext({}), 0))
    graph_seconds = time.monotonic() - t0

    found_by_path: dict = {}
    markers_by_path: dict = {}
    linted: set = set()
    for p in sorted(contexts):
        ctx = contexts[p]
        linted.add(ctx.path)
        markers_by_path[ctx.path] = ctx.markers
        bucket = found_by_path.setdefault(ctx.path, [])
        for rule in active:
            if rule.applies(ctx):
                bucket.extend(rule.check(ctx))
        if tda000:
            extra.extend(engine.marker_violations(ctx))

    for rule in active_project:
        for v in rule.check_project(project):
            found_by_path.setdefault(v.path, []).append(v)

    kept: list = list(extra)
    for path, found in found_by_path.items():
        markers = markers_by_path.get(path)
        supps = (markers.suppressions if markers is not None
                 else project.suppressions_for(path))
        kept.extend(engine.apply_suppressions(found, supps))

    # unused reasoned pins: only meaningful when every rule ran over
    # the file (a --select/--ignore run would misread filtered-out
    # findings as rot)
    if tda000 and not select and not ignore:
        for path in sorted(linted):
            markers = markers_by_path[path]
            for s in markers.suppressions:
                if s.reason and not s.used:
                    kept.append(engine.Violation(
                        code="TDA000", path=path,
                        line=s.comment_line, col=0,
                        message=(
                            f"suppression "
                            f"[{', '.join(sorted(s.codes))}] "
                            f"suppresses no findings — the pinned "
                            f"violation is gone; remove the comment "
                            f"(`tda lint --fix` does) so dead pins "
                            f"cannot mask a future regression"),
                        snippet=project.snippet(path, s.comment_line)
                        or _line_of(path, s.comment_line)))
    kept.sort(key=lambda v: (v.path, v.line, v.col, v.code))
    return LintResult(violations=kept, n_files=len(list(files)),
                      n_linted=len(linted), n_cached=n_cached,
                      graph_seconds=round(graph_seconds, 3))


def _line_of(path: str, line: int) -> str:
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
        return lines[line - 1].strip() if 1 <= line <= len(lines) \
            else ""
    except OSError:
        return ""
