"""``tda lint`` — static analysis for the framework's own invariants.

AST-based rules (``TDA0xx`` codes), each policing a guarantee another
subsystem makes:

==========  =========================================================
TDA001      no wall clock / unseeded RNG in library code (bitwise
            replay, PR 3)
TDA002      no unordered (set/listdir/glob) iteration feeding
            downstream order (collective + serialization order)
TDA010      no Python side effects inside jit/shard_map/pallas_call
            bodies (trace purity)
TDA011      no host syncs inside step loops (``# tda: hot-loop`` or
            step-named ``range`` loops)
TDA020      thread-target writes to shared state hold a lock
            (telemetry/prefetch thread conventions, PR 1)
TDA021      every ``threading.Thread`` states ``daemon=`` explicitly
TDA030      durable writes in ``tpu_distalg/`` route through a
            ``faults.inject`` seam (chaos coverage, PR 3)
TDA040      Pallas ``BlockSpec`` shapes tile in (8, 128) for f32
TDA041      statically-sized resident blocks fit the VMEM budget
TDA050      no raw ``lax.psum``-family collectives in
            ``tpu_distalg/models/`` — gradient traffic stays behind
            the instrumented comms layer (``parallel/comms.py``, PR 5)
TDA051      no dtype-widening cast on a quantized buffer as it enters
            a collective in ``tpu_distalg/parallel/`` — compressed
            payloads ride the wire natively (the int32-psum wire
            PR 5 documented and round 11 removed stays removed)
TDA060      no unbounded ``queue.Queue()`` and no blocking ``get()``
            without a timeout in ``tpu_distalg/serve/`` — the serving
            layer sheds under overload and always observes its stop
            flag (liveness discipline, the Prefetcher guard's shape)
TDA070      SSP discipline in ``tpu_distalg/parallel/``: no unseeded
            RNG feeding a staleness/straggle/membership/epoch
            schedule (the bitwise-replay contract of the
            stale-synchronous layer), and no unbounded host-side wait
            on the clock vector (a departed shard's frozen clock must
            time out, not wedge)
TDA080      no raw ``NamedSharding``/placement-spec construction or
            ``device_put`` with a hand-built layout in
            ``tpu_distalg/models/`` / ``tpu_distalg/serve/`` — every
            placement routes through the partition-rule engine
            (``parallel/partition.py`` rule tables, PR 11)
TDA090      cluster transport discipline in ``tpu_distalg/cluster/``:
            no blocking socket receive/accept without a deadline
            armed in scope (a partition must surface as
            ``TransportTimeout``, never a wedged thread) and no
            ``sendall`` of a payload the frame encoder did not build
            (an unframed write desynchronizes the length-prefixed
            stream)
==========  =========================================================

The ``TDA1xx`` family runs over the PROJECT GRAPH
(:mod:`tpu_distalg.analysis.project` — one parse of the whole lint
surface into cross-module symbol/flow summaries) instead of one file
at a time; each rule pins a bug class review caught across PR 9–13:

==========  =========================================================
TDA100      checkpoint-carry completeness: a state-container field
            mutated across steps must reach its checkpoint/snapshot
            payload builder (the topk EF-residual class)
TDA101      subprocess config handoff: every config field the CLI
            feeds from a flag is forwarded by the argv builder that
            re-spawns the role (the ``--train-json`` class)
TDA102      telemetry contract: every emitted counter/gauge is
            rendered or waived in ``telemetry/report.py``, and bench
            metric lines stay bijective with ``ALL_METRIC_NAMES``
            (the test-only AST tripwire, promoted into the engine)
TDA103      cross-module lock discipline: an attribute written from
            thread entries in different modules needs ONE common
            lock, not one lock per module (the gap TDA020's
            single-file view cannot see)
TDA110      wire-contract bijectivity: every frame kind some peer
            sends has a dispatch branch somewhere, and every dispatch
            branch matches a kind something sends (dead kinds rot
            into silent drops)
TDA111      payload-key contract: a meta key any decoder of kind K
            reads without a default is written by EVERY resolvable
            encoder of K (the cross-process latent-KeyError class)
TDA112      request/reply pairing: a round trip's accepted reply
            kinds are kinds some handler of K actually sends, and an
            ``error``-kind reply is explicitly handled (the PR 13
            "dying coordinator answers" class)
TDA113      incarnation-fencing completeness: every resolvable
            encoder of a fenced frame kind populates the ``inc``
            token (the PR 13 round-2 zombie class)
TDA114      WAL-before-ack at protocol scope: in any handler that
            both appends a record and sends a frame, the append
            dominates the send on every branch path (TDA091
            generalized beyond fsync syntax)
TDA120      geometry-literal discipline (per-file, against the tuner
            tables): a geometry knob (bucket elems, shard counts,
            block sizes, pull-refresh cadence) pinned to an int
            literal in ``tpu_distalg/models/`` or
            ``tpu_distalg/cluster/`` must carry a value
            ``tune/defaults.py`` spells, or a reasoned rig-pin — the
            autotuner's resolver owns everything else
==========  =========================================================

The TDA11x rows run over the protocol graph — the wire-contract slice
of the same project graph; ``tda protocol`` renders that contract as
a table and ``--check`` pins it against ``docs/PROTOCOL.md``.

Suppress a finding with ``# tda: ignore[TDA0xx] -- reason`` (the reason
is mandatory); grandfather existing debt with ``lint_baseline.json``.
A reasoned suppression that suppresses NOTHING is itself reported
(like a stale baseline entry) and ``--fix`` removes it. Run via
``tda lint [paths] [--format json] [--baseline FILE] [--select/
--ignore CODES] [--changed] [--fix]``. Stdlib + telemetry only — no
jax.
"""

from tpu_distalg.analysis import baseline
from tpu_distalg.analysis.carry import RULES as _CARRY
from tpu_distalg.analysis.cluster import RULES as _CLUSTER
from tpu_distalg.analysis.comms import RULES as _COMMS
from tpu_distalg.analysis.concurrency import RULES as _CONCURRENCY
from tpu_distalg.analysis.crosslock import RULES as _CROSSLOCK
from tpu_distalg.analysis.determinism import RULES as _DETERMINISM
from tpu_distalg.analysis.engine import (
    Rule,
    Violation,
    iter_python_files,
    lint_file,
    lint_source,
)
from tpu_distalg.analysis.handoff import RULES as _HANDOFF
from tpu_distalg.analysis.pallas import RULES as _PALLAS
from tpu_distalg.analysis.partition import RULES as _PARTITION
from tpu_distalg.analysis.project import (
    ProjectContext,
    ProjectRule,
    build_project,
    lint_tree,
)
from tpu_distalg.analysis.protocol import RULES as _PROTOCOL
from tpu_distalg.analysis.seams import RULES as _SEAMS
from tpu_distalg.analysis.serve import RULES as _SERVE
from tpu_distalg.analysis.ssp import RULES as _SSP
from tpu_distalg.analysis.telemetry_contract import (
    RULES as _TELEMETRY_CONTRACT,
)
from tpu_distalg.analysis.tracing import RULES as _TRACING
from tpu_distalg.analysis.tune import RULES as _TUNE

#: every shipped per-file rule, in code order
RULES = tuple(sorted(
    _DETERMINISM + _TRACING + _CONCURRENCY + _SEAMS + _PALLAS + _COMMS
    + _SERVE + _SSP + _PARTITION + _CLUSTER + _TUNE,
    key=lambda r: r.code))

#: the interprocedural family — runs once over the project graph
PROJECT_RULES = tuple(sorted(
    _CARRY + _HANDOFF + _TELEMETRY_CONTRACT + _CROSSLOCK + _PROTOCOL,
    key=lambda r: r.code))

__all__ = [
    "PROJECT_RULES",
    "ProjectContext",
    "ProjectRule",
    "RULES",
    "Rule",
    "Violation",
    "baseline",
    "build_project",
    "iter_python_files",
    "lint_file",
    "lint_source",
    "lint_tree",
]
