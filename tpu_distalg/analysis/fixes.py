"""``tda lint --fix`` — the mechanically-safe subset.

Only fixes whose behavior-preservation is decidable from the text are
applied:

  * TDA021: insert ``daemon=False`` into a ``threading.Thread(...)``
    call — False IS the inherited default, so the edit changes nothing
    but makes the lifetime reviewable (pick True by hand where a
    watcher thread must not block exit);
  * TDA000 (reasonless suppression): scaffold the required reason slot
    (``-- TODO: justify this suppression``). The scaffolded TODO counts
    as reason text, so the suppression takes effect immediately — but
    the TODO is grep-able and marks it for review.
  * TDA000 (unused suppression): a reasoned pin that suppresses zero
    findings is dead weight that could mask a future regression —
    remove the comment (the whole line when it stood alone, just the
    trailing comment otherwise). Nothing was being suppressed, so the
    removal cannot surface new findings.
  * TDA102 (stale waiver): a ``SUMMARY_ONLY_COUNTERS`` entry matching
    zero emitted counters is the waiver-table spelling of an unused
    suppression — delete the entry's line (the table keeps one entry
    per line). It waived nothing, so nothing new can fire.

Everything else (hoisting a host sync, adding a lock, routing a write
through a seam) changes semantics and stays a human's job.
"""

from __future__ import annotations

import ast
import re

from tpu_distalg.analysis.concurrency import _is_thread_call

_IGNORE_BARE_RE = re.compile(r"(tda:\s*ignore\[[A-Z0-9,\s]+\])\s*$")
_IGNORE_COMMENT_RE = re.compile(
    r"\s*#\s*tda:\s*ignore\[[A-Z0-9,\s]*\].*$")
_STALE_WAIVER_RE = re.compile(r"waiver '([^']+)' in \w+ matches no")

TODO_REASON = "TODO: justify this suppression"


def fix_file(path: str, violations) -> int:
    """Apply safe fixes for ``violations`` (all within ``path``).
    Returns the number of edits written."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    fixed_source, n = fix_source(source, violations)
    if n:
        with open(path, "w", encoding="utf-8") as f:
            f.write(fixed_source)
    return n


def _last_code_char(lines, end_line: int, end_col: int) -> str:
    """The last non-whitespace character strictly before position
    (end_line, end_col), scanning backwards across lines."""
    col = end_col
    for idx in range(end_line, -1, -1):
        chunk = lines[idx][:col] if col is not None else lines[idx]
        stripped = chunk.rstrip()
        if stripped:
            return stripped[-1]
        col = None
    return ""


def fix_source(source: str, violations) -> tuple[str, int]:
    lines = source.splitlines(keepends=True)
    tree = ast.parse(source)
    edits = []  # (line_idx, fn) applied bottom-up

    daemon_lines = {v.line for v in violations if v.code == "TDA021"}
    if daemon_lines:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and node.lineno in daemon_lines:
                if not _is_thread_call(node):
                    continue
                end_line = node.end_lineno - 1
                end_col = node.end_col_offset - 1  # the ')'
                # the last code char before the ')' decides the
                # separator: a trailing comma (multi-line call) or the
                # bare '(' (no args) must not gain a second comma
                last = _last_code_char(lines, end_line, end_col)
                sep = "" if last in (",", "(") else ", "
                edits.append((end_line, lambda s, c=end_col, p=sep:
                              s[:c] + f"{p}daemon=False" + s[c:]))

    for v in violations:
        if v.code == "TDA000" and "without a reason" in v.message:
            idx = v.line - 1

            def scaffold(s):
                return _IGNORE_BARE_RE.sub(
                    lambda m: f"{m.group(1)} -- {TODO_REASON}",
                    s.rstrip("\n")) + ("\n" if s.endswith("\n")
                                       else "")
            if _IGNORE_BARE_RE.search(lines[idx].rstrip("\n")):
                edits.append((idx, scaffold))
        elif v.code == "TDA000" and \
                "suppresses no findings" in v.message:
            idx = v.line - 1
            if idx >= len(lines):
                continue
            stripped = lines[idx].strip()

            def drop(s):
                if s.strip().startswith("#"):
                    return ""          # an own-line pin: delete it
                out = _IGNORE_COMMENT_RE.sub("", s.rstrip("\n"))
                return out + ("\n" if s.endswith("\n") else "")
            if stripped.startswith("#") or \
                    _IGNORE_COMMENT_RE.search(lines[idx]):
                edits.append((idx, drop))
                if not stripped.startswith("#"):
                    continue
                # an own-line pin's reason often wraps onto following
                # comment lines at the same indent — they are part of
                # the pin, not standalone prose; delete them too
                # (stop at code, a blank line, a different indent, or
                # a new tda: marker; trailing pins are left alone — a
                # comment under one is usually unrelated)
                indent = lines[idx][:len(lines[idx])
                                    - len(lines[idx].lstrip())]
                j = idx + 1
                while j < len(lines) \
                        and "tda:" not in lines[j] \
                        and lines[j].startswith(indent + "#"):
                    edits.append((j, lambda s: ""))
                    j += 1

    for v in violations:
        m = _STALE_WAIVER_RE.search(v.message) \
            if v.code == "TDA102" else None
        if m is None:
            continue
        entry = m.group(1)
        # v.line anchors at the waiver TUPLE's assignment; the entry
        # itself sits on its own line below (the table's committed
        # style). Scan to the tuple's close for the quoted entry and
        # drop that line, plus any continuation comment lines riding
        # under it.
        for j in range(v.line - 1, min(v.line + 200, len(lines))):
            text = lines[j]
            if f'"{entry}"' not in text and f"'{entry}'" not in text:
                if j > v.line - 1 and text.strip().startswith(")"):
                    break
                continue
            edits.append((j, lambda s: ""))
            k = j + 1
            while k < len(lines) \
                    and lines[k].lstrip().startswith("#"):
                edits.append((k, lambda s: ""))
                k += 1
            break

    n = 0
    for idx, fn in sorted(edits, key=lambda e: -e[0]):
        new = fn(lines[idx])
        if new != lines[idx]:
            lines[idx] = new
            n += 1
    return "".join(lines), n
