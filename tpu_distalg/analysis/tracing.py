"""Trace-purity rules — jit bodies and hot step loops (TDA010, TDA011).

DrJAX-style MapReduce-in-JAX work (PAPERS.md) identifies trace-purity
mistakes as the dominant correctness hazard in JAX frameworks: a
``print`` or telemetry emit inside a ``jit``-decorated function runs
ONCE at trace time (then never again — the operator watches a silent
log and calls it a hang), and a mutation of nonlocal state from a
traced body bakes one trace's value into every later step. The sibling
hazard is performance-shaped: a host sync (``float``, ``np.asarray``,
``.item()``, ``.block_until_ready``) inside a per-step loop turns an
async dispatch pipeline into a lockstep crawl — the exact driver-loop
pathology this repo's bench exists to beat (one observed case: ~60
us/step of host round-trip charged to the device rate).
"""

from __future__ import annotations

import ast
import re

from tpu_distalg.analysis.engine import (Rule, call_name,
                                         dotted_name, root_name)

#: decorator name tails that mean "this function body is traced"
_TRACED_TAILS = {"jit", "shard_map", "pallas_call"}

#: telemetry emitters (events.py API) — side effects at trace time
_TELEMETRY_BASES = {"tevents", "events", "telemetry"}
_TELEMETRY_FNS = {"emit", "mark", "counter", "gauge", "span", "bump",
                  "write"}

_STEP_NAME_RE = re.compile(
    r"^(n_|num_)?(steps?|iters?|iterations?|sweeps?|rounds?|epochs?)$",
    re.IGNORECASE)

#: host-sync calls by dotted name
_SYNC_CALLS = {"np.asarray", "numpy.asarray", "jax.device_get",
               "jax.block_until_ready"}
#: host-sync calls by method tail (any receiver)
_SYNC_METHODS = {"item", "block_until_ready"}


def _decorator_is_traced(dec) -> bool:
    """@jax.jit, @jit, @pl.pallas_call(...), @partial(jax.jit, ...)."""
    if isinstance(dec, ast.Call):
        name = call_name(dec)
        if name is not None and name.rsplit(".", 1)[-1] == "partial" \
                and dec.args:
            return _decorator_is_traced(dec.args[0])
        dec = dec.func
    name = None
    if isinstance(dec, (ast.Name, ast.Attribute)):
        name = dotted_name(dec)
    return name is not None and name.rsplit(".", 1)[-1] in _TRACED_TAILS


def _local_bindings(fn: ast.AST) -> set:
    """Names bound by plain assignment / for-targets / with-as inside
    ``fn`` (parameters excluded on purpose: arguments are the caller's
    objects — mutating them through a trace is exactly the bug)."""
    out: set[str] = set()
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, (ast.Assign,)):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                               ast.For, ast.AsyncFor)):
            targets = [node.target]
        elif isinstance(node, (ast.withitem,)) and node.optional_vars:
            targets = [node.optional_vars]
        elif isinstance(node, ast.comprehension):
            targets = [node.target]
        for t in targets:
            _bound_names(t, out)
    return out


def _bound_names(target, out: set) -> None:
    """Names BOUND by an assignment target. Recurses into tuple/list
    unpacking but stops at Attribute/Subscript — ``state['k'] = v``
    binds nothing, it mutates ``state``."""
    if isinstance(target, ast.Name):
        out.add(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            _bound_names(elt, out)
    elif isinstance(target, ast.Starred):
        _bound_names(target.value, out)


class TracedSideEffects(Rule):
    code = "TDA010"
    name = "Python side effect inside a traced function"
    invariant = ("jit/shard_map/pallas_call bodies run ONCE at trace "
                 "time — effects there are not per-step behavior")

    def check(self, ctx):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if not any(_decorator_is_traced(d)
                       for d in fn.decorator_list):
                continue
            yield from self._check_body(ctx, fn)

    def _check_body(self, ctx, fn):
        local = _local_bindings(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name == "print":
                    yield self.violation(
                        ctx, node,
                        "print() inside a traced function runs once "
                        "at trace time, then never again — return the "
                        "value, or use jax.debug.print for per-step "
                        "output")
                elif name is not None and "." in name:
                    base, attr = name.rsplit(".", 1)
                    if base.split(".")[0] in _TELEMETRY_BASES \
                            and attr in _TELEMETRY_FNS:
                        yield self.violation(
                            ctx, node,
                            f"telemetry {name}() inside a traced "
                            f"function fires at trace time only — the "
                            f"event log would show one mark for N "
                            f"steps; emit from the host loop around "
                            f"the call instead")
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                kind = ("global" if isinstance(node, ast.Global)
                        else "nonlocal")
                yield self.violation(
                    ctx, node,
                    f"{kind} write from a traced function bakes one "
                    f"trace-time value into the compiled program; "
                    f"thread state through the function's "
                    f"arguments/returns")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        root = root_name(t)
                        if root is not None and root not in local:
                            yield self.violation(
                                ctx, t,
                                f"mutation of nonlocal object "
                                f"{root!r} inside a traced function "
                                f"happens at trace time, not per "
                                f"step; return the new value instead")


def _walk_pruning_defs(node):
    """Yield the loop body's nodes, skipping nested function/lambda
    SUBTREES — a deferred body does not execute per iteration."""
    stack = [node]
    while stack:
        cur = stack.pop()
        if cur is not node and isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                      ast.Lambda)):
            continue
        yield cur
        stack.extend(ast.iter_child_nodes(cur))


def _is_hot_loop(node, ctx) -> bool:
    if node.lineno in ctx.markers.hot_loops:
        return True
    if isinstance(node, (ast.For, ast.AsyncFor)) \
            and isinstance(node.iter, ast.Call) \
            and call_name(node.iter) == "range":
        for arg in node.iter.args:
            for leaf in ast.walk(arg):
                seg = None
                if isinstance(leaf, ast.Name):
                    seg = leaf.id
                elif isinstance(leaf, ast.Attribute):
                    seg = leaf.attr
                if seg is not None and _STEP_NAME_RE.match(seg):
                    return True
    return False


class HostSyncInHotLoop(Rule):
    code = "TDA011"
    name = "host sync inside a step loop"
    invariant = ("per-step host syncs serialize the dispatch pipeline "
                 "— sync at phase boundaries, not inside the loop")

    def applies(self, ctx):
        # tests sync to assert — that is their job
        return not ctx.is_test

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor,
                                     ast.While)):
                continue
            if not _is_hot_loop(node, ctx):
                continue
            for sub in _walk_pruning_defs(node):
                if not isinstance(sub, ast.Call):
                    continue
                v = self._sync(ctx, sub)
                if v is not None:
                    yield v

    def _sync(self, ctx, call):
        name = call_name(call)
        if name == "float" and len(call.args) == 1 \
                and not isinstance(call.args[0], ast.Constant):
            return self.violation(
                ctx, call,
                "float() on a (device) value every step blocks on the "
                "transfer; accumulate device-side and format once at "
                "the phase boundary")
        if name in _SYNC_CALLS:
            return self.violation(
                ctx, call,
                f"{name}() inside a step loop forces a host sync per "
                f"iteration; hoist it to the segment/phase boundary")
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _SYNC_METHODS:
            return self.violation(
                ctx, call,
                f".{call.func.attr}() inside a step loop forces a "
                f"host sync per iteration; hoist it to the "
                f"segment/phase boundary")
        return None


RULES = (TracedSideEffects(), HostSyncInHotLoop())
