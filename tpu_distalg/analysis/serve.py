"""Serving liveness discipline (TDA060).

The serving layer's availability contract is structural: the request
queue is BOUNDED (a full queue sheds with ``ServeOverloadError`` —
backpressure the client can see — instead of growing until the host
OOMs under overload), and no thread ever blocks on a queue without a
timeout (the dispatch loop must keep observing its stop flag, and a
wedged producer must surface as a timeout, not a silent hang — the same
lesson ``data/pipeline.Prefetcher``'s liveness guard encodes). One
forgotten ``queue.Queue()`` or bare ``.get()`` silently voids both;
TDA060 makes the convention machine-checked for ``tpu_distalg/serve/``
and the distributed serving plane (``cluster/serve.py``,
``cluster/router.py``), which carries the identical contract over TCP.

Flagged shapes::

    queue.Queue()                  # unbounded — grows until OOM
    queue.Queue(0) / Queue(-1)     # maxsize <= 0 is spelled-out
    queue.Queue(maxsize=0)         #   unbounded per the queue docs
    q.get()                        # blocks forever
    q.get(True) / q.get(1)         # explicit block, still no timeout
    q.get(block=True)
    q.get(timeout=None)            # spelled-out block-forever

Fine::

    queue.Queue(maxsize=depth)     # bounded
    q.get(timeout=POLL_SECONDS)    # bounded wait
    q.get_nowait() / q.get(block=False) / q.get(0)
    d.get(key) / d.get(key, default)   # dict.get — non-numeric key
                                       # or two positional args
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import Rule, call_name


def _is_queue_ctor(call: ast.Call) -> bool:
    name = call_name(call)
    return name in ("queue.Queue", "Queue", "queue.LifoQueue",
                    "LifoQueue", "queue.PriorityQueue", "PriorityQueue")


def _maxsize_arg(call: ast.Call):
    """The ctor's maxsize expression, or None when omitted."""
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "maxsize":
            return kw.value
    return None


def _static_value(expr):
    """The expression's numeric value when statically decidable
    (constants and negated constants — ``Queue(-1)`` parses as a
    UnaryOp, not a Constant), else None for dynamic expressions."""
    if isinstance(expr, ast.Constant) and \
            isinstance(expr.value, (bool, int, float)):
        return expr.value
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.USub) \
            and isinstance(expr.operand, ast.Constant) \
            and isinstance(expr.operand.value, (int, float)):
        return -expr.operand.value
    return None


class ServeLivenessDiscipline(Rule):
    code = "TDA060"
    name = "unbounded queue / blocking get without timeout in serve/"
    invariant = ("serving stays live under overload: request queues "
                 "are bounded (full = shed, never grow-until-OOM) and "
                 "every blocking queue get carries a timeout so stop "
                 "flags and wedged producers are always observable")

    def applies(self, ctx):
        # the serving PLANE, not just the serve/ package: the cluster
        # router and replica modules carry the same bounded-queue /
        # observable-stop availability contract over TCP
        if "tpu_distalg/serve/" in ctx.path:
            return True
        return ctx.path.endswith(("tpu_distalg/cluster/serve.py",
                                  "tpu_distalg/cluster/router.py"))

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_queue_ctor(node):
                size = _maxsize_arg(node)
                # queue docs: maxsize <= 0 means INFINITE — so a
                # statically non-positive size (0, -1, …) is the
                # unbounded shape too, not just an omitted arg
                val = None if size is None else _static_value(size)
                unbounded = size is None or (val is not None
                                             and val <= 0)
                if unbounded:
                    yield self.violation(
                        ctx, node,
                        "unbounded queue in the serving layer — under "
                        "overload it grows until the host OOMs instead "
                        "of shedding; construct with maxsize=<depth> "
                        "and shed on queue.Full")
                continue
            yield from self._check_get(ctx, node)

    def _check_get(self, ctx, call: ast.Call):
        name = call_name(call)
        if name is None or not name.endswith(".get"):
            return
        if len(call.args) > 2:
            return  # not the queue.get(block[, timeout]) signature
        if call.args:
            block = _static_value(call.args[0])
            if block is None:
                return  # dict.get(key[, default]) — non-numeric key
            if not block:
                return  # get(False)/get(0): non-blocking
            # truthy numeric block arg (True, 1, …): block-forever
            # unless a REAL timeout bounds it — fall through
        timeout, has_timeout = None, False
        if len(call.args) == 2:
            timeout, has_timeout = call.args[1], True
        for kw in call.keywords:
            if kw.arg == "timeout":
                timeout, has_timeout = kw.value, True
            elif kw.arg == "block" and \
                    isinstance(kw.value, ast.Constant) \
                    and not kw.value.value:
                return  # block=False: non-blocking
        if has_timeout and not (
                isinstance(timeout, ast.Constant)
                and timeout.value is None):
            # a dynamic or non-None timeout bounds the wait;
            # timeout=None is the spelled-out block-forever and
            # falls through to the violation
            return
        yield self.violation(
            ctx, call,
            "blocking .get() without a timeout in the serving layer — "
            "the waiter can never observe a stop flag or a dead "
            "producer; use .get(timeout=...) (loop on queue.Empty) or "
            ".get_nowait()")


RULES = (ServeLivenessDiscipline(),)
