"""The wire-protocol contract — extraction + the TDA11x family.

The multi-process tier speaks a hand-rolled framed-TCP protocol
(``cluster/transport.py``): every frame is ``(kind, meta, arrays)``,
every handler dispatches on the kind literal, and the review history
shows ONE bug class recurring in every round — protocol-contract
drift. A frame kind nobody handles rots into a silent drop; a meta key
one encoder forgets raises a KeyError two modules away; a request site
that never checks for an ``error`` reply misreads a dying
coordinator's answer as a genuine rejection (the PR 13 class); a
resume frame without the incarnation token defeats the zombie fencing
it exists for; an ack that leaves the socket before its WAL record is
durable is a recovery that forgets acknowledged state.

This module recovers the contract FROM SOURCE — per file, into the
project-graph summary (:func:`extract_protocol`, riding
``summarize_context``), so the interprocedural rules and the
``tda protocol`` renderer see one spelling:

* **send sites** — ``send_frame``/``request`` calls with a literal
  kind (plus module-local *forwarders*: any function with a ``kind``
  parameter that passes it on to a send API, e.g. the worker's
  ``rpc``/``_Link.request``), the meta-dict keys each site writes
  (one-level local dataflow: ``dict(ident, window=w)`` resolves
  through ``ident = {"slot": ..., "inc": ...}``), and — for round
  trips — the reply kinds the site's unpacked result is compared
  against (``k != "welcome"``-style catch-alls count as rejection
  handling; comparisons credit the nearest preceding unpack, mirrored
  across ``try``/``except`` redial twins).
* **handler branches** — functions with ``kind``+``meta`` parameters
  (or a ``recv_frame`` unpack) dispatching on kind literals; per
  branch: the kinds matched, the meta keys read (``meta["k"]`` =
  required, ``meta.get("k")`` = optional), the reply kinds returned
  (literal tuples, followed through same-module helper calls), whether
  the branch consults a ``*fenced*`` gate, and the WAL kinds it
  appends.
* **WAL ordering** — per function, every send/append interleaving on
  every branch path (the TDA114 raw verdicts).

What deliberately does NOT resolve (each counted, shown by
``tda protocol``): non-literal kind strings (``wal.append`` replay
passthrough, ``send_frame(conn, *reply)`` star-unpacks), meta dicts
built from attributes (``dict(self.ident)``), non-literal meta keys,
and reply-direction payload contracts (the welcome meta). See the
"Protocol graph" subsection in ARCHITECTURE.md.

The rules (all interprocedural, all over the library surface only):

==========  =========================================================
TDA110      frame-kind bijectivity: every sent kind has a handler in
            some peer module and every handled kind is sent somewhere
TDA111      payload-key contract: a key a decoder of kind K reads
            without a default is written by EVERY resolvable encoder
            of K
TDA112      request/reply pairing: a round trip's accepted reply
            kinds are kinds some handler of K actually sends (or a
            local synthetic like the worker link's ``reset``), and an
            ``error``-kind reply is explicitly handled
TDA113      incarnation-fencing completeness: every resolvable
            encoder of a fenced kind (one whose handler consults the
            ``*fenced*`` gate) populates the ``inc`` token
TDA114      WAL-before-ack at protocol scope: no branch path sends a
            frame before the WAL append in the same handler
==========  =========================================================

Layering: stdlib + engine only (same bare-host contract as the rest
of :mod:`tpu_distalg.analysis`).
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import call_name
from tpu_distalg.analysis.project import ProjectRule, _walk_functions

#: transport round-trip / one-way send APIs (matched by trailing name)
SEND_APIS = ("send_frame", "request")
#: frame byte encoders — payload construction, NOT a network send
#: (the WAL rides these; its kinds are ledger records, not wire kinds)
ENCODE_APIS = ("encode_frame", "encode_frame_parts")
#: the receive side — an unpack of one of these starts a dispatch
RECV_APIS = ("recv_frame",)

_PATH_CAP = 64          # TDA114 per-function branch-path budget
_FOLLOW_DEPTH = 4       # handler-branch helper-call follow budget


def _tail(name: str | None) -> str:
    return (name or "").rsplit(".", 1)[-1]


def _own_walk(node):
    """ast.walk minus nested function bodies (they are scanned as
    their own scopes). Lambdas stay in — ``supervised(lambda:
    self.wal.append(...))`` is this function's append."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


def _params(fn) -> list:
    args = fn.args
    return [a.arg for a in args.posonlyargs + args.args]


def _literal_kind(call: ast.Call):
    """``(kind, index)`` of the first literal-string positional among
    the leading args — the kind slot of every frame API shape
    (``send_frame(sock, "k", ...)`` / ``link.request("k", ...)``) —
    else ``(None, -1)`` (a dynamic site)."""
    for i, a in enumerate(call.args[:3]):
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value, i
    return None, -1


def _meta_arg(call: ast.Call, kind_idx: int):
    if 0 <= kind_idx and kind_idx + 1 < len(call.args):
        return call.args[kind_idx + 1]
    for kw in call.keywords:
        if kw.arg == "meta":
            return kw.value
    return None


def _is_wal_append(call: ast.Call) -> bool:
    """``<something wal-ish>.append(...)`` — the attribute chain left
    of ``.append`` carries a ``wal`` segment (``self.wal.append``,
    ``self._wal.append``, ``wal.append``)."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "append"):
        return False
    chain = call_name(call) or ""
    return "wal" in chain.rsplit(".", 1)[0].lower()


def _compare_kinds(test, var: str):
    """``(kinds, negative)`` when ``test`` compares Name ``var``
    against string literals (``==``/``!=``/``in``/``not in``; ``or``
    chains union) — else None."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        kinds, neg = [], False
        for v in test.values:
            m = _compare_kinds(v, var)
            if m is None:
                return None
            kinds.extend(m[0])
            neg = neg or m[1]
        return (kinds, neg) if kinds else None
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.left, ast.Name)
            and test.left.id == var):
        return None
    op, comp = test.ops[0], test.comparators[0]
    if isinstance(comp, ast.Constant) and isinstance(comp.value, str):
        kinds = [comp.value]
    elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in comp.elts):
        kinds = [e.value for e in comp.elts]
    else:
        return None
    if isinstance(op, ast.Eq) or isinstance(op, ast.In):
        return kinds, False
    if isinstance(op, ast.NotEq) or isinstance(op, ast.NotIn):
        return kinds, True
    return None


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


# ---------------------------------------------------------------------
# meta-dict key resolution (one-level local dataflow)


def _resolve_keys(expr, fn, depth: int = 0):
    """``(keys, maybe, dynamic)`` for a meta expression: ``keys`` are
    written on every path, ``maybe`` only conditionally (a second
    assignment's extras, a ``meta["k"] = ...`` patch), ``dynamic``
    means the dict cannot be resolved from literals + one-level local
    dataflow (``dict(self.ident)`` and friends) — TDA111/TDA113 skip
    dynamic encoders rather than guess."""
    if depth > 5 or expr is None:
        return set(), set(), depth > 5
    if isinstance(expr, ast.Constant) and expr.value is None:
        return set(), set(), False
    if isinstance(expr, ast.Dict):
        keys, maybe, dyn = set(), set(), False
        for k, v in zip(expr.keys, expr.values):
            if k is None:                      # {**base, ...}
                k2, m2, d2 = _resolve_keys(v, fn, depth + 1)
                keys |= k2
                maybe |= m2
                dyn = dyn or d2
            elif isinstance(k, ast.Constant) and isinstance(k.value,
                                                            str):
                keys.add(k.value)
            else:
                dyn = True                     # non-literal key
        return keys, maybe, dyn
    if isinstance(expr, ast.Call) and _tail(call_name(expr)) == "dict":
        keys, maybe, dyn = set(), set(), False
        if expr.args:
            k2, m2, d2 = _resolve_keys(expr.args[0], fn, depth + 1)
            keys |= k2
            maybe |= m2
            dyn = dyn or d2
        for kw in expr.keywords:
            if kw.arg is None:
                dyn = True
            else:
                keys.add(kw.arg)
        return keys, maybe, dyn
    if isinstance(expr, ast.Name):
        assigns = [n for n in _own_walk(fn)
                   if isinstance(n, ast.Assign)
                   and len(n.targets) == 1
                   and isinstance(n.targets[0], ast.Name)
                   and n.targets[0].id == expr.id]
        if not assigns:
            return set(), set(), True
        keys, maybe, dyn = None, set(), False
        for a in assigns:
            k2, m2, d2 = _resolve_keys(a.value, fn, depth + 1)
            maybe |= m2 | k2
            dyn = dyn or d2
            keys = k2 if keys is None else keys & k2
        for n in _own_walk(fn):        # conditional `name["k"] = v`
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Subscript) \
                    and isinstance(n.targets[0].value, ast.Name) \
                    and n.targets[0].value.id == expr.id \
                    and isinstance(n.targets[0].slice, ast.Constant):
                maybe.add(n.targets[0].slice.value)
        keys = keys or set()
        return keys, maybe - keys, dyn
    return set(), set(), True


# ---------------------------------------------------------------------
# the per-module extractor


class _ModuleScan:
    def __init__(self, tree, imports: dict):
        self.tree = tree
        self.imports = imports
        self.fns = list(_walk_functions(tree))
        self.class_methods = {(cls, fn.name): fn
                              for _, cls, fn in self.fns
                              if cls is not None}
        self.module_defs = {fn.name: fn for _, cls, fn in self.fns
                            if cls is None}
        self.forwarders = self._find_forwarders()

    # -- forwarders ---------------------------------------------------

    def _find_forwarders(self) -> dict:
        """name -> 'send' | 'encode' | 'wal' for module-local
        functions with a ``kind`` parameter that pass it on to a frame
        API (or to another forwarder — fixpoint)."""
        out: dict = {}
        cands = [(q, fn) for q, _, fn in self.fns
                 if "kind" in _params(fn)]
        for _ in range(3):                    # chains are short
            grew = False
            for qual, fn in cands:
                if fn.name in out:
                    continue
                for call in ast.walk(fn):     # lambdas included
                    if not isinstance(call, ast.Call):
                        continue
                    if not any(isinstance(n, ast.Name)
                               and n.id == "kind"
                               for a in call.args
                               for n in ast.walk(a)):
                        continue
                    tail = _tail(call_name(call))
                    if tail in SEND_APIS:
                        out[fn.name] = "send"
                    elif _is_wal_append(call):
                        out[fn.name] = "wal"
                    elif tail in ENCODE_APIS:
                        out.setdefault(fn.name, "encode")
                    elif tail in out and tail != fn.name:
                        out.setdefault(fn.name, out[tail])
                if fn.name in out:
                    grew = True
            if not grew:
                break
        return out

    def _local_def(self, call: ast.Call):
        """The module-local def a call resolves to — only when the
        callee does NOT root in an imported module (``link.request``
        resolves to ``_Link.request``; ``transport.request`` stays the
        base API)."""
        tail = _tail(call_name(call))
        if isinstance(call.func, ast.Name):
            return self.module_defs.get(tail)
        root = (call_name(call) or "").split(".", 1)[0]
        if root in self.imports:
            return None
        for (_, name), fn in self.class_methods.items():
            if name == tail:
                return fn
        return self.module_defs.get(tail)

    def _call_class(self, call: ast.Call) -> str | None:
        """'send' / 'encode' / 'wal' / None for one call node."""
        tail = _tail(call_name(call))
        if tail in SEND_APIS:
            return "send"
        if _is_wal_append(call):
            return "wal"
        if tail in ENCODE_APIS:
            return "encode"
        if tail in self.forwarders:
            root = (call_name(call) or "").split(".", 1)[0]
            if root not in self.imports or isinstance(call.func,
                                                      ast.Name):
                return self.forwarders[tail]
        return None

    # -- round-trip reply discipline -----------------------------------

    def _unpack_credits(self, fn):
        """Per request-ish call (by line): the reply kinds its
        unpacked result is compared against + whether any comparison
        is a catch-all rejection (``!=``/``not in``). Comparisons
        credit the nearest preceding unpack of the same name; a
        try-body unpack and an except-handler re-unpack of the same
        name (the redial-twin idiom — the comparison after the
        ``try`` credits only the handler's) share credits."""
        unpacks = []        # [line, var, credits, negative]
        trys = [n for n in _own_walk(fn) if isinstance(n, ast.Try)]

        for n in _own_walk(fn):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.value, ast.Call)):
                continue
            cls = self._call_class(n.value)
            tail = _tail(call_name(n.value))
            if cls != "send" and tail not in RECV_APIS:
                continue
            tgt = n.targets[0]
            var = None
            if isinstance(tgt, ast.Tuple) and tgt.elts \
                    and isinstance(tgt.elts[0], ast.Name):
                var = tgt.elts[0].id
            if var is not None and var != "_":
                unpacks.append([n.value.lineno, var, set(), False])
        for n in _own_walk(fn):
            if not isinstance(n, (ast.Compare, ast.BoolOp)):
                continue
            for var in sorted({u[1] for u in unpacks}):
                m = _compare_kinds(n, var)
                if m is None:
                    continue
                cands = [u for u in unpacks
                         if u[1] == var and u[0] <= n.lineno]
                if not cands:
                    continue
                hit = max(cands, key=lambda u: u[0])
                hit[2].update(m[0])
                hit[3] = hit[3] or m[1]
                break
        # redial twins: an unpack in a Try's BODY and one in its
        # except HANDLER (same var) are the same logical round trip —
        # a comparison after the try credits only the later (handler)
        # unpack, so copy credits across the pair. Unpacks that merely
        # share a try body do NOT share credits.
        def _within(line, stmts):
            return any(s.lineno <= line <= (s.end_lineno or s.lineno)
                       for s in stmts)

        for t in trys:
            in_body = [u for u in unpacks if _within(u[0], t.body)]
            in_handlers = [u for u in unpacks
                           if any(_within(u[0], h.body)
                                  for h in t.handlers)]
            for b in in_body:
                for h in in_handlers:
                    if b[1] != h[1]:
                        continue
                    kinds = b[2] | h[2]
                    neg = b[3] or h[3]
                    b[2], h[2] = set(kinds), set(kinds)
                    b[3] = h[3] = neg
        return {u[0]: (u[2], u[3]) for u in unpacks}

    def _chain_credits(self, call: ast.Call, depth: int = 0):
        """Reply kinds checked INSIDE a forwarder chain (the worker's
        ``rpc`` folds ``reset``/``error`` for every call site)."""
        if depth > 2:
            return set(), False
        target = self._local_def(call)
        if target is None or _tail(call_name(call)) \
                not in dict(self.forwarders, **{a: "send"
                                                for a in SEND_APIS}):
            return set(), False
        kinds, neg = set(), False
        credits = self._unpack_credits(target)
        for k, n in credits.values():
            kinds |= k
            neg = neg or n
        for inner in _own_walk(target):
            if isinstance(inner, ast.Call) \
                    and self._call_class(inner) == "send":
                k2, n2 = self._chain_credits(inner, depth + 1)
                kinds |= k2
                neg = neg or n2
        return kinds, neg

    # -- send / encode / wal sites -------------------------------------

    def scan_sites(self):
        sends, encodes, wals, n_dynamic = [], [], [], 0
        for qual, _cls, fn in self.fns:
            credits = self._unpack_credits(fn)
            recv_lines = sorted(
                n.lineno for n in _own_walk(fn)
                if isinstance(n, ast.Call)
                and _tail(call_name(n)) in RECV_APIS)
            for call in _own_walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                cls = self._call_class(call)
                if cls is None:
                    continue
                kind, kidx = _literal_kind(call)
                if kind is None:
                    if cls == "send":
                        n_dynamic += 1
                    continue
                if cls == "wal":
                    wals.append({"kind": kind, "fn": qual,
                                 "line": call.lineno})
                    continue
                if cls == "encode":
                    encodes.append({"kind": kind, "fn": qual,
                                    "line": call.lineno})
                    continue
                keys, maybe, dyn = _resolve_keys(
                    _meta_arg(call, kidx), fn)
                is_request = _tail(call_name(call)) != "send_frame" \
                    or not any(r < call.lineno for r in recv_lines)
                accepts, rejects = credits.get(call.lineno,
                                               (set(), False))
                c_kinds, c_neg = self._chain_credits(call)
                sends.append({
                    "kind": kind, "fn": qual, "line": call.lineno,
                    "role": "request" if is_request else "reply",
                    "keys": sorted(keys), "maybe": sorted(maybe),
                    "dynamic": dyn,
                    "accepts": sorted(accepts | c_kinds),
                    "rejects": rejects or c_neg,
                })
        return sends, encodes, wals, n_dynamic

    # -- synthetic local replies ----------------------------------------

    def scan_synthetics(self):
        """Literal reply tuples returned by send-forwarders — kinds a
        round trip can legitimately receive that no HANDLER sends (the
        worker link's ``("reset", welcome, center)``). Full ast.walk:
        the synthetic return typically lives in the retry closure
        nested inside the forwarder."""
        out = []
        for qual, _cls, fn in self.fns:
            if self.forwarders.get(fn.name) != "send":
                continue
            for n in ast.walk(fn):
                if isinstance(n, ast.Return) \
                        and isinstance(n.value, ast.Tuple) \
                        and n.value.elts \
                        and isinstance(n.value.elts[0], ast.Constant) \
                        and isinstance(n.value.elts[0].value, str):
                    out.append({"kind": n.value.elts[0].value,
                                "fn": qual, "line": n.lineno})
        return out

    # -- handler dispatch -------------------------------------------------

    def scan_handlers(self):
        out = []
        for qual, cls, fn in self.fns:
            params = _params(fn)
            if "kind" in params and any(p in ("meta", "meta_")
                                        for p in params):
                meta = "meta" if "meta" in params else "meta_"
                out.extend(self._dispatch(fn, cls, qual, "kind", meta))
                continue
            # recv_frame unpack dispatch (accept loops)
            for n in _own_walk(fn):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Tuple) \
                        and isinstance(n.value, ast.Call) \
                        and _tail(call_name(n.value)) in RECV_APIS:
                    elts = n.targets[0].elts
                    if elts and isinstance(elts[0], ast.Name):
                        meta = elts[1].id if len(elts) > 1 and \
                            isinstance(elts[1], ast.Name) else None
                        out.extend(self._dispatch(
                            fn, cls, qual, elts[0].id, meta))
                    break
        return out

    def _dispatch(self, fn, cls, qual, kind_var, meta_var):
        branches = []
        self._scan_block(list(fn.body), fn, cls, kind_var, meta_var,
                         branches)
        return branches

    def _scan_block(self, stmts, fn, cls, kind_var, meta_var,
                    branches):
        for i, st in enumerate(stmts):
            if isinstance(st, ast.If):
                m = _compare_kinds(st.test, kind_var)
                if m is not None and not m[1]:
                    branches.append(self._branch(
                        m[0], st.body, st.lineno, fn, cls, qual=None))
                    self._scan_block(st.orelse, fn, cls, kind_var,
                                     meta_var, branches)
                elif m is not None and m[1] and _terminates(st.body):
                    # `if kind != "route": reject; continue` — the
                    # REST of this block is the kind's handler
                    branches.append(self._branch(
                        m[0], stmts[i + 1:], st.lineno, fn, cls,
                        qual=None))
                else:
                    self._scan_block(st.body, fn, cls, kind_var,
                                     meta_var, branches)
                    self._scan_block(st.orelse, fn, cls, kind_var,
                                     meta_var, branches)
            elif isinstance(st, (ast.While, ast.For)):
                self._scan_block(st.body, fn, cls, kind_var, meta_var,
                                 branches)
            elif isinstance(st, ast.Try):
                for blk in (st.body, st.orelse, st.finalbody):
                    self._scan_block(blk, fn, cls, kind_var, meta_var,
                                     branches)
                for h in st.handlers:
                    self._scan_block(h.body, fn, cls, kind_var,
                                     meta_var, branches)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                self._scan_block(st.body, fn, cls, kind_var, meta_var,
                                 branches)
        # meta_var reads in the branch bodies are collected by _branch
        # against the dispatch function's meta name; nothing to do here

    def _branch(self, kinds, stmts, line, fn, cls, qual):
        facts = {"reads": {}, "replies": set(), "fenced": False,
                 "wal": set()}
        enclosing = fn
        meta = None
        params = _params(fn)
        if "meta" in params:
            meta = "meta"
        elif "meta_" in params:
            meta = "meta_"
        else:
            for n in _own_walk(fn):        # the recv-unpack meta name
                if isinstance(n, ast.Assign) \
                        and isinstance(n.targets[0], ast.Tuple) \
                        and isinstance(n.value, ast.Call) \
                        and _tail(call_name(n.value)) in RECV_APIS:
                    elts = n.targets[0].elts
                    if len(elts) > 1 and isinstance(elts[1], ast.Name):
                        meta = elts[1].id
                    break
        self._collect(stmts, meta, cls, facts, set(), 0)
        qual = next((q for q, _c, f in self.fns if f is enclosing),
                    fn.name)
        return {"kinds": sorted(set(kinds)), "fn": qual, "line": line,
                "reads": sorted([k, req] for k, req
                                in facts["reads"].items()),
                "replies": sorted(facts["replies"]),
                "fenced": facts["fenced"],
                "wal": sorted(facts["wal"])}

    def _collect(self, stmts, meta, cls, facts, visited, depth):
        """Branch facts from statements: meta reads, literal reply
        tuples (returned or sent), fence-gate calls, WAL kinds —
        following same-module helper calls that touch the meta."""
        for st in stmts:
            for n in [st] + list(_own_walk(st)):
                if isinstance(n, ast.Subscript) and meta \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id == meta \
                        and isinstance(n.slice, ast.Constant) \
                        and isinstance(n.slice.value, str) \
                        and isinstance(n.ctx, ast.Load):
                    facts["reads"][n.slice.value] = True
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "get" and meta \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == meta and n.args \
                        and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str):
                    facts["reads"].setdefault(n.args[0].value, False)
                if isinstance(n, ast.Return) \
                        and isinstance(n.value, ast.Tuple) \
                        and n.value.elts \
                        and isinstance(n.value.elts[0], ast.Constant) \
                        and isinstance(n.value.elts[0].value, str):
                    facts["replies"].add(n.value.elts[0].value)
                if isinstance(n, ast.Return) \
                        and isinstance(n.value, ast.Call):
                    # `return self._handle_score(arrays)` — the
                    # callee's returns ARE this branch's replies,
                    # whether or not the meta flows in
                    self._collect_call(n.value, meta, cls, facts,
                                       visited, depth, forced=True)
                if isinstance(n, ast.Call):
                    self._collect_call(n, meta, cls, facts, visited,
                                       depth)

    def _collect_call(self, call, meta, cls, facts, visited, depth,
                      forced=False):
        tail = _tail(call_name(call))
        if "fenc" in tail:
            facts["fenced"] = True
        ccls = self._call_class(call)
        kind, _ = _literal_kind(call)
        if ccls == "wal" and kind is not None:
            facts["wal"].add(kind)
        if ccls == "send" and kind is not None:
            facts["replies"].add(kind)
        if depth >= _FOLLOW_DEPTH or ccls is not None:
            return
        # follow a same-module helper the meta flows into (or whose
        # return IS the branch's reply)
        target = None
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id == "self" and cls is not None:
            target = self.class_methods.get((cls, call.func.attr))
        elif isinstance(call.func, ast.Name):
            target = self.module_defs.get(call.func.id)
        if target is None or id(target) in visited:
            return
        touches_meta = meta is not None and any(
            isinstance(n, ast.Name) and n.id == meta
            for a in call.args for n in ast.walk(a))
        if not forced and not touches_meta and meta is not None:
            return
        visited.add(id(target))
        new_meta = None
        tparams = _params(target)
        if tparams and tparams[0] == "self":
            tparams = tparams[1:]
        for j, a in enumerate(call.args):
            if isinstance(a, ast.Name) and a.id == meta \
                    and j < len(tparams):
                new_meta = tparams[j]
                break
        tcls = next((c for _, c, f in self.fns if f is target), None)
        self._collect(list(target.body), new_meta, tcls, facts,
                      visited, depth + 1)

    # -- TDA114: send/append interleavings ------------------------------

    def scan_wal_order(self):
        out = []
        for qual, _cls, fn in self.fns:
            events = self._path_events(list(fn.body))
            seen = set()
            for path in events:
                sent = None            # (kind, line) of first send
                for ev, kind, line in path:
                    if ev == "send":
                        sent = sent or (kind, line)
                    elif ev == "wal" and sent is not None:
                        key = (sent[1], kind)
                        if key not in seen:
                            seen.add(key)
                            out.append({
                                "fn": qual, "line": sent[1],
                                "send_kind": sent[0],
                                "wal_kind": kind})
        return out

    def _stmt_events(self, st):
        events = []
        for n in ast.walk(st):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not isinstance(n, ast.Call):
                continue
            cls = self._call_class(n)
            kind, _ = _literal_kind(n)
            if kind is None:
                continue
            if cls == "send":
                events.append(("send", kind, n.lineno))
            elif cls == "wal":
                events.append(("wal", kind, n.lineno))
        return sorted(events, key=lambda e: e[2])

    def _path_events(self, stmts):
        """Every branch path's (event, kind, line) sequence, loops
        taken once, ``return``/``raise`` terminating, capped at
        ``_PATH_CAP`` paths."""
        paths = [([], True)]          # (events, still-live)

        def extend(branches):
            nonlocal paths
            new = []
            for ev, live in paths:
                if not live:
                    new.append((ev, live))
                    continue
                for bev, blive in branches:
                    if len(new) >= _PATH_CAP:
                        break
                    new.append((ev + bev, blive))
            paths = new[:_PATH_CAP]

        for st in stmts:
            if all(not live for _, live in paths):
                break
            if isinstance(st, ast.If):
                cond = [(self._stmt_events(st.test), True)]
                extend(cond)
                body = self._sub_paths(st.body)
                orelse = self._sub_paths(st.orelse) or [([], True)]
                extend(body + orelse)
            elif isinstance(st, (ast.While, ast.For)):
                extend([([], True)]
                       + self._sub_paths(st.body))
            elif isinstance(st, ast.Try):
                body = self._sub_paths(st.body)
                handlers = [p for h in st.handlers
                            for p in self._sub_paths(h.body)]
                extend(body + (handlers or []))
                if st.finalbody:
                    extend(self._sub_paths(st.finalbody))
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                extend(self._sub_paths(st.body))
            elif isinstance(st, (ast.Return, ast.Raise)):
                extend([(self._stmt_events(st), False)])
            elif isinstance(st, (ast.Break, ast.Continue)):
                extend([([], False)])
            else:
                extend([(self._stmt_events(st), True)])
        return [ev for ev, _ in paths]

    def _sub_paths(self, stmts):
        if not stmts:
            return []
        sub = self._path_events(stmts)
        # _path_events loses liveness at this boundary; a terminated
        # sub-path simply carries no further events, which is the same
        # thing for ordering purposes
        return [(ev, True) for ev in sub]


def extract_protocol(tree, imports: dict) -> dict:
    """One module's protocol-graph contribution (JSON-able; empty
    lists when the module never touches the wire)."""
    scan = _ModuleScan(tree, imports)
    sends, encodes, wals, n_dynamic = scan.scan_sites()
    doc = {
        "sends": sorted(sends, key=lambda s: (s["line"], s["kind"])),
        "encodes": sorted(encodes,
                          key=lambda s: (s["line"], s["kind"])),
        "wal_appends": sorted(wals,
                              key=lambda s: (s["line"], s["kind"])),
        "handlers": sorted(scan.scan_handlers(),
                           key=lambda h: (h["line"],)),
        "synthetics": sorted(scan.scan_synthetics(),
                             key=lambda s: (s["line"], s["kind"])),
        "wal_order": sorted(scan.scan_wal_order(),
                            key=lambda s: (s["line"],)),
        "n_dynamic_sends": n_dynamic,
    }
    return doc


# ---------------------------------------------------------------------
# the assembled contract (rules + `tda protocol` share this)


def build_contract(project) -> dict:
    """Aggregate every library module's protocol facts into one
    contract: per frame kind its senders, handlers, reply kinds,
    required/optional payload keys and fencing; plus WAL record kinds,
    synthetic local replies, and the deliberately-unresolved counts."""
    frames: dict = {}
    wal_records: dict = {}
    encodes: dict = {}
    synthetics: dict = {}
    wal_order: list = []
    n_dynamic = 0

    def frame(kind):
        return frames.setdefault(kind, {"senders": [], "handlers": []})

    for s in project.library():
        proto = s.get("protocol")
        if not proto:
            continue
        path = s["path"]
        for site in proto["sends"]:
            frame(site["kind"])["senders"].append(
                dict(site, path=path))
        for h in proto["handlers"]:
            for kind in h["kinds"]:
                frame(kind)["handlers"].append(dict(h, path=path))
        for w in proto["wal_appends"]:
            wal_records.setdefault(w["kind"], []).append(
                dict(w, path=path))
        for e in proto["encodes"]:
            encodes.setdefault(e["kind"], []).append(
                dict(e, path=path))
        for syn in proto["synthetics"]:
            synthetics.setdefault(syn["kind"], []).append(
                dict(syn, path=path))
        for o in proto["wal_order"]:
            wal_order.append(dict(o, path=path))
        n_dynamic += proto["n_dynamic_sends"]

    return {"frames": frames, "wal_records": wal_records,
            "encodes": encodes, "synthetics": synthetics,
            "wal_order": wal_order, "n_dynamic_sends": n_dynamic}


def _required_keys(entry) -> dict:
    """key -> (path, line) for keys some handler reads WITHOUT a
    default."""
    out: dict = {}
    for h in entry["handlers"]:
        for key, required in h["reads"]:
            if required:
                out.setdefault(key, (h["path"], h["line"]))
    return out


def _reply_kinds(entry) -> set:
    out = set()
    for h in entry["handlers"]:
        out.update(h["replies"])
    return out


# ---------------------------------------------------------------------
# the rules


class _ProtocolRule(ProjectRule):
    def check_project(self, project):
        contract = build_contract(project)
        if not contract["frames"]:
            return
        yield from self.check_contract(project, contract)

    def check_contract(self, project, contract):
        raise NotImplementedError


class FrameKindBijectivity(_ProtocolRule):
    code = "TDA110"
    name = "frame kind sent with no handler, or handled but never sent"
    invariant = (
        "the wire contract is bijective: every frame kind some peer "
        "sends has a dispatch branch in some handler module, and "
        "every dispatch branch matches a kind something actually "
        "sends — an unhandled kind rots into a silent error reply, a "
        "dead branch into unreviewed protocol surface")

    def check_contract(self, project, contract):
        frames = contract["frames"]
        any_requests = any(
            s["role"] == "request"
            for e in frames.values() for s in e["senders"])
        any_handlers = any(e["handlers"] for e in frames.values())
        if not (any_requests and any_handlers):
            return    # single-sided surface (one file linted): no
            #           bijectivity claim is decidable
        for kind in sorted(frames):
            entry = frames[kind]
            requests = [s for s in entry["senders"]
                        if s["role"] == "request"]
            if requests and not entry["handlers"]:
                seen = set()
                for s in requests:
                    if s["path"] in seen:
                        continue
                    seen.add(s["path"])
                    yield self.project_violation(
                        project, s["path"], s["line"],
                        f"frame kind '{kind}' is sent here but no "
                        f"handler in any module dispatches on it — "
                        f"the receiver's unknown-kind fallthrough "
                        f"answers 'error' and the frame rots into a "
                        f"silent drop; add a dispatch branch or "
                        f"retire the send")
            elif entry["handlers"] and not requests:
                seen = set()
                for h in entry["handlers"]:
                    if h["path"] in seen:
                        continue
                    seen.add(h["path"])
                    yield self.project_violation(
                        project, h["path"], h["line"],
                        f"frame kind '{kind}' has a dispatch branch "
                        f"here but nothing on the lint surface sends "
                        f"it — dead protocol surface no review "
                        f"exercises; retire the branch or restore "
                        f"the sender")


class PayloadKeyContract(_ProtocolRule):
    code = "TDA111"
    name = "meta key a decoder requires that an encoder never writes"
    invariant = (
        "a meta key any handler of kind K reads without a default "
        "(meta[\"k\"]) is written by every resolvable encoder of K — "
        "the missing-key spelling is a KeyError that fires two "
        "modules and one process boundary away from the encoder that "
        "caused it")

    def check_contract(self, project, contract):
        for kind in sorted(contract["frames"]):
            entry = contract["frames"][kind]
            required = _required_keys(entry)
            if not required:
                continue
            for s in entry["senders"]:
                if s["role"] != "request" or s["dynamic"]:
                    continue
                missing = sorted(set(required) - set(s["keys"]))
                if not missing:
                    continue
                key = missing[0]
                rpath, rline = required[key]
                yield self.project_violation(
                    project, s["path"], s["line"],
                    f"encoder of '{kind}' omits meta key(s) "
                    f"{missing} that {rpath}:{rline} reads without a "
                    f"default — a KeyError in the handler, one "
                    f"process away from this send; write the key(s) "
                    f"or give the read a .get default")


class RequestReplyPairing(_ProtocolRule):
    code = "TDA112"
    name = ("request accepts a reply kind its handler never sends, "
            "or never handles an error-kind reply")
    invariant = (
        "every round trip's accepted reply kinds are kinds some "
        "handler of the request actually sends (or a local synthetic "
        "like the worker link's 'reset'), and every round trip "
        "explicitly handles an 'error' reply — a dying peer's error "
        "frame misread as a genuine rejection was the PR 13 "
        "coordinator-kill bug")

    def check_contract(self, project, contract):
        frames = contract["frames"]
        synthetic = set(contract["synthetics"])
        seen_err: set = set()
        for kind in sorted(frames):
            entry = frames[kind]
            if not entry["handlers"]:
                continue      # TDA110's finding, not a pairing claim
            replies = _reply_kinds(entry) | synthetic | {"error"}
            for s in entry["senders"]:
                if s["role"] != "request":
                    continue
                for acc in s["accepts"]:
                    if acc in replies:
                        continue
                    yield self.project_violation(
                        project, s["path"], s["line"],
                        f"request '{kind}' checks its reply against "
                        f"'{acc}', a kind no handler of '{kind}' "
                        f"sends (handlers reply "
                        f"{sorted(_reply_kinds(entry)) or ['<none>']})"
                        f" — the comparison can never be true; fix "
                        f"the kind or the handler")
                handles_error = "error" in s["accepts"] or s["rejects"]
                if not handles_error \
                        and (s["path"], kind) not in seen_err:
                    seen_err.add((s["path"], kind))
                    yield self.project_violation(
                        project, s["path"], s["line"],
                        f"request '{kind}' never checks for an "
                        f"'error' reply (no == 'error' and no "
                        f"catch-all != rejection on the unpacked "
                        f"kind) — a fenced-out or dying peer's error "
                        f"frame would be silently adopted as data "
                        f"(the PR 13 class); raise on k == 'error' "
                        f"or reject non-expected kinds")


class IncarnationFencing(_ProtocolRule):
    code = "TDA113"
    name = "encoder of a fenced frame kind omits the 'inc' token"
    invariant = (
        "every resolvable encoder of a fenced frame kind (one whose "
        "handler consults the *fenced* gate) populates the 'inc' "
        "incarnation token — a token-less frame is invisible to the "
        "zombie fencing and either acts for a dead incarnation or "
        "reads as its liveness (the PR 13 round-2 class)")

    def check_contract(self, project, contract):
        frames = contract["frames"]
        for kind in sorted(frames):
            entry = frames[kind]
            if not any(h["fenced"] for h in entry["handlers"]):
                continue
            for s in entry["senders"]:
                if s["role"] != "request" or s["dynamic"]:
                    continue
                if "inc" in s["keys"]:
                    continue
                yield self.project_violation(
                    project, s["path"], s["line"],
                    f"'{kind}' is a fenced kind (its handler "
                    f"consults the incarnation gate) but this "
                    f"encoder never writes the 'inc' token — the "
                    f"frame is either rejected as a zombie's or, "
                    f"worse, keeps a dying incarnation looking "
                    f"alive; send dict(ident, ...) like the other "
                    f"encoders")


class WalBeforeAck(_ProtocolRule):
    code = "TDA114"
    name = "frame sent before the WAL append on some branch path"
    invariant = (
        "write-AHEAD at protocol scope (TDA091 generalized beyond "
        "fsync syntax): in any handler that both appends a WAL "
        "record and sends a frame, the append dominates the send on "
        "every branch path — an ack that escapes before its record "
        "is a recovery that silently forgets acknowledged state")

    def check_contract(self, project, contract):
        for o in sorted(contract["wal_order"],
                        key=lambda o: (o["path"], o["line"])):
            yield self.project_violation(
                project, o["path"], o["line"],
                f"'{o['send_kind']}' frame leaves the socket before "
                f"the WAL append of '{o['wal_kind']}' on this branch "
                f"path — the peer can observe state a crashed "
                f"recovery would forget; append (and fsync) before "
                f"the send")


RULES = (FrameKindBijectivity(), PayloadKeyContract(),
         RequestReplyPairing(), IncarnationFencing(), WalBeforeAck())


# ---------------------------------------------------------------------
# `tda protocol` rendering


def _mods(entries) -> str:
    return ", ".join(sorted({e["path"] for e in entries})) or "—"


def contract_rows(contract) -> list:
    """One deterministic row per frame kind:
    ``(kind, senders, handlers, replies, required, optional,
    fenced)``."""
    rows = []
    for kind in sorted(contract["frames"]):
        entry = contract["frames"][kind]
        if not entry["handlers"] and not any(
                s["role"] == "request" for s in entry["senders"]):
            continue    # reply-direction kind ('error', 'welcome'):
            #             it shows up in the replies column instead
        required = sorted(_required_keys(entry))
        optional = sorted(
            {k for h in entry["handlers"]
             for k, req in h["reads"] if not req} - set(required))
        rows.append((
            kind,
            _mods([s for s in entry["senders"]
                   if s["role"] == "request"]),
            _mods(entry["handlers"]),
            ", ".join(sorted(_reply_kinds(entry))) or "—",
            ", ".join(required) or "—",
            ", ".join(optional) or "—",
            "yes" if any(h["fenced"] for h in entry["handlers"])
            else "",
        ))
    return rows


_COLUMNS = ("kind", "senders", "handlers", "replies",
            "required keys", "optional keys", "fenced")

_PREAMBLE = (
    "Generated by `tda protocol --format md` — do not edit by hand. "
    "`tda protocol --check` (wired into `scripts/lint_gate.sh`) "
    "fails when this file drifts from the extracted contract; "
    "regenerate with "
    "`python -m tpu_distalg.cli protocol --format md > "
    "docs/PROTOCOL.md`. Module paths only (no line numbers), so the "
    "table is stable under unrelated edits.")


def render_md(contract) -> str:
    lines = ["# Wire protocol contract", "", _PREAMBLE, "",
             "## Frames", ""]
    rows = contract_rows(contract)
    lines.append("| " + " | ".join(_COLUMNS) + " |")
    lines.append("|" + "---|" * len(_COLUMNS))
    for row in rows:
        lines.append("| " + " | ".join(row) + " |")
    lines += ["", "## WAL record kinds", "",
              "| record kind | appended by |", "|---|---|"]
    for kind in sorted(contract["wal_records"]):
        lines.append(f"| {kind} | "
                     f"{_mods(contract['wal_records'][kind])} |")
    if contract["synthetics"]:
        lines += ["", "## Synthetic local replies", "",
                  "Reply kinds a crash-tolerant link can hand its "
                  "caller that no remote handler ever sends:", ""]
        for kind in sorted(contract["synthetics"]):
            lines.append(
                f"- `{kind}` — "
                f"{_mods(contract['synthetics'][kind])}")
    lines += ["", "## Deliberately unresolved", "",
              f"- {contract['n_dynamic_sends']} send site(s) with a "
              f"non-literal frame kind (WAL replay passthroughs, "
              f"`send_frame(conn, *reply)` star-unpacks) — excluded "
              f"from the tables above.",
              "- Meta dicts built from attributes "
              "(`dict(self.ident)`) resolve as *dynamic* and are "
              "skipped by the key/fencing rules.",
              "- Reply-direction payload keys (what a *reply's* meta "
              "must carry, e.g. the welcome) are out of scope.",
              ""]
    return "\n".join(lines)


def render_text(contract) -> str:
    rows = contract_rows(contract)
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows
              else len(c) for i, c in enumerate(_COLUMNS)]
    out = ["  ".join(c.ljust(w) for c, w in zip(_COLUMNS, widths))]
    out.append("  ".join("-" * w for w in widths))
    for row in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    out.append("")
    out.append("wal records: " + (", ".join(
        f"{k} ({_mods(v)})" for k, v in
        sorted(contract["wal_records"].items())) or "none"))
    if contract["synthetics"]:
        out.append("synthetic local replies: "
                   + ", ".join(sorted(contract["synthetics"])))
    out.append(f"unresolved dynamic-kind send sites: "
               f"{contract['n_dynamic_sends']}")
    return "\n".join(out)


def render_json(contract) -> dict:
    rows = contract_rows(contract)
    return {
        "frames": [dict(zip(_COLUMNS, row)) for row in rows],
        "frame_sites": {
            kind: entry for kind, entry in
            sorted(contract["frames"].items())},
        "wal_records": {k: v for k, v in
                        sorted(contract["wal_records"].items())},
        "synthetics": {k: v for k, v in
                       sorted(contract["synthetics"].items())},
        "n_dynamic_sends": contract["n_dynamic_sends"],
    }
