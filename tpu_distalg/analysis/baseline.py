"""Lint baselines — grandfathered violations, pinned and auditable.

A baseline lets ``tda lint`` gate NEW violations in CI while known ones
are burned down: the committed ``lint_baseline.json`` holds a
fingerprint per grandfathered finding (code + path + stripped source
line — line-number drift does not invalidate it). Two properties keep
it honest:

  * matching is a MULTISET per fingerprint: baselining one violation
    does not silently cover a second identical one added later;
  * a stale entry (its violation no longer exists) is an ERROR, not a
    quiet success — the baseline must shrink with the debt, or it
    becomes a pile of permanent exemptions nobody can audit.

``tda lint --update-baseline`` regenerates the file from the current
tree.
"""

from __future__ import annotations

import collections
import json
import os

VERSION = 1


def save(path: str, violations) -> dict:
    """Write a baseline covering ``violations``; returns the document."""
    counts = collections.Counter(
        (v.code, v.path, v.fingerprint, v.snippet) for v in violations)
    doc = {
        "version": VERSION,
        "entries": [
            {"code": code, "path": p, "fingerprint": fp,
             "snippet": snippet, "count": n}
            for (code, p, fp, snippet), n in sorted(counts.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def load(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("version") != VERSION:
        raise ValueError(
            f"baseline {path} has version {doc.get('version')!r}; "
            f"this linter speaks {VERSION} — regenerate with "
            f"'tda lint --update-baseline'")
    return doc


def apply(doc: dict, violations):
    """Split ``violations`` into (new, baselined) and report stale
    entries. Returns ``(new, baselined, stale)`` where ``stale`` is the
    list of baseline entries with fewer live matches than their
    count."""
    budget = {
        (e["code"], e["path"], e["fingerprint"]): int(e.get("count", 1))
        for e in doc.get("entries", [])
    }
    used: collections.Counter = collections.Counter()
    new, baselined = [], []
    for v in violations:
        key = (v.code, v.path, v.fingerprint)
        if used[key] < budget.get(key, 0):
            used[key] += 1
            baselined.append(v)
        else:
            new.append(v)
    stale = [
        e for e in doc.get("entries", [])
        if used[(e["code"], e["path"], e["fingerprint"])]
        < int(e.get("count", 1))
    ]
    return new, baselined, stale


def resolve(path: str | None) -> str | None:
    """Default baseline: ``lint_baseline.json`` next to the cwd when it
    exists and no explicit path was given."""
    if path is not None:
        return path
    default = "lint_baseline.json"
    return default if os.path.exists(default) else None
