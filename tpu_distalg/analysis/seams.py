"""Fault-seam coverage — raw durable writes in library code (TDA030).

PR 3 wired deterministic fault injection at seven seams, and the chaos
suite's guarantee ("every recovery path provably recovers") is only as
exhaustive as those seams: a new ``open(..., 'w')`` or ``os.replace``
that bypasses them is durable-state mutation the chaos schedule can
never reach — the coverage rots silently as code grows. This rule makes
the seam set self-policing: any raw write/rename in ``tpu_distalg/``
must sit in a function that also routes through ``faults.inject`` (the
blessed atomic-publish helpers — ``utils/checkpoint.save``,
``data/cache.build_cache`` — already do).
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import Rule, call_name

#: modes that create/overwrite durable bytes ('a' append is the
#: telemetry event log's mode and is not an atomic-publish concern)
_WRITE_MODE_CHARS = ("w", "x")


def _write_mode(call: ast.Call) -> str | None:
    """The mode string of an ``open`` call when it writes, else None."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str) \
            and any(c in mode.value for c in _WRITE_MODE_CHARS):
        return mode.value
    return None


def _has_inject(fn) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name is not None \
                    and name.rsplit(".", 1)[-1] == "inject":
                return True
    return False


class RawDurableWrite(Rule):
    code = "TDA030"
    name = "raw durable write outside a fault seam"
    invariant = ("every durable-state mutation in tpu_distalg/ routes "
                 "through a faults.inject seam or a blessed "
                 "atomic-publish helper, so chaos coverage stays "
                 "exhaustive")

    def applies(self, ctx):
        # the analysis package itself is host-side dev tooling (it
        # writes baselines and applies fixes); it never runs inside a
        # chaos schedule, so its writes are not seam-coverage gaps
        return ctx.is_library and "/analysis/" not in ctx.path

    def check(self, ctx):
        yield from self._scan(ctx, ctx.tree, covered=False)

    def _scan(self, ctx, node, covered):
        for child in ast.iter_child_nodes(node):
            child_covered = covered
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                child_covered = covered or _has_inject(child)
            if isinstance(child, ast.Call):
                name = call_name(child)
                if name == "open" and not covered:
                    mode = _write_mode(child)
                    if mode is not None:
                        yield self.violation(
                            ctx, child,
                            f"raw open(..., {mode!r}) outside any "
                            f"faults.inject seam — route durable "
                            f"writes through utils/checkpoint.save, "
                            f"data/cache.build_cache, or add an "
                            f"injection point so chaos schedules can "
                            f"reach this write")
                elif name in ("os.replace", "os.rename") \
                        and not covered:
                    yield self.violation(
                        ctx, child,
                        f"{name}() outside any faults.inject seam — "
                        f"a publish/rename the chaos suite cannot "
                        f"exercise; use the blessed atomic-publish "
                        f"helpers or add an injection point")
            yield from self._scan(ctx, child, child_covered)


RULES = (RawDurableWrite(),)
