"""Cross-module lock discipline — TDA103.

TDA020 already polices the single-file convention (a thread body's
shared-state write holds *a* lock), but it cannot see the cross-file
failure: two thread entries in DIFFERENT modules each dutifully lock —
different locks — around writes to the same attribute. Each file lints
clean; the program still has the r5 spliced-ADVICE race, just spread
across an import boundary.

Detection, over the project graph: every thread-entry function's
attribute writes are collected with the set of lock-ish names held at
the write (``with self._lock:`` → ``{_lock}``). Writes are grouped
cross-module — ``self.attr`` writes by (class, attr) so unrelated
classes that happen to share a field name never collide; other writes
by attribute name, and only across modules that share an import edge
(an unconnected coincidence is noise, not shared state). A group
spanning two or more modules whose lock sets have an EMPTY
intersection is the finding: no common lock orders those writes.

Heuristic on purpose: lock identity is by NAME segment, so two
modules locking distinct objects both called ``_lock`` pass — the
rule trades that false-negative for zero-alias-analysis simplicity,
the same bargain TDA020 struck.
"""

from __future__ import annotations

import collections

from tpu_distalg.analysis.project import ProjectRule


class CrossModuleLockDiscipline(ProjectRule):
    code = "TDA103"
    name = "cross-module thread writes without a common lock"
    invariant = ("an attribute written from thread entries in two or "
                 "more modules is written under one shared lock, not "
                 "one lock per module")

    def check_project(self, project):
        groups: dict = collections.defaultdict(list)
        for s in project.library():
            for w in s["thread_writes"]:
                key = (("self", w["cls"], w["attr"]) if w["self"]
                       else ("obj", w["attr"]))
                groups[key].append((s, w))
        for key, sites in sorted(groups.items()):
            mods = sorted({s["module"] for s, _ in sites})
            if len(mods) < 2:
                continue
            if key[0] == "obj" and not all(
                    project.connected(mods[0], m) or
                    any(project.connected(m, m2) for m2 in mods
                        if m2 != m)
                    for m in mods):
                continue
            common = None
            for _, w in sites:
                locks = set(w["locks"])
                common = locks if common is None else common & locks
            if common:
                continue
            attr = key[-1]
            for s, w in sites:
                others = ", ".join(m for m in mods
                                   if m != s["module"])
                held = (f"under {'/'.join(w['locks'])}"
                        if w["locks"] else "with no lock held")
                yield self.project_violation(
                    project, s["path"], w["line"],
                    f"{w['entry']} writes '{attr}' {held}, but "
                    f"thread entries in {others} also write it under "
                    f"a DIFFERENT lock — no common lock orders these "
                    f"writes (the cross-file race TDA020 cannot "
                    f"see); share one lock object across the "
                    f"modules")


RULES = (CrossModuleLockDiscipline(),)
