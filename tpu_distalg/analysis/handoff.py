"""Subprocess config handoff — TDA101.

The bug class, caught twice in PR 13 review alone: the CLI parses a
flag into a config field, a launcher re-spawns that role as a
subprocess via ``python -m tpu_distalg.cli ...`` — and forgets to
forward the flag. The child then runs on the DEFAULT: the coordinator
trains a different task (``--train-json``, round 1) or runs alien
heartbeat/deadline/grace timings (round 2). Nothing crashes; the two
processes just quietly disagree.

Detection, over the project graph: *consumption sites* are
``SomethingConfig(field=args.dest, ...)`` constructions anywhere (with
one level of local dataflow, so ``spec = SyncSpec.parse(args.sync)``
still maps ``staleness=spec.staleness`` back to ``--sync``); the
argparse registry (every literal ``add_argument("--flag")``) maps each
dest to its flag spelling. *Spawners* are functions that take a
parameter annotated with that config type AND build a
``python -m *.cli`` argv. For every config field consumed from args,
the spawner's argv literals must contain at least one of the field's
source flags — ANY one, because alternates like ``--train-json``
(which overrides ``--algo``/``--n-rows``) legitimately subsume the
rest.

Fields built from values the dataflow cannot see (derived in helpers,
environment fallbacks past one hop) are not checked — the rule's
promise is "no flag the CLI demonstrably feeds this field is dropped",
not full value tracking.
"""

from __future__ import annotations

import collections

from tpu_distalg.analysis.project import ProjectRule


class SubprocessConfigHandoff(ProjectRule):
    code = "TDA101"
    name = "config field not forwarded to a spawned role"
    invariant = ("every config field the CLI feeds from a flag is "
                 "forwarded by the argv builder that re-spawns the "
                 "role — a lossy handoff trains/serves a different "
                 "configuration than the caller asked for")

    def check_project(self, project):
        dest_flags: dict = collections.defaultdict(set)
        consumed: dict = collections.defaultdict(dict)
        for s in project.library():
            for dest, flags in s["argparse_flags"].items():
                dest_flags[dest].update(flags)
            for call in s["config_calls"]:
                fields = consumed[call["config"]]
                for field, dests in call["fields"].items():
                    fields.setdefault(field, set()).update(dests)
        for s in project.library():
            for sp in s["spawners"]:
                have = set(sp["flags"])
                for cfg in sp["configs"]:
                    for field, dests in sorted(
                            consumed.get(cfg, {}).items()):
                        need = set()
                        for d in sorted(dests):
                            need |= dest_flags.get(d, set())
                        if need and not (need & have):
                            yield self.project_violation(
                                project, s["path"], sp["line"],
                                f"{cfg}.{field} is fed from the CLI "
                                f"({'/'.join(sorted(need))}) but "
                                f"{sp['func']} builds a subprocess "
                                f"argv that forwards none of those "
                                f"flags — the spawned role runs on "
                                f"the default (the --train-json "
                                f"class); forward one of them")


RULES = (SubprocessConfigHandoff(),)
