"""Determinism rules — the bitwise-replay contract (TDA001, TDA002).

PR 3's chaos harness asserts a recovered run is BITWISE-equal to an
undisturbed one, and PR 2's cache format requires content to be a pure
function of the header. Both die the moment library code reads wall
clock into a value, draws from an unseeded RNG, or lets hash/filesystem
iteration order leak into anything emitted.
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis import engine
from tpu_distalg.analysis.engine import Rule, call_name

#: wall-clock reads that poison a replayed value (time.monotonic /
#: perf_counter measure DURATIONS and are fine)
_WALL_CLOCK = {
    "time.time", "time.time_ns",
    "datetime.now", "datetime.datetime.now",
    "datetime.utcnow", "datetime.datetime.utcnow",
    "datetime.today", "datetime.datetime.today",
    "date.today", "datetime.date.today",
}

#: the module-level (hidden-global-state, unseedable-per-call) random API
_RANDOM_FNS = {
    "random", "randint", "randrange", "uniform", "shuffle", "choice",
    "choices", "sample", "gauss", "normalvariate", "betavariate",
    "expovariate", "vonmisesvariate", "seed", "getrandbits",
}

#: np.random.X that IS the seeded API (everything else on np.random is
#: the legacy global-state interface)
_NP_SEEDED_OK = {
    "default_rng", "Generator", "SeedSequence", "PCG64", "Philox",
    "MT19937", "BitGenerator",
}


class WallClockAndUnseededRandom(Rule):
    code = "TDA001"
    name = "wall-clock / unseeded RNG in library code"
    invariant = ("bitwise replay: every value a run produces must be a "
                 "function of (config, seed, step)")

    def applies(self, ctx):
        # library code only; telemetry OWNS wall-clock timestamps (they
        # annotate events, they never feed a computed value)
        return ctx.is_library and not ctx.is_telemetry

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            if name in _WALL_CLOCK:
                yield self.violation(
                    ctx, node,
                    f"{name}() in library code — wall clock voids the "
                    f"bitwise-replay contract; use time.monotonic()/"
                    f"perf_counter() for durations, or thread a "
                    f"timestamp in from the caller")
            elif name.startswith("random.") \
                    and name.split(".", 1)[1] in _RANDOM_FNS:
                yield self.violation(
                    ctx, node,
                    f"{name}() uses the process-global RNG — replay "
                    f"cannot reseed it per call site; use "
                    f"random.Random(seed) (or jax threefry keyed on "
                    f"the step)")
            elif (name.startswith("np.random.")
                  or name.startswith("numpy.random.")):
                fn = name.rsplit(".", 1)[1]
                if fn not in _NP_SEEDED_OK:
                    yield self.violation(
                        ctx, node,
                        f"{name}() is numpy's legacy global-state RNG; "
                        f"use np.random.default_rng(seed) so the draw "
                        f"is a function of an explicit seed")


#: iteration sources whose order is hash- or filesystem-dependent
_FILESYSTEM_CALLS = {"os.listdir", "listdir", "glob.glob",
                     "glob.iglob", "iglob"}
_HASH_CALLS = {"set", "frozenset"}
_UNORDERED_CALLS = _FILESYSTEM_CALLS | _HASH_CALLS


class UnorderedIteration(Rule):
    code = "TDA002"
    name = "unordered iteration feeding downstream order"
    invariant = ("collective and serialization order must not depend "
                 "on hash seed or filesystem enumeration order")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            found = self._unordered(node.iter)
            if found is not None:
                src, kind = found
                yield self.violation(
                    ctx, node,
                    f"iterating {src} — its order is {kind}-dependent "
                    f"and will differ across runs/hosts; wrap in "
                    f"sorted(...) when the order can reach a "
                    f"collective, a serialized artifact, or any "
                    f"emitted output")

    @staticmethod
    def _unordered(it) -> tuple[str, str] | None:
        if isinstance(it, (ast.Set, ast.SetComp)):
            return "a set literal", "hash"
        if isinstance(it, ast.Call):
            name = engine.call_name(it)
            if name in _FILESYSTEM_CALLS:
                return f"{name}(...)", "filesystem-enumeration"
            if name in _HASH_CALLS:
                return f"{name}(...)", "hash"
        return None


RULES = (WallClockAndUnseededRandom(), UnorderedIteration())
