"""The ``tda lint`` front-end — arguments, output, ruff chaining.

Exit codes: 0 clean (baselined findings included), 1 un-baselined
violations or stale baseline entries (or a ruff failure when chained),
2 usage errors. The whole run executes inside a telemetry ``lint`` span
with per-code counters, so a CI run under ``--telemetry-dir`` leaves
the same structured record every other subsystem does.
"""

from __future__ import annotations

import collections
import json
import os
import shutil
import subprocess
import sys

from tpu_distalg.analysis import baseline as blmod
from tpu_distalg.analysis import engine, fixes
from tpu_distalg.analysis import project as projmod
from tpu_distalg.telemetry import events as tevents

#: the repo's default lint surface (existing entries only, so the
#: command works from any subdirectory too)
DEFAULT_PATHS = ("tpu_distalg", "tests", "scripts", "bench.py")

#: the project-graph summary cache home (shared with bench's caches);
#: silently skipped when unwritable
CACHE_DIR = ".bench_cache"


def add_parser_args(p):
    p.add_argument("paths", nargs="*", metavar="PATH",
                   help="files/directories to lint (default: "
                        "tpu_distalg/ tests/ bench.py, those that "
                        "exist)")
    p.add_argument("--format", default="text",
                   choices=["text", "json"],
                   help="text (one finding per line) or json (for CI)")
    p.add_argument("--baseline", type=str, default=None,
                   metavar="FILE",
                   help="suppress findings recorded in FILE "
                        "(default: ./lint_baseline.json when present); "
                        "stale entries are an error")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline file from the current "
                        "findings and exit 0")
    p.add_argument("--select", type=str, default=None, metavar="CODES",
                   help="comma-separated TDA codes to run (default "
                        "all)")
    p.add_argument("--ignore", type=str, default=None, metavar="CODES",
                   help="comma-separated TDA codes to skip")
    p.add_argument("--fix", action="store_true",
                   help="apply the mechanically-safe fixes (TDA021 "
                        "daemon=False; scaffold reasonless "
                        "suppressions; remove unused ones) and "
                        "re-lint")
    p.add_argument("--changed", action="store_true",
                   help="incremental mode: run the per-file TDA0xx "
                        "rules only over git-modified files, while "
                        "the TDA1xx project graph still covers the "
                        "whole surface (summaries content-hash-"
                        "cached under .bench_cache/); stale-baseline "
                        "errors are skipped (partial view)")
    p.add_argument("--no-ruff", action="store_true",
                   help="skip the chained ruff run even when ruff is "
                        "installed")


def add_protocol_args(p):
    p.add_argument("paths", nargs="*", metavar="PATH",
                   help="files/directories to extract the wire "
                        "contract from (default: the lint surface)")
    p.add_argument("--format", default="text",
                   choices=["text", "json", "md"],
                   help="text (aligned table), json (for CI), or md "
                        "(the docs/PROTOCOL.md spelling)")
    p.add_argument("--check", nargs="?", const="docs/PROTOCOL.md",
                   default=None, metavar="FILE",
                   help="diff the extracted contract against the "
                        "committed markdown (default "
                        "docs/PROTOCOL.md); exit 1 on drift")


def run_protocol(args) -> int:
    """``tda protocol`` — render the extracted wire contract, or
    ``--check`` it against the committed ``docs/PROTOCOL.md`` (same
    docs-can-never-drift shape as ``check_readme_claims.py``)."""
    from tpu_distalg.analysis import protocol as protomod

    paths = list(args.paths) or [p for p in DEFAULT_PATHS
                                 if os.path.exists(p)]
    if not paths:
        print("tda protocol: no paths given and none of "
              f"{'/'.join(DEFAULT_PATHS)} exist here", file=sys.stderr)
        return 2
    try:
        files = engine.iter_python_files(paths)
        with tevents.span("protocol", files=len(files)):
            proj, _ = projmod.build_project(files,
                                            cache_dir=CACHE_DIR)
            contract = protomod.build_contract(proj)
            tevents.gauge("protocol.frame_kinds",
                          len(contract["frames"]))
            if args.check is not None:
                return _check_protocol_doc(args.check, contract)
            if args.format == "json":
                print(json.dumps(protomod.render_json(contract),
                                 indent=1))
            elif args.format == "md":
                print(protomod.render_md(contract))
            else:
                print(protomod.render_text(contract))
        return 0
    except (FileNotFoundError, ValueError) as e:
        print(f"tda protocol: {e}", file=sys.stderr)
        return 2


def _check_protocol_doc(doc_path: str, contract) -> int:
    from tpu_distalg.analysis import protocol as protomod

    want = protomod.render_md(contract)
    try:
        with open(doc_path, encoding="utf-8") as f:
            have = f.read()
    except OSError as e:
        print(f"FAIL {doc_path}: unreadable ({e}); regenerate with "
              f"`python -m tpu_distalg.cli protocol --format md > "
              f"{doc_path}`")
        return 1
    if have.strip() == want.strip():
        print(f"ok: {doc_path} matches the extracted wire contract")
        return 0
    want_l, have_l = want.strip().splitlines(), have.strip().splitlines()
    n_shown = 0
    for i in range(max(len(want_l), len(have_l))):
        w = want_l[i] if i < len(want_l) else "<missing>"
        h = have_l[i] if i < len(have_l) else "<missing>"
        if w != h:
            print(f"FAIL {doc_path}:{i + 1}:")
            print(f"  committed: {h}")
            print(f"  extracted: {w}")
            n_shown += 1
            if n_shown >= 10:
                print("  ... (further drift elided)")
                break
    print(f"FAIL {doc_path} drifted from the code; regenerate with "
          f"`python -m tpu_distalg.cli protocol --format md > "
          f"{doc_path}`")
    return 1


def _codes(arg: str | None):
    if arg is None:
        return None
    return tuple(c.strip().upper() for c in arg.split(",")
                 if c.strip())


def run_lint(args) -> int:
    from tpu_distalg.analysis import PROJECT_RULES, RULES

    paths = list(args.paths) or [p for p in DEFAULT_PATHS
                                 if os.path.exists(p)]
    if not paths:
        print("tda lint: no paths given and none of "
              f"{'/'.join(DEFAULT_PATHS)} exist here", file=sys.stderr)
        return 2
    try:
        files = engine.iter_python_files(paths)
        select, ignore = _codes(args.select), _codes(args.ignore)
        with tevents.span("lint", files=len(files)):
            rc = _run(args, files, RULES, PROJECT_RULES, select,
                      ignore)
        return rc
    except (FileNotFoundError, ValueError) as e:
        print(f"tda lint: {e}", file=sys.stderr)
        return 2


def _git_changed() -> set | None:
    """Worktree-modified .py paths (staged + unstaged + untracked),
    norm_path-spelled RELATIVE TO THE CWD (git reports repo-root-
    relative paths; a subdirectory run must still intersect with the
    cwd-relative lint file list); None (= lint everything) when git is
    absent or this is not a work tree."""
    try:
        proc = subprocess.run(
            ["git", "status", "--porcelain", "-uall"],
            capture_output=True, text=True, timeout=30)
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode or top.returncode:
        return None
    root = top.stdout.strip()
    out: set = set()
    for line in proc.stdout.splitlines():
        rest = line[3:]
        if " -> " in rest:                    # rename: new side counts
            rest = rest.split(" -> ", 1)[1]
        rest = rest.strip().strip('"')
        if rest.endswith(".py"):
            # absolute, then norm_path re-relativizes against the cwd
            out.add(engine.norm_path(os.path.join(root, rest)))
    return out


def _run(args, files, rules, project_rules, select, ignore) -> int:
    changed = None
    if args.changed:
        changed = _git_changed()
        if changed is None:
            print("tda lint: --changed needs a git work tree; "
                  "linting everything", file=sys.stderr)

    def lint_once():
        return projmod.lint_tree(
            files, rules, project_rules, select=select,
            ignore=ignore, changed_only=changed,
            cache_dir=CACHE_DIR)

    result = lint_once()
    violations = result.violations

    if args.fix and violations:
        by_file = collections.defaultdict(list)
        for v in violations:
            by_file[v.path].append(v)
        n_fixed = sum(fixes.fix_file(p, vs)
                      for p, vs in by_file.items())
        if n_fixed:
            print(f"tda lint: applied {n_fixed} fix(es); re-linting")
            result = lint_once()
            violations = result.violations

    tevents.counter("lint.files", result.n_linted)
    tevents.counter("lint.cached", result.n_cached)
    tevents.gauge("lint.graph_seconds", result.graph_seconds)
    tevents.counter("lint.violations", len(violations))
    for code, n in collections.Counter(
            v.code for v in violations).items():
        tevents.counter(f"lint.{code}", n)

    bl_path = blmod.resolve(args.baseline)
    if args.update_baseline:
        target = args.baseline or "lint_baseline.json"
        blmod.save(target, violations)
        print(f"tda lint: baseline written: {target} "
              f"({len(violations)} finding(s))")
        return 0

    baselined, stale = [], []
    if bl_path is not None:
        doc = blmod.load(bl_path)
        violations, baselined, stale = blmod.apply(doc, violations)
        if changed is not None:
            # a --changed run sees a PARTIAL violation set: entries
            # for un-linted files would all read as stale
            stale = []

    ruff_files = files if changed is None else \
        [f for f in files if engine.norm_path(f) in changed]
    ruff_rc, ruff_out = (0, "") if args.no_ruff or not ruff_files \
        else _chain_ruff(ruff_files)

    if args.format == "json":
        print(json.dumps({
            "files": len(files),
            "linted": result.n_linted,
            "cached": result.n_cached,
            "graph_seconds": result.graph_seconds,
            "violations": [v.as_dict() for v in violations],
            "baselined": len(baselined),
            "stale_baseline": stale,
            "ruff_rc": ruff_rc,
            "ruff_output": ruff_out,
        }, indent=1))
    else:
        for v in violations:
            print(v.text())
        if ruff_out:
            print(ruff_out, end="")
        for e in stale:
            print(f"{e['path']}: stale baseline entry {e['code']} "
                  f"({e['snippet']!r}) — the violation is gone; "
                  f"regenerate with --update-baseline")
        summary = (f"tda lint: {len(files)} file(s)"
                   + (f" ({result.n_linted} linted, graph over all)"
                      if changed is not None else "")
                   + f", {len(violations)} violation(s)")
        if result.n_cached:
            summary += f", {result.n_cached} graph summar(ies) cached"
        if baselined:
            summary += f", {len(baselined)} baselined"
        if stale:
            summary += f", {len(stale)} stale baseline entr(ies)"
        print(summary)

    tevents.emit("lint_summary", files=len(files),
                 linted=result.n_linted, cached=result.n_cached,
                 violations=len(violations), baselined=len(baselined),
                 stale=len(stale), ruff_rc=ruff_rc)
    return 1 if (violations or stale or ruff_rc) else 0


def _chain_ruff(files) -> tuple[int, str]:
    """One lint entrypoint: when ruff is installed, run the pyproject-
    configured pycodestyle/pyflakes/isort subset over the same files
    and fold its exit code into ours. Output is CAPTURED (not
    inherited) so ``--format json`` stays parseable JSON. Silently
    skipped when absent — the container has no network and must not
    fail on a missing luxury."""
    ruff = shutil.which("ruff")
    if ruff is None:
        return 0, ""
    proc = subprocess.run([ruff, "check", *files],
                          capture_output=True, text=True)
    if proc.returncode:
        print("tda lint: ruff reported findings (chained run)",
              file=sys.stderr)
    return (1 if proc.returncode else 0), proc.stdout
