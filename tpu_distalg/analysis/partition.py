"""Partition-engine coverage (TDA080) — no raw sharding construction
in model or serving code.

The partition-rule engine (``parallel/partition.py``) is the single
place a model's placement lives: one registered :class:`RuleTable` per
model, matched over named pytree leaves, with the generated
place/gather/reshard functions carrying the layout AND the byte
accounting. A hand-built ``NamedSharding`` (or a bare ``PartitionSpec``
fed to a placement op) added to a model afterwards is a layout the
rule table never names: the 2-D ``--mesh-shape`` config can't re-shape
it, ``reshard`` can't plan over it, and the golden-hash placement pins
don't cover it — the exact per-model hand-rolling the engine replaced.
TDA080 keeps ``tpu_distalg/models/`` and ``tpu_distalg/serve/`` clean:
placement goes through ``partition.put`` / ``place`` / ``ensure`` /
``leaf_sharding`` (or stays inside ``parallel/``), never through raw
construction.

Flagged shapes (in ``models/`` and ``serve/``)::

    NamedSharding(mesh, P('data'))          # raw sharding construction
    jax.sharding.NamedSharding(mesh, spec)
    jax.device_put(x, some_sharding)        # hand placement (2+ args)
    jax.device_put(x, device=s)             # keyword spelling
    PositionalSharding(...)                 # any sharding ctor family
    with_sharding_constraint(x, P('data'))  # bare spec into a
                                            #   placement op

Fine::

    partition.put(x, 'w', 'ssgd', mesh)     # the engine owns it
    partition.leaf_sharding('als_train', 'V', mesh)
    shard_map(f, mesh, in_specs=(P('data'),), out_specs=P())
                                            # program specs, not
                                            #   placement — unflagged
    jax.device_put(x)                       # bare staging, no layout
    lax.with_sharding_constraint(x, rows)   # a name bound from the
                                            #   engine — unflagged
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import Rule, call_name

#: sharding constructors whose appearance in model/serve code IS the
#: violation (wherever the result flows)
_SHARDING_CTORS = ("NamedSharding", "PositionalSharding",
                   "GSPMDSharding", "SingleDeviceSharding")

#: placement ops: the second positional arg (or ``device=``) names a
#: layout — exactly what must come from a rule table
_PLACEMENT_OPS = ("device_put", "with_sharding_constraint")


def _tail(name: str | None) -> str | None:
    return None if name is None else name.rsplit(".", 1)[-1]


class RawShardingInModels(Rule):
    code = "TDA080"
    name = "raw sharding construction outside the partition engine"
    invariant = ("every placement in tpu_distalg/models/ and "
                 "tpu_distalg/serve/ routes through the partition-rule "
                 "engine (parallel/partition.py — put/place/ensure/"
                 "leaf_sharding over a registered RuleTable), so one "
                 "rule table names each model's layout, 2-D meshes "
                 "stay a --mesh-shape config, and reshard plans/"
                 "accounts every layout change")

    def applies(self, ctx):
        return ("tpu_distalg/models/" in ctx.path
                or "tpu_distalg/serve/" in ctx.path)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _tail(call_name(node))
            if name in _SHARDING_CTORS:
                yield self.violation(
                    ctx, node,
                    f"raw {name}(...) in model/serve code — placement "
                    f"belongs to a registered rule table; use "
                    f"partition.put/place/ensure, or "
                    f"partition.leaf_sharding(table, leaf, mesh) when "
                    f"a sharding object itself is needed")
                continue
            if name in _PLACEMENT_OPS:
                yield from self._check_placement(ctx, node, name)

    def _check_placement(self, ctx, call: ast.Call, name: str):
        """``device_put(x, s)`` / ``with_sharding_constraint(x, s)``:
        an explicit layout arg is a hand placement UNLESS it is an
        engine call (``partition.*``). A bare name (``rows``) is
        allowed for ``with_sharding_constraint`` only — inside-jit
        constraint code legitimately closes over an engine-derived
        sharding — while ``device_put`` with ANY explicit layout must
        spell the engine call at the site (restored-state re-puts are
        exactly where hand layouts creep back in)."""
        layout = call.args[1] if len(call.args) >= 2 else None
        if layout is None:
            for kw in call.keywords:
                # device_put spells it device=/sharding=,
                # with_sharding_constraint spells it shardings=
                if kw.arg in ("device", "sharding", "shardings"):
                    layout = kw.value
                    break
        if layout is None:
            return  # bare staging: no layout named
        if isinstance(layout, ast.Call):
            lname = call_name(layout) or ""
            if lname.split(".")[0] == "partition":
                return  # engine-derived at the site
            # any other call producing the layout (a spec ctor, a
            # sharding ctor, a local helper) is a hand placement
            yield self.violation(
                ctx, call,
                f"{name}() with a hand-built layout — derive it "
                f"from the rule table instead "
                f"(partition.put/ensure, or partition."
                f"leaf_sharding(table, leaf, mesh))")
            return
        if name == "device_put":
            yield self.violation(
                ctx, call,
                "device_put() with an explicit layout in model/serve "
                "code — route the placement through the partition "
                "engine (partition.put/place/ensure) so the rule "
                "table stays the single owner of this model's layout")


RULES = (RawShardingInModels(),)
