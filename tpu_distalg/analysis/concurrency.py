"""Concurrency rules — thread targets and thread construction (TDA020,
TDA021).

Every background thread this repo runs (telemetry heartbeat, prefetch
producer, supervisor init worker, bench hard-deadline) follows two
conventions that were each earned the hard way: shared state written
from a thread body is written under a lock (the r5 bench's spliced
ADVICE summary was exactly an unlocked dual-writer), and every
``threading.Thread`` states ``daemon=`` explicitly (an inherited
non-daemon default once kept a finished run alive until the driver's
SIGKILL — the difference between rc 0 and a timeout).
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import (Rule, call_name, dotted_name,
                                         root_name)


def _is_thread_call(call: ast.Call) -> bool:
    name = call_name(call)
    return name in ("threading.Thread", "Thread")


def _thread_entry_functions(tree: ast.Module):
    """(function node, how) pairs that run ON a thread: named
    ``target=`` of a Thread(...) call, or ``run`` methods of classes
    whose bases end in ``Thread``."""
    target_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_thread_call(node):
            for kw in node.keywords:
                if kw.arg == "target" and isinstance(kw.value,
                                                     ast.Name):
                    target_names.add(kw.value.id)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            thread_base = any(
                (dotted_name(b) or "").rsplit(".", 1)[-1] == "Thread"
                for b in node.bases)
            if thread_base:
                for item in node.body:
                    if isinstance(item, ast.FunctionDef) \
                            and item.name == "run":
                        yield item, f"{node.name}.run"
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in target_names:
            yield node, f"Thread target {node.name}"


def _lockish(expr) -> bool:
    """``with self._lock: ...`` / ``with _EMIT_LOCK: ...`` — any name
    segment containing 'lock' (case-insensitive) counts; so does the
    ``.acquire()``-less ``with lock_for(x):`` helper shape."""
    for leaf in ast.walk(expr):
        seg = None
        if isinstance(leaf, ast.Name):
            seg = leaf.id
        elif isinstance(leaf, ast.Attribute):
            seg = leaf.attr
        if seg is not None and "lock" in seg.lower():
            return True
    return False


class UnlockedThreadWrite(Rule):
    code = "TDA020"
    name = "unlocked shared-state write from a thread body"
    invariant = ("state shared with a thread is written under a lock "
                 "or handed off through a queue — never bare")

    def check(self, ctx):
        for fn, how in _thread_entry_functions(ctx.tree):
            local = self._locals(fn)
            yield from self._scan(ctx, fn, how, local,
                                  under_lock=False)

    @staticmethod
    def _locals(fn) -> set:
        out = set()
        for node in ast.walk(fn):
            targets = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                                   ast.For)):
                if isinstance(node.target, ast.Name):
                    targets = [node.target]
            out.update(t.id for t in targets)
        return out

    def _scan(self, ctx, node, how, local, under_lock):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            locked = under_lock
            if isinstance(child, (ast.With, ast.AsyncWith)):
                if any(_lockish(item.context_expr)
                       for item in child.items):
                    locked = True
            if isinstance(child, (ast.Assign, ast.AugAssign)) \
                    and not locked:
                targets = (child.targets
                           if isinstance(child, ast.Assign)
                           else [child.target])
                for t in targets:
                    if not isinstance(t, (ast.Attribute,
                                          ast.Subscript)):
                        continue
                    root = root_name(t)
                    if root is None or root in local:
                        continue
                    yield self.violation(
                        ctx, t,
                        f"{how} writes shared state "
                        f"({ast.unparse(t)}) without a lock held in "
                        f"the enclosing scope — wrap in 'with "
                        f"<lock>:' or hand the value through a "
                        f"queue.Queue")
            yield from self._scan(ctx, child, how, local, locked)


class ImplicitThreadDaemon(Rule):
    code = "TDA021"
    name = "threading.Thread without explicit daemon="
    invariant = ("thread lifetime is stated, not inherited — a "
                 "non-daemon leftover blocks interpreter exit; a "
                 "daemon leftover dies mid-write")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_thread_call(node) \
                    and not any(kw.arg == "daemon"
                                for kw in node.keywords):
                yield self.violation(
                    ctx, node,
                    "threading.Thread(...) without daemon= — state "
                    "the lifetime explicitly (daemon=True: may die "
                    "mid-write at exit; daemon=False: must be "
                    "joined); `tda lint --fix` inserts daemon=False, "
                    "the inherited default")


RULES = (UnlockedThreadWrite(), ImplicitThreadDaemon())
