"""Telemetry contract — TDA102 (and the bench-metric collector the
tests share).

Two drift directions, both review-caught historically, both
cross-module:

* a counter/gauge is emitted somewhere in the library but
  ``telemetry/report.py`` never renders it and never waives it — the
  signal exists in JSONL and nowhere a human looks. Every emitted name
  must appear in report.py (a literal in a renderer), match a
  ``PER_WORKER_PREFIXES`` family (rendered as per-worker columns), or
  be listed in ``SUMMARY_ONLY_COUNTERS`` (the explicit "generic
  counters: line is enough" waiver; ``name.*`` entries waive a
  family). F-string names (``f"lint.{code}"``) are checked by their
  static prefix against the family entries.

* a bench metric line's name drifts from ``ALL_METRIC_NAMES`` — the
  CPU-fallback tier then leaves it blank on a dead-backend round
  (rogue emission), or keeps emitting a stale skipped-with-zero line
  forever (canonical-but-unemitted). This was an AST tripwire
  duplicated across three test files; the collector here
  (:func:`metric_contract` / :func:`contract_problems` /
  :func:`assert_registered`) is now the ONE implementation — the
  engine runs it as TDA102 and the tests call it directly.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from tpu_distalg.analysis.project import ProjectRule, _joined_pattern

#: the tuple name that declares the canonical bench metric set
CANONICAL_TUPLE = "ALL_METRIC_NAMES"

#: the report-side waiver table (lives in telemetry/report.py)
WAIVER_TUPLE = "SUMMARY_ONLY_COUNTERS"


# ---------------------------------------------------------------------
# the bench-metric collector (shared with tests/)


@dataclasses.dataclass
class MetricContract:
    """One module's metric emission surface vs its canonical set."""

    path: str
    canonical: tuple
    canonical_line: int
    literals: dict          # name -> first emission line
    patterns: list          # (compiled regex, line) for f-string names


def metric_contract_from_source(source: str,
                                path: str = "bench.py"
                                ) -> MetricContract | None:
    """Parse a module's ``{"metric": ...}`` emission dicts and its
    ``ALL_METRIC_NAMES`` tuple. None when the module declares no
    canonical set."""
    tree = ast.parse(source)
    canonical, can_line = None, 0
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == CANONICAL_TUPLE \
                and isinstance(stmt.value, (ast.Tuple, ast.List)):
            canonical = tuple(
                e.value for e in stmt.value.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str))
            can_line = stmt.lineno
    if canonical is None:
        return None
    literals: dict = {}
    patterns: list = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant)
                    and k.value == "metric"):
                continue
            if isinstance(v, ast.Constant) and isinstance(v.value,
                                                          str):
                literals.setdefault(v.value, node.lineno)
            elif isinstance(v, ast.JoinedStr):
                patterns.append((re.compile(_joined_pattern(v)),
                                 node.lineno))
    return MetricContract(path=path, canonical=canonical,
                          canonical_line=can_line,
                          literals=literals, patterns=patterns)


def bench_contract(repo_root: str | None = None) -> MetricContract:
    """The repo's bench.py contract (the tests' entry point)."""
    root = repo_root or os.getcwd()
    path = os.path.join(root, "bench.py")
    with open(path, encoding="utf-8") as f:
        contract = metric_contract_from_source(f.read(), path)
    if contract is None:
        raise ValueError(f"{path} declares no {CANONICAL_TUPLE}")
    return contract


def contract_problems(contract: MetricContract):
    """Both drift directions: ``(unemitted, rogue)`` where
    ``unemitted`` is canonical names with no emission site and
    ``rogue`` maps non-canonical literal emissions to their line."""
    unemitted = [
        n for n in contract.canonical
        if n not in contract.literals
        and not any(p.match(n) for p, _ in contract.patterns)]
    rogue = {n: line for n, line in sorted(contract.literals.items())
             if n not in contract.canonical}
    return unemitted, rogue


def assert_registered(names, repo_root: str | None = None) -> None:
    """Test helper: each name is canonical AND has a live emission
    site — the one spelling of the membership checks that used to be
    re-implemented per test file."""
    contract = bench_contract(repo_root)
    missing = [n for n in names if n not in contract.canonical]
    assert not missing, (
        f"not in {CANONICAL_TUPLE} (the CPU-fallback tier would "
        f"leave these blank on a dead-backend round): {missing}")
    unemitted, _ = contract_problems(contract)
    dead = [n for n in names if n in unemitted]
    assert not dead, (
        f"registered in {CANONICAL_TUPLE} but no emission site in "
        f"bench.py (renamed phase metric?): {dead}")


# ---------------------------------------------------------------------
# the rule


def _star_covered(name: str, entries) -> bool:
    for w in entries:
        if w == name:
            return True
        if w.endswith("*") and name.startswith(w[:-1]):
            return True
    return False


def _prefix_covered(prefix: str, families) -> bool:
    return any(prefix.startswith(p) or p.startswith(prefix)
               for p in families if p)


class TelemetryContract(ProjectRule):
    code = "TDA102"
    name = "telemetry emission outside the rendered/waived contract"
    invariant = ("every emitted counter/gauge is rendered or "
                 "explicitly waived in telemetry/report.py, and every "
                 "bench metric line is canonical in ALL_METRIC_NAMES "
                 "(and vice versa)")

    def check_project(self, project):
        yield from self._check_counters(project)
        yield from self._check_metrics(project)

    def _check_counters(self, project):
        reports = [s for s in project if s.get("report_like")]
        if not reports:
            return   # no report module on this lint surface
        rendered: set = set()
        waivers: list = []
        families: list = []
        for r in reports:
            rendered.update(r["report_strings"])
            waivers.extend(r["str_tuples"].get(
                WAIVER_TUPLE, {}).get("values", []))
            families.extend(r["str_tuples"].get(
                "PER_WORKER_PREFIXES", {}).get("values", []))
        families += [w[:-1] for w in waivers if w.endswith("*")]
        report_paths = {r["path"] for r in reports}
        seen: set = set()
        emitted_names: set = set()
        emitted_prefixes: set = set()
        for s in project.library():
            if s["path"] in report_paths:
                continue
            for emit in s["counter_emits"]:
                name, prefix = emit["name"], emit["prefix"]
                if name is not None:
                    emitted_names.add(name)
                elif prefix:
                    emitted_prefixes.add(prefix)
                key = (s["path"], name or prefix, emit["line"])
                if key in seen:
                    continue
                seen.add(key)
                if name is not None:
                    ok = name in rendered \
                        or _prefix_covered(name, families) \
                        or _star_covered(name, waivers)
                else:
                    ok = _prefix_covered(prefix, families)
                if ok:
                    continue
                what = f"'{name}'" if name is not None \
                    else f"f-string family '{prefix}…'"
                yield self.project_violation(
                    project, s["path"], emit["line"],
                    f"{emit['kind']} {what} is emitted but "
                    f"telemetry/report.py neither renders nor waives "
                    f"it — a signal nobody can see; add a report "
                    f"line, or list it in {WAIVER_TUPLE} "
                    f"('name' or 'family.*') to state that the "
                    f"generic counters rendering is enough")
        # the reverse direction — waiver rot. An entry matching zero
        # emissions is a retired counter's ghost: it reads as "this
        # signal is accounted for" while waiving nothing, exactly the
        # drift the unused-suppression detector stops for inline pins.
        # Only decidable when the surface actually emits (a lone
        # report-module lint sees no emissions and must stay silent).
        if not emitted_names and not emitted_prefixes:
            return
        for r in reports:
            decl = r["str_tuples"].get(WAIVER_TUPLE)
            if decl is None:
                continue
            for entry in decl["values"]:
                if entry.endswith("*"):
                    fam = entry[:-1]
                    used = any(n.startswith(fam)
                               for n in emitted_names) \
                        or any(fam.startswith(p) or p.startswith(fam)
                               for p in emitted_prefixes)
                else:
                    used = entry in emitted_names \
                        or any(entry.startswith(p)
                               for p in emitted_prefixes)
                if used:
                    continue
                yield self.project_violation(
                    project, r["path"], decl["line"],
                    f"waiver '{entry}' in {WAIVER_TUPLE} matches no "
                    f"emitted counter/gauge on this surface — a "
                    f"retired signal's ghost; remove the entry (or "
                    f"restore the emission it claims to waive)")

    def _check_metrics(self, project):
        # ONE implementation of the drift checks: rebuild the
        # collector's MetricContract from the summary fields and run
        # contract_problems — the rule and the tests cannot diverge
        for s in project.library():
            decl = s["str_tuples"].get(CANONICAL_TUPLE)
            if decl is None:
                continue
            literals = {}
            for d in s["metric_dicts"]:
                if d["name"] is not None:
                    literals.setdefault(d["name"], d["line"])
            contract = MetricContract(
                path=s["path"], canonical=tuple(decl["values"]),
                canonical_line=decl["line"], literals=literals,
                patterns=[(re.compile(d["pattern"]), d["line"])
                          for d in s["metric_dicts"]
                          if d["pattern"] is not None])
            unemitted, rogue = contract_problems(contract)
            for n in unemitted:
                yield self.project_violation(
                    project, s["path"], contract.canonical_line,
                    f"canonical metric '{n}' has no emission "
                    f"site in {s['path']} (renamed phase metric "
                    f"without updating {CANONICAL_TUPLE}?) — the "
                    f"CPU-fallback tier would emit it as a stale "
                    f"skipped-with-zero line forever")
            for n, line in sorted(rogue.items()):
                yield self.project_violation(
                    project, s["path"], line,
                    f"metric '{n}' is emitted but missing from "
                    f"{CANONICAL_TUPLE} — a dead-backend round "
                    f"would leave it blank (the r05 class); "
                    f"register it")


RULES = (TelemetryContract(),)
