"""Pallas hygiene rules — tiling and VMEM budget (TDA040, TDA041).

The repo's kernels carry these constraints as hand-written guards and
hard-won docstrings (``ops/pallas_kmeans.py`` rejects over-budget shift
tables at plan time; ``pallas_pagerank`` documents its ~11M-vertex VMEM
ceiling). These rules move the statically-decidable half of that to
lint time: f32 blocks tile in (8, 128) — a lane dimension that is not a
multiple of 128 pads silently (wasted VMEM + MXU occupancy) or fails in
Mosaic — and the resident block set of one ``pallas_call`` must fit the
VMEM budget. Only LITERALLY-computable shapes are judged (module-level
int constants fold; anything parameterized is skipped), so a flag here
is a certainty, not a guess.
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import (Rule, call_name,
                                         const_int, dotted_name)

#: f32 minimum tile (sublane, lane); bf16 doubles the sublane to 16 —
#: this rule checks the f32 floor, the common denominator the repo's
#: kernels are written against
SUBLANE, LANE = 8, 128

#: the repo's per-kernel resident-block budget (the spmv plan guard and
#: every pallas_call's vmem_limit_bytes are set against ~100-128 MB)
VMEM_BUDGET_BYTES = 128 * 1024 * 1024

_DTYPE_BYTES = {
    "float32": 4, "f32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1,
    "float64": 8, "int64": 8, "uint64": 8,
}

_NON_VMEM_SPACES = {"SMEM", "ANY", "HBM", "SEMAPHORE"}


def _block_shape(call: ast.Call):
    """The shape tuple node of a BlockSpec(...) call, or None."""
    shape = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "block_shape":
            shape = kw.value
    return shape if isinstance(shape, ast.Tuple) else None


def _memory_space_tail(call: ast.Call) -> str | None:
    for kw in call.keywords:
        if kw.arg == "memory_space":
            name = None
            v = kw.value
            if isinstance(v, (ast.Name, ast.Attribute)):
                name = dotted_name(v)
            return name.rsplit(".", 1)[-1] if name else "?"
    return None


def _iter_blockspecs(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name is not None \
                    and name.rsplit(".", 1)[-1] == "BlockSpec":
                yield node


class BlockShapeTiling(Rule):
    code = "TDA040"
    name = "BlockSpec shape off the (8, 128) f32 tile"
    invariant = ("VMEM blocks tile in (sublane=8, lane=128) for f32 — "
                 "off-tile shapes pad silently or fail in Mosaic")

    def check(self, ctx):
        for spec in _iter_blockspecs(ctx.tree):
            space = _memory_space_tail(spec)
            if space in _NON_VMEM_SPACES:
                continue  # SMEM scalars etc. tile differently
            shape = _block_shape(spec)
            if shape is None or len(shape.elts) < 2:
                continue
            dims = [const_int(e, ctx.consts) for e in shape.elts]
            lane, sub = dims[-1], dims[-2]
            # lane/sublane 1 are the degenerate broadcast/column
            # shapes Mosaic handles natively (this repo's (1, L)
            # constant rows and (b, 1) per-row scalar columns) — only
            # real off-tile sizes are flagged
            if lane is not None and lane != 1 and lane % LANE != 0:
                yield self.violation(
                    ctx, spec,
                    f"BlockSpec lane (last) dimension {lane} is not a "
                    f"multiple of {LANE} — the block pads to the next "
                    f"{LANE}-lane tile (wasted VMEM/MXU) or fails to "
                    f"lower; pad the array and mask instead")
            # sublane 1 is the broadcast-row shape Mosaic handles
            # natively (the repo's (1, L) constant blocks) — only
            # flag real off-tile sublane counts
            if sub is not None and sub != 1 and sub % SUBLANE != 0:
                yield self.violation(
                    ctx, spec,
                    f"BlockSpec sublane dimension {sub} is not a "
                    f"multiple of {SUBLANE} (f32 tile floor; bf16 "
                    f"needs 16) — round the block up and mask the "
                    f"tail")


def _dtype_bytes(node) -> int:
    name = None
    if isinstance(node, (ast.Name, ast.Attribute)):
        d = dotted_name(node)
        name = d.rsplit(".", 1)[-1] if d else None
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    return _DTYPE_BYTES.get(name or "", 4)


class VmemFootprint(Rule):
    code = "TDA041"
    name = "resident VMEM footprint over budget"
    invariant = (f"the blocks one pallas_call holds resident must fit "
                 f"the {VMEM_BUDGET_BYTES >> 20} MB VMEM budget — "
                 f"checked at lint time for statically-sized kernels")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None \
                    or name.rsplit(".", 1)[-1] != "pallas_call":
                continue
            total = 0
            for kw in node.keywords:
                if kw.arg in ("in_specs", "out_specs"):
                    for spec in ast.walk(kw.value):
                        if isinstance(spec, ast.Call) and (
                                call_name(spec) or ""
                        ).rsplit(".", 1)[-1] == "BlockSpec":
                            total += self._spec_bytes(spec, ctx)
                elif kw.arg == "scratch_shapes":
                    for scr in ast.walk(kw.value):
                        if isinstance(scr, ast.Call) and (
                                call_name(scr) or ""
                        ).rsplit(".", 1)[-1] == "VMEM":
                            total += self._scratch_bytes(scr, ctx)
            if total > VMEM_BUDGET_BYTES:
                yield self.violation(
                    ctx, node,
                    f"statically-computable resident blocks total "
                    f"{total / (1 << 20):.0f} MB — over the "
                    f"{VMEM_BUDGET_BYTES >> 20} MB VMEM budget; "
                    f"shrink the block shapes or stream through a "
                    f"grid axis (this sum counts only "
                    f"literal-shaped specs, so it is a LOWER bound)")

    @staticmethod
    def _spec_bytes(spec: ast.Call, ctx) -> int:
        if _memory_space_tail(spec) in _NON_VMEM_SPACES:
            return 0
        shape = _block_shape(spec)
        if shape is None:
            return 0
        dims = [const_int(e, ctx.consts) for e in shape.elts]
        if any(d is None for d in dims):
            return 0  # parameterized — not statically computable
        n = 1
        for d in dims:
            n *= d
        return n * 4  # BlockSpec carries no dtype; assume f32

    @staticmethod
    def _scratch_bytes(scr: ast.Call, ctx) -> int:
        if not scr.args or not isinstance(scr.args[0], ast.Tuple):
            return 0
        dims = [const_int(e, ctx.consts)
                for e in scr.args[0].elts]
        if any(d is None for d in dims):
            return 0
        n = 1
        for d in dims:
            n *= d
        itemsize = (_dtype_bytes(scr.args[1])
                    if len(scr.args) > 1 else 4)
        return n * itemsize


RULES = (BlockShapeTiling(), VmemFootprint())
