"""Geometry-literal discipline (TDA120) — hand-pinned tuner knobs in
``models/`` and ``cluster/`` stay in the tuner's default tables.

The autotuner (``tpu_distalg/tune/``) makes run geometry a MEASURED
decision: ``tune/defaults.py`` is the one table of hand-pinned values
(what ``--tune off`` runs), and the resolver overrides them per rig
from a profiled cost model. A fresh int literal assigned to one of the
geometry knob names in ``tpu_distalg/models/`` or
``tpu_distalg/cluster/`` — a ``bucket_elems = 32768`` default, an
``n_shards: int = 4``, a ``block_rows=1024`` call-site pin — is
exactly the drift the tuner exists to end: one rig's folklore
re-hard-coded where neither the default table nor the resolver can
see it. The README's canonical numbers then silently depend on a
spelling no profile can re-derive.

Flagged (in ``models/`` and ``cluster/``)::

    block_rows = 1024                    # not in BLOCK_ROWS' values
    def f(*, ps_shards: int = 4): ...    # annotated default off-table
    RowStore(center, n_shards=4)         # call-site pin off-table

Fine::

    block_rows = 4096                    # a value the table spells
    n_shards=tune_defaults.PS_SHARDS     # sourced FROM the table
    bucket = spec.bucket_elems           # config-carried, not pinned
    block_rows = cfg.block_rows          # ditto
    n_shards = 4  # tda: ignore[TDA120] -- <why this rig-pin is right>

Values are folded with the module-consts resolver (``1 << 16`` and
``2 * HALF`` count as literals), so arithmetic re-spellings don't
evade the table.
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import Rule, const_int

from tpu_distalg.tune.defaults import GEOMETRY_KNOBS


def _keyword_pins(call: ast.Call):
    for kw in call.keywords:
        if kw.arg in GEOMETRY_KNOBS:
            yield kw.arg, kw.value, kw.value


def _assign_pins(node):
    """``(knob, value-node, report-node)`` for assignment-like pins."""
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id in GEOMETRY_KNOBS:
                yield tgt.id, node.value, node
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        tgt = node.target
        if isinstance(tgt, ast.Name) and tgt.id in GEOMETRY_KNOBS:
            yield tgt.id, node.value, node
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = node.args
        pos = a.posonlyargs + a.args
        for arg, default in zip(pos[len(pos) - len(a.defaults):],
                                a.defaults):
            if arg.arg in GEOMETRY_KNOBS:
                yield arg.arg, default, default
        for arg, default in zip(a.kwonlyargs, a.kw_defaults):
            if default is not None and arg.arg in GEOMETRY_KNOBS:
                yield arg.arg, default, default


class PinnedGeometryLiteral(Rule):
    code = "TDA120"
    name = "hand-pinned geometry literal outside the tuner tables"
    invariant = ("geometry knobs in models/ and cluster/ carry values "
                 "the tune/defaults.py table spells (or a reasoned "
                 "rig-pin), so the autotuner's resolver sees every "
                 "knob it is supposed to own")

    def applies(self, ctx):
        return ("tpu_distalg/models/" in ctx.path
                or "tpu_distalg/cluster/" in ctx.path)

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                pins = _keyword_pins(node)
            else:
                pins = _assign_pins(node)
            for knob, value, where in pins:
                folded = const_int(value, ctx.consts)
                if folded is None:
                    continue    # config-carried / attribute-sourced
                allowed = GEOMETRY_KNOBS[knob]
                if folded in allowed:
                    continue
                yield self.violation(
                    ctx, where,
                    f"geometry knob '{knob}' pinned to {folded}, "
                    f"which the tuner's default table does not spell "
                    f"(tune/defaults.py allows "
                    f"{', '.join(map(str, allowed))}) — one rig's "
                    f"folklore the resolver cannot see; source the "
                    f"value from tune.defaults, thread it through "
                    f"config, or keep the pin with a reasoned "
                    f"suppression")


RULES = (PinnedGeometryLiteral(),)
