"""Cluster transport discipline (TDA090).

The multi-process runtime's availability and safety contract is
structural, like the serving layer's (TDA060): every blocking socket
receive in ``tpu_distalg/cluster/`` is DEADLINE-BOUNDED (a partitioned
peer must surface as :class:`~tpu_distalg.cluster.transport.
TransportTimeout`, never wedge a coordinator thread forever), and
every payload that hits the wire is LENGTH-PREFIX FRAMED through the
transport's encoder (an unframed ``sendall`` desynchronizes the
stream — the receiver reads the bytes as a length prefix and either
allocates garbage or wedges; it is also how pickle-shaped ad-hoc
payloads would sneak in). One forgotten bare ``recv()`` or raw
``sendall(b"...")`` silently voids both; TDA090 makes the convention
machine-checked.

Flagged shapes::

    conn, _ = listener.accept()        # no settimeout in scope
    data = sock.recv(4096)             # no settimeout in scope
    sock.settimeout(None)              # spelled-out block-forever
    sock.sendall(b"hello")             # unframed payload
    sock.sendall(payload)              # payload not built by a
                                       #   frame encoder in scope

Fine::

    sock.settimeout(remaining)         # then recv/accept in the same
    chunk = sock.recv(n)               #   function: deadline-bounded
    buf = encode_frame(kind, meta)     # framed, then sent
    sock.sendall(buf)
    sock.sendall(encode_frame(...))    # framed inline

The deadline check is function-scoped: a ``.settimeout(x)`` call with
a non-``None`` argument anywhere in the SAME function body arms every
receive in it (the transport's ``_recv_exact`` shape — recompute the
remaining budget, set it, read). ``settimeout(None)`` does not count:
that is the spelled-out block-forever.
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import Rule, call_name

_RECV_METHODS = ("recv", "recvfrom", "recv_into", "recvmsg")


def _attr_method(call: ast.Call) -> str | None:
    """The trailing attribute name of a method-style call
    (``x.y.recv(...)`` -> ``'recv'``), else None."""
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _direct_calls(fn: ast.AST):
    """Calls belonging DIRECTLY to ``fn`` — nested function bodies are
    excluded (they are checked as their own scope, with their own
    settimeout evidence)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _has_deadline(fn: ast.AST) -> bool:
    """True when the function arms a non-None socket timeout."""
    for call in _direct_calls(fn):
        if _attr_method(call) != "settimeout":
            continue
        if call.args and isinstance(call.args[0], ast.Constant) \
                and call.args[0].value is None:
            continue  # settimeout(None): the spelled-out block-forever
        if call.args or call.keywords:
            return True
    return False


def _frame_names(tree: ast.AST) -> set[str]:
    """Names that produce framed bytes: anything imported from or
    defined as a ``*frame*`` encoder (``encode_frame`` is the
    transport's; a sibling module may alias it)."""
    names = {"encode_frame"}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and "frame" in node.name and node.name.startswith(
                    ("encode", "frame", "make", "build")):
            names.add(node.name)
    return names


def _is_framed(arg, framed_vars: set[str], frame_fns: set[str]) -> bool:
    if isinstance(arg, ast.Call):
        name = call_name(arg)
        return bool(name) and (
            name.split(".")[-1] in frame_fns
            or "frame" in name.split(".")[-1])
    if isinstance(arg, ast.Name):
        return arg.id in framed_vars
    return False


class ClusterTransportDiscipline(Rule):
    code = "TDA090"
    name = ("unbounded socket receive / unframed sendall in "
            "cluster/")
    invariant = (
        "the cluster runtime stays live and speaks one wire format: "
        "every blocking socket receive is deadline-bounded (a "
        "partition surfaces as TransportTimeout, never a wedged "
        "thread) and every sendall payload is length-prefix framed "
        "by the transport encoder (an unframed write desynchronizes "
        "the stream)")

    def applies(self, ctx):
        return "tpu_distalg/cluster/" in ctx.path

    def check(self, ctx):
        frame_fns = _frame_names(ctx.tree)
        scopes = [n for n in ast.walk(ctx.tree)
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))]
        for fn in scopes:
            yield from self._check_scope(ctx, fn, frame_fns)

    def _check_scope(self, ctx, fn, frame_fns):
        has_deadline = _has_deadline(fn)
        # variables assigned from a frame encoder in this scope are
        # framed payloads (buf = encode_frame(...); sock.sendall(buf))
        framed_vars: set[str] = set()
        for call in _direct_calls(fn):
            method = _attr_method(call)
            if method == "settimeout" and call.args and \
                    isinstance(call.args[0], ast.Constant) and \
                    call.args[0].value is None and not has_deadline:
                yield self.violation(
                    ctx, call,
                    "settimeout(None) is the spelled-out block-"
                    "forever — every blocking receive in cluster/ "
                    "must carry a real deadline (TransportTimeout is "
                    "the partition observable)")
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _is_framed(node.value, framed_vars, frame_fns):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        framed_vars.add(tgt.id)
        for call in _direct_calls(fn):
            method = _attr_method(call)
            if method in _RECV_METHODS or method == "accept":
                if not has_deadline:
                    yield self.violation(
                        ctx, call,
                        f".{method}() with no socket timeout armed in "
                        f"this function — a dead or partitioned peer "
                        f"wedges this thread forever; call "
                        f".settimeout(<remaining deadline>) before "
                        f"blocking (transport._recv_exact is the "
                        f"shape)")
            elif method == "sendall":
                if not call.args or not _is_framed(
                        call.args[0], framed_vars, frame_fns):
                    yield self.violation(
                        ctx, call,
                        "sendall of a payload not built by the frame "
                        "encoder — an unframed write desynchronizes "
                        "the length-prefixed stream (and is how "
                        "ad-hoc pickle-shaped payloads sneak in); "
                        "route it through transport.encode_frame / "
                        "send_frame")


RULES = (ClusterTransportDiscipline(),)
