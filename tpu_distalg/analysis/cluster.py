"""Cluster transport discipline (TDA090).

The multi-process runtime's availability and safety contract is
structural, like the serving layer's (TDA060): every blocking socket
receive in ``tpu_distalg/cluster/`` is DEADLINE-BOUNDED (a partitioned
peer must surface as :class:`~tpu_distalg.cluster.transport.
TransportTimeout`, never wedge a coordinator thread forever), and
every payload that hits the wire is LENGTH-PREFIX FRAMED through the
transport's encoder (an unframed ``sendall`` desynchronizes the
stream — the receiver reads the bytes as a length prefix and either
allocates garbage or wedges; it is also how pickle-shaped ad-hoc
payloads would sneak in). One forgotten bare ``recv()`` or raw
``sendall(b"...")`` silently voids both; TDA090 makes the convention
machine-checked.

Flagged shapes::

    conn, _ = listener.accept()        # no settimeout in scope
    data = sock.recv(4096)             # no settimeout in scope
    sock.settimeout(None)              # spelled-out block-forever
    sock.sendall(b"hello")             # unframed payload
    sock.sendall(payload)              # payload not built by a
                                       #   frame encoder in scope

Fine::

    sock.settimeout(remaining)         # then recv/accept in the same
    chunk = sock.recv(n)               #   function: deadline-bounded
    buf = encode_frame(kind, meta)     # framed, then sent
    sock.sendall(buf)
    sock.sendall(encode_frame(...))    # framed inline

The deadline check is function-scoped: a ``.settimeout(x)`` call with
a non-``None`` argument anywhere in the SAME function body arms every
receive in it (the transport's ``_recv_exact`` shape — recompute the
remaining budget, set it, read). ``settimeout(None)`` does not count:
that is the spelled-out block-forever.
"""

from __future__ import annotations

import ast

from tpu_distalg.analysis.engine import Rule, call_name

_RECV_METHODS = ("recv", "recvfrom", "recv_into", "recvmsg")


def _attr_method(call: ast.Call) -> str | None:
    """The trailing attribute name of a method-style call
    (``x.y.recv(...)`` -> ``'recv'``), else None."""
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def _direct_calls(fn: ast.AST):
    """Calls belonging DIRECTLY to ``fn`` — nested function bodies are
    excluded (they are checked as their own scope, with their own
    settimeout evidence)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _has_deadline(fn: ast.AST) -> bool:
    """True when the function arms a non-None socket timeout."""
    for call in _direct_calls(fn):
        if _attr_method(call) != "settimeout":
            continue
        if call.args and isinstance(call.args[0], ast.Constant) \
                and call.args[0].value is None:
            continue  # settimeout(None): the spelled-out block-forever
        if call.args or call.keywords:
            return True
    return False


def _frame_names(tree: ast.AST) -> set[str]:
    """Names that produce framed bytes: anything imported from or
    defined as a ``*frame*`` encoder (``encode_frame`` is the
    transport's; a sibling module may alias it)."""
    names = {"encode_frame"}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and "frame" in node.name and node.name.startswith(
                    ("encode", "frame", "make", "build")):
            names.add(node.name)
    return names


def _is_framed(arg, framed_vars: set[str], frame_fns: set[str]) -> bool:
    if isinstance(arg, ast.Call):
        name = call_name(arg)
        return bool(name) and (
            name.split(".")[-1] in frame_fns
            or "frame" in name.split(".")[-1])
    if isinstance(arg, ast.Name):
        return arg.id in framed_vars
    return False


class ClusterTransportDiscipline(Rule):
    code = "TDA090"
    name = ("unbounded socket receive / unframed sendall in "
            "cluster/")
    invariant = (
        "the cluster runtime stays live and speaks one wire format: "
        "every blocking socket receive is deadline-bounded (a "
        "partition surfaces as TransportTimeout, never a wedged "
        "thread) and every sendall payload is length-prefix framed "
        "by the transport encoder (an unframed write desynchronizes "
        "the stream)")

    def applies(self, ctx):
        return "tpu_distalg/cluster/" in ctx.path

    def check(self, ctx):
        frame_fns = _frame_names(ctx.tree)
        scopes = [n for n in ast.walk(ctx.tree)
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))]
        for fn in scopes:
            yield from self._check_scope(ctx, fn, frame_fns)

    def _check_scope(self, ctx, fn, frame_fns):
        has_deadline = _has_deadline(fn)
        # variables assigned from a frame encoder in this scope are
        # framed payloads (buf = encode_frame(...); sock.sendall(buf))
        framed_vars: set[str] = set()
        for call in _direct_calls(fn):
            method = _attr_method(call)
            if method == "settimeout" and call.args and \
                    isinstance(call.args[0], ast.Constant) and \
                    call.args[0].value is None and not has_deadline:
                yield self.violation(
                    ctx, call,
                    "settimeout(None) is the spelled-out block-"
                    "forever — every blocking receive in cluster/ "
                    "must carry a real deadline (TransportTimeout is "
                    "the partition observable)")
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _is_framed(node.value, framed_vars, frame_fns):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        framed_vars.add(tgt.id)
        for call in _direct_calls(fn):
            method = _attr_method(call)
            if method in _RECV_METHODS or method == "accept":
                if not has_deadline:
                    yield self.violation(
                        ctx, call,
                        f".{method}() with no socket timeout armed in "
                        f"this function — a dead or partitioned peer "
                        f"wedges this thread forever; call "
                        f".settimeout(<remaining deadline>) before "
                        f"blocking (transport._recv_exact is the "
                        f"shape)")
            elif method == "sendall":
                if not call.args or not _is_framed(
                        call.args[0], framed_vars, frame_fns):
                    yield self.violation(
                        ctx, call,
                        "sendall of a payload not built by the frame "
                        "encoder — an unframed write desynchronizes "
                        "the length-prefixed stream (and is how "
                        "ad-hoc pickle-shaped payloads sneak in); "
                        "route it through transport.encode_frame / "
                        "send_frame")


def _calls_fsync(fn: ast.AST) -> bool:
    """True when the function calls an fsync (``os.fsync`` or a
    ``*fsync*`` helper like the WAL's ``_fsync_dir``) — the marker of
    the fsync-rename discipline."""
    for call in _direct_calls(fn):
        name = None
        if isinstance(call.func, ast.Attribute):
            name = call.func.attr
        elif isinstance(call.func, ast.Name):
            name = call.func.id
        if name is not None and "fsync" in name:
            return True
    return False


def _write_capable_mode(call: ast.Call) -> str | None:
    """The mode string of an ``open`` call when it can MUTATE the file
    (w/x/a/+ — append is exactly the WAL's mode, and durable bytes
    are durable bytes), else None."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and \
            isinstance(mode.value, str) and \
            any(c in mode.value for c in "wxa+"):
        return mode.value
    return None


class WalDurabilityDiscipline(Rule):
    code = "TDA091"
    name = ("file write outside the WAL/checkpoint fsync-rename "
            "discipline, or a WAL append not durable before the "
            "socket send")
    invariant = (
        "the coordinator's crash-tolerance contract is write-AHEAD: "
        "durable state in tpu_distalg/cluster/ is mutated only "
        "inside fsync-disciplined helpers (cluster/wal.py, "
        "utils/checkpoint), and a record's bytes are flushed+fsynced "
        "BEFORE the ack that depends on them leaves the socket — a "
        "buffered write that an ack escapes ahead of is a recovery "
        "that silently forgets acknowledged state")

    def applies(self, ctx):
        return "tpu_distalg/cluster/" in ctx.path

    def check(self, ctx):
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef,
                               ast.AsyncFunctionDef)):
                yield from self._check_fn(ctx, fn)

    def _check_fn(self, ctx, fn):
        has_fsync = _calls_fsync(fn)
        writes, sends, flushes, fsyncs = [], [], [], []
        for call in _direct_calls(fn):
            name = call_name(call)
            method = _attr_method(call)
            if name == "open":
                mode = _write_capable_mode(call)
                if mode is not None and not has_fsync:
                    yield self.violation(
                        ctx, call,
                        f"open(..., {mode!r}) in cluster/ with no "
                        f"fsync in this function — durable cluster "
                        f"state goes through the WAL/checkpoint "
                        f"fsync-rename helpers (cluster/wal.py, "
                        f"utils/checkpoint), not ad-hoc writes a "
                        f"crash can tear silently")
            elif name in ("os.replace", "os.rename") \
                    and not has_fsync:
                yield self.violation(
                    ctx, call,
                    f"{name}() in cluster/ with no fsync in this "
                    f"function — a rename-publish whose directory "
                    f"entry a power cut can lose; use the "
                    f"WAL/checkpoint fsync-rename helpers")
            if method == "write":
                writes.append(call)
            elif method == "sendall" or (
                    name is not None
                    and name.rsplit(".", 1)[-1] == "send_frame"):
                sends.append(call)
            elif method == "flush":
                flushes.append(call)
            if name is not None and "fsync" in name.rsplit(
                    ".", 1)[-1]:
                fsyncs.append(call)
        # SOURCE order: _direct_calls walks an AST stack whose order
        # is arbitrary — pairing must judge each write against its
        # genuinely FIRST later send, or an unfsynced nearer send
        # hides behind a safe farther one (a false negative in the
        # exact hole this rule exists to close)
        sends.sort(key=lambda c: c.lineno)
        for w in writes:
            for s in sends:
                if s.lineno <= w.lineno:
                    continue
                ok = (any(w.lineno < f.lineno <= s.lineno
                          for f in flushes)
                      and any(w.lineno < y.lineno <= s.lineno
                              for y in fsyncs))
                if not ok:
                    yield self.violation(
                        ctx, s,
                        "socket send after a WAL/file write with no "
                        "flush+fsync between them — the ack can "
                        "escape ahead of the record's durability, "
                        "and a recovered coordinator would forget "
                        "state a worker already observed; fsync "
                        "before the send (wal.WriteAheadLog.append "
                        "is the shape)")
                break  # one finding per write: its FIRST later send


RULES = (ClusterTransportDiscipline(), WalDurabilityDiscipline())
