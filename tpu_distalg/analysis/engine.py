"""The `tda lint` engine — AST rules, suppressions, reporting.

The framework's headline guarantees (bitwise replay, atomic publishes,
race-free emission, exhaustive fault seams) are CONVENTIONS: a single
``time.time()`` in a seeded path or a raw ``open(..., 'w')`` that
bypasses an injection seam silently voids them, and code review is the
only thing that has caught such regressions so far. This package turns
each convention into a machine-checked rule with a ``TDA0xx`` code —
the correctness floor scales with contributors instead of reviewers.

Layering: stdlib + :mod:`tpu_distalg.telemetry` ONLY (like telemetry
and faults themselves) — ``tda lint`` must run in a bare host process
with no jax, no numpy, no backend.

Engine pieces (rules live in sibling modules, one file per invariant
family — see :data:`tpu_distalg.analysis.RULES`):

  * :class:`Violation` — one finding, with a position-independent
    ``fingerprint`` (code + path + stripped source line) so baselines
    survive unrelated line drift;
  * :class:`LintContext` — a parsed file plus everything rules need:
    source lines, module-level integer constants (folded), path
    classification (library / telemetry / test code), and the comment
    markers;
  * suppressions — ``# tda: ignore[TDA0xx] -- reason`` on the flagged
    line or the line above. The reason text is REQUIRED: a bare
    ignore does not suppress and is itself reported as ``TDA000``
    (an unexplained suppression is a convention-violation with extra
    steps). ``# tda: hot-loop`` marks a loop for TDA011 the same way.
    Comments are found with :mod:`tokenize`, so look-alike text inside
    string literals (e.g. this package's own test fixtures) is inert.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import os
import re
import tokenize

#: rule codes must match this (and TDA000 is reserved for the engine:
#: syntax errors and malformed suppressions)
CODE_RE = re.compile(r"^TDA\d{3}$")

_IGNORE_RE = re.compile(
    r"tda:\s*ignore\[([A-Z0-9,\s]+)\]\s*(?:(?:--|:)\s*(\S.*))?")
_HOT_LOOP_RE = re.compile(r"tda:\s*hot-loop")

_SKIP_DIRS = {".git", "__pycache__", ".bench_cache", ".pytest_cache",
              "node_modules", "build", "dist", ".claude"}


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding. ``snippet`` is the stripped source line — part of
    the fingerprint, so a baseline entry tracks the offending CODE, not
    its line number. ``end_line`` is the flagged statement's last line
    (suppression comments anywhere in that span apply)."""

    code: str
    message: str
    path: str
    line: int
    col: int
    snippet: str = ""
    end_line: int = 0

    @property
    def fingerprint(self) -> str:
        key = f"{self.code}|{self.path}|{self.snippet}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def text(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.code} {self.message}")

    def as_dict(self) -> dict:
        return {"code": self.code, "message": self.message,
                "path": self.path, "line": self.line, "col": self.col,
                "snippet": self.snippet,
                "fingerprint": self.fingerprint}


class Rule:
    """One invariant. Subclasses set the class attributes and implement
    :meth:`check`; :meth:`applies` narrows the rule to the code it
    protects (e.g. TDA001 polices library code, not tests)."""

    code: str = "TDA000"
    name: str = ""
    invariant: str = ""

    def applies(self, ctx: "LintContext") -> bool:
        return True

    def check(self, ctx: "LintContext"):
        raise NotImplementedError

    def violation(self, ctx: "LintContext", node,
                  message: str) -> Violation:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = ""
        if 1 <= line <= len(ctx.lines):
            snippet = ctx.lines[line - 1].strip()
        return Violation(code=self.code, message=message, path=ctx.path,
                         line=line, col=col, snippet=snippet,
                         end_line=getattr(node, "end_lineno", line)
                         or line)


# ---------------------------------------------------------------------
# shared AST helpers


def dotted_name(node) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def root_name(node) -> str | None:
    """The leftmost Name of an Attribute/Subscript chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def const_int(node, consts: dict) -> int | None:
    """Fold ``node`` to an int using literal arithmetic and the
    module-level constants in ``consts`` — the resolver behind the
    Pallas rules' "statically-computable" qualifier."""
    if isinstance(node, ast.Constant):
        return node.value if isinstance(node.value, int) else None
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = const_int(node.operand, consts)
        return None if v is None else -v
    if isinstance(node, ast.BinOp):
        left = const_int(node.left, consts)
        right = const_int(node.right, consts)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.FloorDiv):
                return left // right
            if isinstance(node.op, ast.Mod):
                return left % right
            if isinstance(node.op, ast.LShift):
                return left << right
            if isinstance(node.op, ast.RShift):
                return left >> right
            if isinstance(node.op, ast.Pow):
                return left ** right
        except (ZeroDivisionError, OverflowError, ValueError):
            return None
    return None


def _module_consts(tree: ast.Module) -> dict:
    """Module-level ``NAME = <int expr>`` bindings, folded iteratively
    so later constants may reference earlier ones."""
    consts: dict = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            v = const_int(stmt.value, consts)
            if v is not None:
                consts[stmt.targets[0].id] = v
    return consts


# ---------------------------------------------------------------------
# suppression / marker comments


@dataclasses.dataclass
class Suppression:
    line: int          # the code line this suppression covers
    comment_line: int  # where the comment itself sits
    codes: frozenset   # rule codes, e.g. {"TDA001"}
    reason: str        # required; "" marks a malformed suppression
    used: bool = False


@dataclasses.dataclass
class Markers:
    suppressions: list
    hot_loops: set  # code lines marked `# tda: hot-loop`
    malformed: list  # (line, message) pairs -> TDA000


def scan_markers(source: str) -> Markers:
    """Tokenize-based comment scan. An own-line comment covers the next
    code line; a trailing comment covers its own line."""
    comments: list[tuple[int, int, str]] = []  # (row, col, text)
    code_rows: set[int] = set()
    try:
        toks = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        toks = []
    for tok in toks:
        if tok.type == tokenize.COMMENT:
            comments.append((tok.start[0], tok.start[1], tok.string))
        elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                              tokenize.INDENT, tokenize.DEDENT,
                              tokenize.ENDMARKER):
            code_rows.add(tok.start[0])

    def covered_line(row: int, col: int) -> int:
        if row in code_rows:
            return row          # trailing comment
        nxt = [r for r in code_rows if r > row]
        return min(nxt) if nxt else row

    supps, hot, malformed = [], set(), []
    for row, col, text in comments:
        m = _IGNORE_RE.search(text)
        if m:
            codes = frozenset(
                c.strip() for c in m.group(1).split(",") if c.strip())
            reason = (m.group(2) or "").strip()
            target = covered_line(row, col)
            bad = [c for c in codes if not CODE_RE.match(c)]
            if bad:
                malformed.append(
                    (row, f"suppression names unknown code(s) "
                          f"{', '.join(sorted(bad))} — want TDA0xx"))
            supps.append(Suppression(line=target, comment_line=row,
                                     codes=codes, reason=reason))
        if _HOT_LOOP_RE.search(text):
            hot.add(covered_line(row, col))
    return Markers(suppressions=supps, hot_loops=hot,
                   malformed=malformed)


# ---------------------------------------------------------------------
# context + file/source entry points


@dataclasses.dataclass
class LintContext:
    path: str            # posix-normalized, as reported
    tree: ast.Module
    lines: list
    consts: dict
    markers: Markers
    is_library: bool     # under tpu_distalg/ (the shipped package)
    is_telemetry: bool   # under tpu_distalg/telemetry/ (owns wall time)
    is_test: bool        # under tests/ (host syncs are its job)


def norm_path(path: str) -> str:
    """Canonical posix spelling: ``./x`` == ``x`` == ``<cwd>/x`` — a
    baseline fingerprint must not depend on how the caller typed the
    path."""
    p = os.path.normpath(path)
    if os.path.isabs(p):
        rel = os.path.relpath(p)
        if not rel.startswith(".."):
            p = rel
    return p.replace(os.sep, "/")


def _classify(path: str) -> tuple[bool, bool, bool]:
    p = path
    lib = "tpu_distalg/" in p and "/analysis/fixtures" not in p
    tel = "tpu_distalg/telemetry/" in p
    test = "tests/" in p or os.path.basename(p).startswith("test_")
    return lib, tel, test


def make_context(source: str, path: str) -> LintContext:
    tree = ast.parse(source)
    path = norm_path(path)
    lib, tel, test = _classify(path)
    return LintContext(
        path=path, tree=tree,
        lines=source.splitlines(), consts=_module_consts(tree),
        markers=scan_markers(source), is_library=lib,
        is_telemetry=tel, is_test=test)


def _select(rules, select=None, ignore=None, known=None):
    """Filter ``rules`` by --select/--ignore codes. ``known`` widens
    the validation set (the CLI validates against per-file AND project
    rules together, then filters each family separately)."""
    known = set(known or ()) | {r.code for r in rules} | {"TDA000"}
    for group in (select or ()), (ignore or ()):
        for c in group:
            if c not in known:
                raise ValueError(
                    f"unknown rule code {c!r}; known: "
                    f"{', '.join(sorted(known))}")
    out = [r for r in rules
           if (not select or r.code in select)
           and (not ignore or r.code not in ignore)]
    return out


def apply_suppressions(violations, suppressions) -> list:
    """Drop findings covered by a REASONED suppression whose line sits
    in the finding's statement span; mark those suppressions used (the
    unused-pin report reads the flag). Shared by the per-file pass and
    the project pass so one pin serves both."""
    kept = []
    for v in sorted(violations, key=lambda v: (v.line, v.col,
                                               v.code)):
        span_end = max(v.line, v.end_line)
        supp = next(
            (s for s in suppressions
             if v.line <= s.line <= span_end
             and v.code in s.codes and s.reason),
            None)
        if supp is not None:
            supp.used = True
            continue
        kept.append(v)
    return kept


def marker_violations(ctx: "LintContext") -> list:
    """The engine's own TDA000 findings for one parsed file: bare
    (reasonless) suppressions and malformed markers."""
    out = []
    for s in ctx.markers.suppressions:
        if not s.reason:
            out.append(Violation(
                code="TDA000", path=ctx.path, line=s.comment_line,
                col=0,
                message=(
                    "suppression without a reason — write "
                    "'# tda: ignore[CODE] -- why it is safe' "
                    "(an unexplained ignore is unreviewable)"),
                snippet=ctx.lines[s.comment_line - 1].strip()
                if s.comment_line <= len(ctx.lines) else ""))
    for line, msg in ctx.markers.malformed:
        out.append(Violation(
            code="TDA000", path=ctx.path, line=line, col=0,
            message=msg,
            snippet=ctx.lines[line - 1].strip()
            if line <= len(ctx.lines) else ""))
    return out


def syntax_violation(path: str, e: SyntaxError) -> Violation:
    return Violation(
        code="TDA000", path=norm_path(path),
        line=e.lineno or 1, col=(e.offset or 1) - 1,
        message=f"file does not parse: {e.msg}",
        snippet=(e.text or "").strip())


def lint_source(source: str, path: str, rules, *,
                select=None, ignore=None) -> list:
    """Lint one source string. Returns surviving violations (TDA000
    engine findings included unless filtered)."""
    active = _select(rules, select, ignore)
    tda000 = (not select or "TDA000" in select) and \
        (not ignore or "TDA000" not in ignore)
    try:
        ctx = make_context(source, path)
    except SyntaxError as e:
        return [syntax_violation(path, e)] if tda000 else []

    found: list[Violation] = []
    for rule in active:
        if rule.applies(ctx):
            found.extend(rule.check(ctx))

    # suppressions: reasoned ones drop matching findings; bare ones
    # suppress NOTHING and are reported themselves
    kept = apply_suppressions(found, ctx.markers.suppressions)
    if tda000:
        kept.extend(marker_violations(ctx))
    return sorted(kept, key=lambda v: (v.line, v.col, v.code))


def lint_file(path: str, rules, *, select=None, ignore=None) -> list:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path, rules,
                           select=select, ignore=ignore)


def iter_python_files(paths):
    """Expand files/directories into a sorted .py file list (sorted so
    output and baselines are stable across filesystems — the linter
    holds itself to its own TDA002)."""
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in _SKIP_DIRS)
                out.extend(os.path.join(root, f)
                           for f in sorted(files)
                           if f.endswith(".py"))
        else:
            raise FileNotFoundError(f"no such path: {p}")
    return sorted(dict.fromkeys(out))
