"""Checkpoint-carry completeness — TDA100.

The bug class: a trainer's cross-step state grows a field (the topk
EF residual riding the scan carry, PR 5) and the checkpoint payload
builder — often in a DIFFERENT module — keeps serializing the old
shape. Resume then silently reconstructs partial state: the run
completes, converges a little worse, and nothing errors. Review caught
it once; this rule makes the contract structural.

Detection, over the project graph: a *field serializer* is a dict
literal whose string keys read the same-named attributes off one
object (``{"status": st.status, "admit": st.admit, ...}``) and whose
matched keys are all fields of ONE dataclass visible (defined or
imported, re-exports followed) from the builder's module. For that
dataclass, any field that is MUTATED anywhere in library code (a plain
``obj.field = ...`` / ``obj.field += ...`` write — the "changes across
steps" signal) but absent from the serializer's keys is a finding:
either the payload must carry it, or a reasoned
``# tda: ignore[TDA100]`` on the builder must say why recovery is
correct without it (liveness clocks and connection fencing state are
the legitimate examples — see cluster/coordinator.py).

Deliberate limits: container-mutations (``st.pushes[w] = v``) do not
count as field mutation (those fields are usually reconstructed from
replayed records, not snapshots), and ``jax.tree.leaves(state)``-style
whole-tree payloads are structurally complete and never looked at.
"""

from __future__ import annotations

import collections

from tpu_distalg.analysis.project import ProjectRule


class CheckpointCarryCompleteness(ProjectRule):
    code = "TDA100"
    name = "mutated state field missing from checkpoint payload"
    invariant = ("every cross-step-mutated field of a state container "
                 "reaches its serializer, or a reasoned pin says why "
                 "recovery is whole without it")

    def check_project(self, project):
        # attr name -> [(module, line)] across library code
        mutated: dict = collections.defaultdict(list)
        for s in project.library():
            for attr, line in s["attr_writes"]:
                mutated[attr].append((s["module"], line))
        for s in project.library():
            visible = project.visible_dataclasses(s)
            for pb in s["payload_builders"]:
                matched = set(pb["matched"])
                candidates = [
                    (name, ds, info) for name, ds, info in visible
                    if matched <= set(info["fields"])]
                if not candidates:
                    continue
                # the serializer's dataclass: the candidate whose
                # field set the matched keys cover best; an exact tie
                # is ambiguous and skipped
                scored = sorted(
                    candidates,
                    key=lambda c: (-len(matched & set(c[2]["fields"])),
                                   len(c[2]["fields"])))
                if len(scored) > 1 and \
                        set(scored[0][2]["fields"]) \
                        == set(scored[1][2]["fields"]):
                    continue
                name, ds, info = scored[0]
                keys = set(pb["keys"])
                for field in sorted(info["fields"]):
                    if field in keys or not mutated.get(field):
                        continue
                    wm, wl = mutated[field][0]
                    yield self.project_violation(
                        project, s["path"], pb["line"],
                        f"payload serializes {name} fields "
                        f"({', '.join(sorted(matched))}) but omits "
                        f"'{field}', which is mutated across steps "
                        f"(e.g. {wm}:{wl}) — a resume from this "
                        f"payload silently drops that state (the EF-"
                        f"residual class); carry it or pin with a "
                        f"reasoned '# tda: ignore[TDA100]' stating "
                        f"why recovery is correct without it",
                        end_line=pb["end_line"])


RULES = (CheckpointCarryCompleteness(),)
