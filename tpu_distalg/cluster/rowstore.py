"""Sharded-state parameter server — the row store.

The PR 11 PS tier replicates the center: every shard holds a SLICE of
a model that must, in full, fit every host, and every push/pull moves
the whole model. This module is the model-parallel replacement: the
PS tier owns DISJOINT ROW RANGES of each leaf under the
partition-table-driven :class:`~tpu_distalg.parallel.partition.
RowOwnershipMap` (the same ``np.array_split`` arithmetic
``ps.split_center`` always used, now a first-class shared object),
workers pull only the rows their window touches and push sparse
per-row deltas, and staleness is PER-ROW: every stored row carries the
version (windows merged) of its last update, a pull returns
``(values, versions)``, and a push's per-row base versions drive
row-wise ``decay**age`` weights and the row-wise SSP gate — the
power-law access pattern Sparse Allreduce (arXiv:1312.3020) exploits,
applied to cluster state: hot rows ride every window, tail rows stay
untouched and unshipped.

Wire format: a sparse row push/pull is the ordinary framed transport
payload (``transport.encode_frame``) with, per leaf, a ``{name}.rows``
int64 row-index array, an optional ``{name}.vbase`` int64 per-row
base-version array, and the VALUES as either raw f32 (``dense``) or
the existing ``--comm int8/topk`` host-codec parts
(``pcomms.encode_tree`` under an explicit seed path, EF-free — the
rank/factor pushes here are absolute row states or one-shot row
deltas, not an accumulating gradient stream, so stateless seeded
rounding keeps a killed-and-respawned worker's re-encode bitwise).
Pulls ship raw f32 row values: the sparse row selection is already
the wire win, and exact pulls are what make per-row base versions
exact. The WAL logs the PUSHED wire arrays per commit (a per-row redo
record, same discipline as the SSP commit record: replay re-runs the
identical decode, and re-push dedup keys on the same
``wal.delta_digest``).

On top of the store, :func:`run_cluster_pagerank` ports PageRank to
the fleet: the dst-sorted edge blocks of a ``graphs/ingest.py`` cache
are partitioned across workers (one worker per cache shard / dst
window), each worker pulls only the ranks of the DISTINCT SOURCE
vertices its edges reference (< the full vertex set on a power-law
graph — the measured ``cluster_sparse_pull_fraction``), computes its
window's contributions host-side (the numpy twin of
``ops.graph.block_contribs``), and pushes the ``(didx + lo, acc ·
dmask)`` sparse pairs — the cluster-scope twin of
``comms.sparse_allreduce``, applied at the PS in slot order. Chaos
points: ``cluster:worker`` kills recompute the iteration
(deterministic respawn), ``cluster:coordinator`` kills at the commit
point roll the in-flight iteration back (record not yet durable),
``cluster:ps`` kills at the shard merge seam exercise the REDO path
(record durable, merge lost — recovery replays it), and
``cluster:rpc`` oserrors retry the frame. All recover to the bitwise
final ranks of the undisturbed run.

numpy + stdlib only at runtime (the codec module imports jax, as the
coordinator already does); device placement is never consulted —
this is HOST cluster state.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from tpu_distalg.cluster import transport
from tpu_distalg.cluster import wal as walmod
from tpu_distalg.faults import registry as fregistry
from tpu_distalg.parallel import comms as pcomms
from tpu_distalg.parallel import partition
from tpu_distalg.parallel.ssp import DEFAULT_DECAY
from tpu_distalg.telemetry import events as tevents
from tpu_distalg.tune import defaults as tune_defaults

#: schedule cell code for a kill (hang cells hold seconds)
KILL_CELL = -1.0

#: seed-path tag for the fleet's stateless push encode (disjoint from
#: comms.PUSH_SEED_TAG/PULL_SEED_TAG so a rowstore push can never
#: collide with an SSP push's stochastic-rounding stream)
ROW_SEED_TAG = 7

#: suffix of the per-leaf row-index wire array
ROWS_SUFFIX = ".rows"
#: suffix of the per-leaf per-row base-version wire array
VBASE_SUFFIX = ".vbase"


class RowStalenessError(RuntimeError):
    """A pushed row's base version is older than the staleness bound
    allows — the row-wise SSP gate refused the contribution."""


def strip_row_arrays(arrays: dict) -> tuple[dict, dict, dict]:
    """Split a pushed wire dict into ``(value_arrays, rows, vbase)``
    where ``rows``/``vbase`` map leaf name -> int64 array. The value
    arrays are exactly what the host codec (or the dense path)
    decodes; the row metadata never enters the codec."""
    vals, rows, vbase = {}, {}, {}
    for k, v in arrays.items():
        if k.endswith(ROWS_SUFFIX):
            rows[k[:-len(ROWS_SUFFIX)]] = np.asarray(v, np.int64)
        elif k.endswith(VBASE_SUFFIX):
            vbase[k[:-len(VBASE_SUFFIX)]] = np.asarray(v, np.int64)
        else:
            vals[k] = v
    return vals, rows, vbase


class _RowShard:
    """One PS shard of the row store: its row ranges of every sharded
    leaf (whole replicated leaves live on shard 0), a same-leading-dim
    int64 version array per leaf, one lock."""

    def __init__(self, leaves: dict):
        self.lock = threading.Lock()
        self.leaves = {k: np.asarray(v, np.float32)
                       if np.asarray(v).dtype.kind == "f"
                       else np.asarray(v).copy()
                       for k, v in leaves.items()}
        self.versions = {
            k: np.zeros((v.shape[0] if v.ndim else 1,), np.int64)
            for k, v in self.leaves.items()}


class RowStore:
    """Row-partitioned cluster state: ``n_shards`` :class:`_RowShard`\\ s
    under one :class:`~tpu_distalg.parallel.partition.RowOwnershipMap`,
    per-row versions, sparse pull and row-wise weighted merge.

    ``staleness`` (optional) arms the row-wise SSP gate: a merge whose
    per-row age exceeds it raises :class:`RowStalenessError` instead of
    silently down-weighting a contribution the protocol should never
    have admitted."""

    def __init__(self, center: dict, *, table: str = "lr",
                 n_shards: int = tune_defaults.PS_SHARDS,
                 decay: float = DEFAULT_DECAY,
                 staleness: int | None = None):
        self.map = partition.RowOwnershipMap.for_center(
            center, table, n_shards)
        self.n_shards = self.map.n_shards
        self.decay = float(decay)
        self.staleness = staleness
        self.shards = [_RowShard(piece)
                       for piece in self.map.split(center)]
        self.version = 0

    # ------------------------------------------------------ pulling

    def pull_rows(self, name: str, rows) -> tuple[np.ndarray,
                                                  np.ndarray]:
        """``(values, versions)`` for ``rows`` of leaf ``name``, in
        the caller's row order — the sparse pull. Counters account
        the rows actually shipped vs the dense-replication
        equivalent (the whole leading dim)."""
        own = self.map[name]
        rows = np.asarray(rows, np.int64)
        owners = own.owner_of(rows)
        probe_shard = 0 if own.sharded else own.owner
        first = self.shards[probe_shard].leaves[name]
        out = np.empty((rows.shape[0],) + first.shape[1:],
                       first.dtype)
        vers = np.empty((rows.shape[0],), np.int64)
        for i in range(self.n_shards):
            sel = owners == i
            if not np.any(sel):
                continue
            lo, _hi = own.range_of(i)
            sh = self.shards[i]
            with sh.lock:
                local = rows[sel] - lo
                out[sel] = sh.leaves[name][local]
                vers[sel] = sh.versions[name][local]
        n_dim = int(own.shape[0]) if len(own.shape) else 1
        tevents.counter("rowstore.rows_pulled", int(rows.shape[0]))
        tevents.counter("rowstore.pull_rows_dense", n_dim)
        return out, vers

    # ------------------------------------------------------ merging

    def _ages(self, commit_window: int, rows: np.ndarray,
              vbase: np.ndarray) -> np.ndarray:
        ages = np.maximum(
            0, np.int64(commit_window) - np.asarray(vbase, np.int64))
        if self.staleness is not None and ages.size \
                and int(ages.max()) > int(self.staleness):
            worst = rows[int(np.argmax(ages))]
            raise RowStalenessError(
                f"row {int(worst)} pushed with age {int(ages.max())} "
                f"> staleness bound {self.staleness} — the row-wise "
                f"SSP gate refuses it")
        return ages

    def merge_rows(self, commit_window: int,
                   contribs: list) -> list[dict]:
        """One commit of sparse per-row deltas, in SLOT order:
        ``contribs`` is ``[(slot, {name: (rows, vals, vbase)})]``
        where ``rows`` is int64 row indices, ``vals`` the per-row
        delta block and ``vbase`` per-row base versions (int64 array,
        or a scalar applied row-wise). Per row: ``leaf[r] += Σ wᵢ(r)·
        Δᵢ[r] / Σ wᵢ(r)`` over the contributions touching ``r``, with
        ``wᵢ(r) = decay**(commit_window − vbase)`` — exactly the
        replicated :class:`~tpu_distalg.cluster.ps.PsShard` arithmetic
        (f32 term accumulation in contribution order, one python-float
        weight sum, one f32 divide) restricted row-wise, so a push
        touching EVERY row at a uniform base merges bit-identically to
        the dense replicated path. Rows nobody touched do not move and
        keep their version. Returns per-contribution records
        ``[{slot, age, weight, rows}]`` (age/weight of the oldest
        row); bumps ``version``."""
        records = []
        staged: list[tuple[int, dict]] = []
        for slot, leaf_deltas in contribs:
            prepared: dict = {}
            age_max = 0
            w_min = 1.0
            for name, (rows, vals, vbase) in leaf_deltas.items():
                rows = np.asarray(rows, np.int64)
                vbase = (np.full(rows.shape, int(vbase), np.int64)
                         if np.ndim(vbase) == 0
                         else np.asarray(vbase, np.int64))
                ages = self._ages(commit_window, rows, vbase)
                w = (np.float32(self.decay)
                     ** ages.astype(np.float32))
                if ages.size:
                    age_max = max(age_max, int(ages.max()))
                    w_min = min(w_min, float(w.min()))
                prepared[name] = (rows,
                                  np.asarray(vals, np.float32), w)
            staged.append((int(slot), prepared))
            records.append({"slot": int(slot), "age": age_max,
                            "weight": round(w_min, 6),
                            "rows": int(sum(
                                r.shape[0] for r, _v, _w
                                in prepared.values()))})
        if any(r["rows"] for r in records):
            tevents.gauge("rowstore.max_row_staleness",
                          max(r["age"] for r in records))
        for i, sh in enumerate(self.shards):
            with sh.lock:
                for name, own in self.map.leaves.items():
                    lo, hi = own.range_of(i)
                    if hi <= lo:
                        continue
                    leaf = sh.leaves[name]
                    acc = np.zeros_like(leaf, dtype=np.float32)
                    wsum = np.zeros((leaf.shape[0],), np.float64)
                    touched = np.zeros((leaf.shape[0],), bool)
                    for _slot, prepared in staged:
                        if name not in prepared:
                            continue
                        rows, vals, w = prepared[name]
                        sel = (rows >= lo) & (rows < hi)
                        if not np.any(sel):
                            continue
                        local = rows[sel] - lo
                        wl = w[sel]
                        term = (wl.reshape(
                            (-1,) + (1,) * (vals.ndim - 1))
                            * vals[sel])
                        acc[local] = acc[local] + term
                        wsum[local] += wl.astype(np.float64)
                        touched[local] = True
                    apply = touched & (wsum > 0.0)
                    if np.any(apply):
                        div = wsum[apply].astype(np.float32).reshape(
                            (-1,) + (1,) * (leaf.ndim - 1))
                        leaf[apply] = leaf[apply] + acc[apply] / div
                        sh.versions[name][apply] = commit_window + 1
        self.version = max(self.version, commit_window + 1)
        return records

    def replace_rows(self, commit_window: int, name: str,
                     rows, vals) -> None:
        """Absolute row update (the PageRank rank replacement): set
        ``leaf[rows] = vals`` and bump those rows' versions — no
        weighting, the caller owns the combine."""
        own = self.map[name]
        rows = np.asarray(rows, np.int64)
        vals = np.asarray(vals)
        for i in range(self.n_shards):
            lo, hi = own.range_of(i)
            if hi <= lo:
                continue
            sel = (rows >= lo) & (rows < hi)
            if not np.any(sel):
                continue
            sh = self.shards[i]
            with sh.lock:
                local = rows[sel] - lo
                sh.leaves[name][local] = vals[sel]
                sh.versions[name][local] = commit_window + 1
        self.version = max(self.version, commit_window + 1)

    # ----------------------------------------------------- snapshots

    def snapshot(self) -> dict:
        """The assembled center (copies, consistent per shard)."""
        parts = []
        for sh in self.shards:
            with sh.lock:
                parts.append({k: v.copy()
                              for k, v in sh.leaves.items()})
        return self.map.join(parts)

    def row_versions(self, name: str) -> np.ndarray:
        """The full per-row version vector of leaf ``name`` (shard
        slices concatenated in ownership order)."""
        own = self.map[name]
        if not own.sharded:
            sh = self.shards[own.owner]
            with sh.lock:
                return sh.versions[name].copy()
        parts = []
        for i in range(self.n_shards):
            sh = self.shards[i]
            with sh.lock:
                parts.append(sh.versions[name].copy())
        return np.concatenate(parts)


# --------------------------------------------------------- wire frames


def frame_roundtrip(kind: str, meta: dict, arrays: dict,
                    *, counter: str) -> tuple[dict, dict]:
    """Encode one row-store frame, account its REAL wire bytes, pass
    it through the ``cluster:rpc`` seam, and parse it back — the
    in-process fleet's stand-in for a socket send/recv that keeps the
    byte accounting and the dtype-safety checks (TDA051's no-widening
    contract) honest. Returns ``(meta, arrays)`` as the receiver sees
    them. An injected transient rpc fault retries the identical
    bytes."""
    buf = transport.encode_frame(kind, meta, arrays)
    tevents.counter(counter, len(buf))
    last: Exception | None = None
    for _ in range(4):
        try:
            fregistry.inject("cluster:rpc", None)
            break
        except fregistry.InjectedOSError as e:
            last = e
            tevents.counter("rowstore.rpc_retries")
    else:
        raise last  # storm outlasted the retry budget
    psize = transport._PREFIX.size
    _magic, hlen, blen, _crc = transport._PREFIX.unpack(buf[:psize])
    header = buf[psize:psize + hlen]
    body = buf[psize + hlen:psize + hlen + blen]
    _kind, m, arrs = transport.parse_payload(header, body)
    return m, arrs


def encode_row_push(codec, name: str, rows: np.ndarray,
                    vals: np.ndarray, *seed_path: int) -> dict:
    """The sparse push payload for one leaf: ``{name}.rows`` int64 +
    values, raw f32 when ``codec is None`` else the host-codec parts
    (EF-free, seeded by ``seed_path`` so a respawned worker re-encodes
    the identical bytes)."""
    arrays = {f"{name}{ROWS_SUFFIX}": np.asarray(rows, np.int64)}
    if codec is None:
        arrays[f"{name}.val"] = np.asarray(vals, np.float32)
    else:
        enc, _res = pcomms.encode_tree(
            codec, {name: np.asarray(vals, np.float32)}, None,
            ROW_SEED_TAG, *seed_path)
        arrays.update(enc)
    return arrays


def decode_row_push(codec, name: str, arrays: dict,
                    n_rows: int, tail: tuple = ()) -> tuple[
                        np.ndarray, np.ndarray]:
    """Inverse of :func:`encode_row_push` -> ``(rows, vals)``."""
    vals_arrays, rows, _vb = strip_row_arrays(arrays)
    idx = rows[name]
    if codec is None:
        vals = np.asarray(vals_arrays[f"{name}.val"], np.float32)
    else:
        template = {name: np.zeros((n_rows,) + tail, np.float32)}
        vals = pcomms.decode_tree(codec, vals_arrays, template)[name]
    return idx, vals.reshape((n_rows,) + tail)


# ----------------------------------------------------- fault schedules


def compile_point_schedule(point: str, n_windows: int, n_cols: int = 1,
                           *, plan=None) -> np.ndarray:
    """The plan-pure fault schedule for one fleet point: one probe per
    (window, col) cell in row-major order against a fresh quiet
    registry — same discipline as ``worker.compile_worker_schedule``.
    Cell ``KILL_CELL`` = kill, > 0 = hang/straggle argument."""
    live = fregistry.active()
    if plan is None:
        plan = live.plan if live is not None else None
    out = np.zeros((n_windows, n_cols), np.float64)
    if plan is None or not any(r.point == point for r in plan.rules):
        return out
    reg = fregistry.FaultRegistry(plan, quiet=True)
    for w in range(n_windows):
        for c in range(n_cols):
            hit = reg.probe(point)
            if hit is None:
                continue
            kind, arg = hit
            if kind == "kill":
                out[w, c] = KILL_CELL
            else:
                out[w, c] = float(
                    arg if arg is not None
                    else fregistry.DEFAULT_HANG_SECONDS)
    if live is not None and live.plan == plan:
        live.record(reg.fired)
    return out


# ------------------------------------------------- cluster PageRank


@dataclasses.dataclass
class ClusterPageRankConfig:
    n_iterations: int = 8
    q: float = 0.15
    ps_shards: int = 2
    comm: str = "dense"
    table: str = "pagerank_cluster"
    plan_spec: str | None = None
    wal_dir: str | None = None
    #: rows one worker may hold at once (pull working set); ``None``
    #: disables the check. The ">1-host-RAM" contract: a budget below
    #: the vertex count forces streaming row pulls and FAILS LOUDLY if
    #: any worker ever materializes more.
    model_budget_rows: int | None = None
    max_restarts: int = 6


class _FleetDied(RuntimeError):
    """Internal: a seeded coordinator/PS kill fired — unwind to the
    recovery loop (the process-death stand-in of the in-process
    fleet)."""


class _PrWorker:
    """One fleet worker: its contiguous dst-window slice of the edge
    cache, precomputed sparse pull set (distinct sources with nonzero
    weight) and sparse push pairs (the shard's ``didx + lo`` window
    offsets)."""

    def __init__(self, slot: int, rows: np.ndarray, lo: int,
                 window: int, block_edges: int, didx: np.ndarray,
                 dmask: np.ndarray, budget: int | None):
        self.slot = slot
        self.window = int(window)
        self.lo = int(lo)
        rows = np.asarray(rows)
        w = np.ascontiguousarray(rows[:, 2]).view(np.float32)
        nz = w != 0.0          # padding rows carry zero weight: inert
        self.src = rows[:, 0][nz].astype(np.int64)
        self.dst_local = rows[:, 1][nz].astype(np.int64) - self.lo
        self.w = np.ascontiguousarray(w[nz])
        # block partial-sum boundaries: the engine accumulates the
        # window acc ONE EDGE BLOCK AT A TIME (`acc + block_contribs`
        # in block order), and matching that f32 association is what
        # keeps the fleet within 1e-6 of it over many sweeps. A
        # zero-weight padding row adds exactly +0.0, so dropping them
        # leaves every block partial bit-identical.
        block_of = np.flatnonzero(nz) // int(block_edges)
        self.block_starts = np.concatenate(
            [[0], np.flatnonzero(np.diff(block_of)) + 1,
             [self.src.shape[0]]]).astype(np.int64)
        self.pull_idx = np.unique(self.src)
        if budget is not None and self.pull_idx.shape[0] > budget:
            raise RuntimeError(
                f"worker {slot} needs {self.pull_idx.shape[0]} rank "
                f"rows at once but the model budget is {budget} — "
                f"the cache's dst windows must shrink (more shards), "
                f"not the honesty of the claim")
        self.src_local = np.searchsorted(self.pull_idx, self.src)
        self.didx = np.asarray(didx, np.int64)
        self.dmask = np.asarray(dmask, np.float32)
        self.push_idx = self.didx + self.lo

    def contribs(self, pulled: np.ndarray) -> np.ndarray:
        """The window accumulation (numpy twin of
        ``ops.graph.block_contribs`` summed block-by-block, the
        engine's association) reduced to the sparse pairs the push
        ships: ``acc[didx] * dmask``."""
        acc = np.zeros((self.window,), np.float32)
        vals = pulled[self.src_local] * self.w
        for b in range(self.block_starts.shape[0] - 1):
            s, e = self.block_starts[b], self.block_starts[b + 1]
            part = np.zeros((self.window,), np.float32)
            np.add.at(part, self.dst_local[s:e], vals[s:e])
            acc = acc + part
        return acc[self.didx] * self.dmask


def pagerank_event_digest(events: list) -> str:
    """CRC32 hex over the commit event sequence — the fleet's replay
    comparison surface (kill/recovery evidence deliberately outside
    it: wall clock and restart counts legitimately differ)."""
    import zlib

    crc = 0
    for e in events:
        crc = zlib.crc32(repr(e).encode(), crc)
    return f"{crc & 0xFFFFFFFF:08x}"


def run_cluster_pagerank(path: str, cfg: ClusterPageRankConfig) -> dict:
    """PageRank over the fleet through the row store: one worker per
    cache shard, sparse rank pulls/pushes through real wire frames,
    WAL row-redo records per commit, seeded chaos at the worker /
    coordinator / PS / rpc seams — final ranks match the
    single-process ``graphs.run_streamed_pagerank`` within 1e-6 (same
    combine order: slot-ordered origin accumulation, the
    ``sparse_allreduce`` contract) and replay bitwise under kills.

    Workers compute each iteration on threads (pure functions of the
    pulled rows — scheduling cannot change bytes); the commit applies
    in slot order. Synchronous by construction: every push's base is
    the iteration's own version, so per-row ages are 0 — the
    asynchronous staleness story lives in the SSP trainer's rowstore
    mode, not here."""
    from tpu_distalg.data import cache as dcache
    from tpu_distalg.graphs import ingest

    mm, header = dcache.open_cache(path, layout=ingest.LAYOUT)
    geom = header["geom"]
    V = int(geom["n_vertices"])
    S = int(geom["n_shards"])
    window = int(geom["window"])
    lo = np.asarray(geom["lo"], np.int64)
    deg, didx, dmask = ingest.read_aux(path, geom)
    has_out = (deg > 0).astype(np.float32)
    n_iters = int(cfg.n_iterations)
    q = np.float32(cfg.q)

    codec = pcomms.make_host_codec(cfg.comm)
    plan = (fregistry.FaultPlan.parse(cfg.plan_spec)
            if cfg.plan_spec else None)
    worker_sched = compile_point_schedule(
        "cluster:worker", n_iters, S, plan=plan)
    coord_sched = compile_point_schedule(
        "cluster:coordinator", n_iters, plan=plan)
    ps_sched = compile_point_schedule(
        "cluster:ps", n_iters, plan=plan)

    workers = [
        _PrWorker(s, dcache.shard_view(mm, S, s), int(lo[s]), window,
                  int(geom["block_edges"]), didx[s], dmask[s],
                  cfg.model_budget_rows)
        for s in range(S)]
    peak_pull = max(w.pull_idx.shape[0] for w in workers)
    rows_pulled_iter = int(sum(w.pull_idx.shape[0] for w in workers))
    sparse_fraction = rows_pulled_iter / float(S * V)

    def new_store() -> RowStore:
        return RowStore(
            {"ranks": np.full((V,), 1.0 / V, np.float32)},
            table=cfg.table, n_shards=cfg.ps_shards)

    wal = None
    if cfg.wal_dir:
        wal = walmod.WriteAheadLog(cfg.wal_dir)

    def recover() -> tuple[RowStore, int, list]:
        """Rebuild the store from the WAL's row-redo records (base
        ranks are a pure function of V): re-decode each durable
        commit's pushed wire arrays and re-apply — bitwise, because
        the decode is a pure function of the logged bytes."""
        store = new_store()
        events: list = []
        if cfg.wal_dir is None:
            return store, 0, events
        records, _base = walmod.WriteAheadLog.replay(
            cfg.wal_dir, 1 << 30)
        for kind, meta, arrays in records:
            if kind != "rowcommit":
                continue
            it = int(meta["window"])
            _apply_commit(store, it, meta, arrays)
            events.append(_commit_event(it, meta))
        return store, store.version, events

    def _commit_event(it: int, meta: dict) -> tuple:
        return ("rowcommit", it,
                tuple(int(c["digest"]) for c in meta["contribs"]))

    def _apply_commit(store: RowStore, it: int, meta: dict,
                      arrays: dict) -> None:
        """Slot-ordered origin accumulation (the sparse_allreduce
        contract) + the dangling/teleport update, applied through the
        row store."""
        c = np.zeros((V,), np.float32)
        for contrib in meta["contribs"]:
            s = int(contrib["slot"])
            prefix = f"{s}/"
            sub = {k[len(prefix):]: v for k, v in arrays.items()
                   if k.startswith(prefix)}
            idx, vals = decode_row_push(
                codec, "ranks", sub, workers[s].push_idx.shape[0])
            np.add.at(c, idx, vals)
        dangling = np.float32(meta["dangling"])
        new_ranks = q / np.float32(V) + (np.float32(1.0) - q) * (
            c + dangling / np.float32(V))
        store.replace_rows(it, "ranks", np.arange(V, dtype=np.int64),
                           new_ranks)
        tevents.gauge("rowstore.max_row_staleness", 0)

    store = new_store()
    events: list = []
    if wal is not None:
        store, ver, events = recover()
        wal.open_segment(0, {"workload": "pagerank",
                             "n_iterations": n_iters})
    recoveries = 0
    restarts = 0
    fired_cells: set[tuple[str, int]] = set()
    t0 = time.monotonic()

    it = store.version
    while it < n_iters:
        try:
            # ---- workers: sparse pull, window compute, sparse push
            pushes: dict[int, tuple[dict, dict]] = {}

            def run_worker(s: int):
                wkr = workers[s]
                cell = float(worker_sched[it, s])
                if cell == KILL_CELL and ("w", it * S + s) \
                        not in fired_cells:
                    fired_cells.add(("w", it * S + s))
                    raise fregistry.InjectedKill(
                        f"worker {s} killed at iteration {it}")
                pulled, _vers = store.pull_rows("ranks", wkr.pull_idx)
                _pm, _pa = frame_roundtrip(
                    "rowpull",
                    {"slot": s, "window": it, "rows":
                     int(wkr.pull_idx.shape[0])},
                    {"ranks.rows": wkr.pull_idx,
                     "ranks.val": pulled},
                    counter="rowstore.wire_pull_bytes")
                tevents.counter("rowstore.wire_dense_bytes", 4 * V)
                vals = wkr.contribs(pulled)
                arrays = encode_row_push(
                    codec, "ranks", wkr.push_idx, vals, it, s)
                meta, arrs = frame_roundtrip(
                    "rowpush", {"slot": s, "window": it, "base": it},
                    arrays, counter="rowstore.wire_push_bytes")
                tevents.counter("rowstore.rows_pushed",
                                int(wkr.push_idx.shape[0]))
                tevents.counter("rowstore.wire_dense_bytes", 4 * V)
                pushes[s] = (meta, arrs)

            for s in range(S):
                # deterministic respawn: a killed worker's iteration
                # recomputes from the same pulled rows — same bytes
                for attempt in (0, 1):
                    try:
                        run_worker(s)
                        break
                    except fregistry.InjectedKill:
                        if attempt:
                            raise
                        recoveries += 1
                        tevents.counter("cluster.recoveries")

            # ---- commit (coordinator role), slot order
            cell = float(coord_sched[it, 0])
            if cell != 0.0 and ("c", it) not in fired_cells:
                fired_cells.add(("c", it))
                if cell == KILL_CELL:
                    # pushes in RAM, record not durable: rollback path
                    raise _FleetDied(f"coordinator kill at {it}")
                time.sleep(cell)
            snap = store.snapshot()["ranks"]
            dangling = float(np.float32(np.sum(
                snap * (np.float32(1.0) - has_out))))
            wal_meta = {
                "window": it, "version": it + 1,
                "dangling": dangling,
                "contribs": [
                    {"slot": s,
                     "digest": walmod.delta_digest(pushes[s][1])}
                    for s in sorted(pushes)],
            }
            wal_arrays = {f"{s}/{k}": v for s in sorted(pushes)
                          for k, v in pushes[s][1].items()}
            if wal is not None:
                wal.append("rowcommit", wal_meta, wal_arrays)
            # the cluster:ps seam: record durable, merge not applied —
            # a kill here exercises the REDO path (replay re-applies)
            cell = float(ps_sched[it, 0])
            if cell != 0.0 and ("p", it) not in fired_cells:
                fired_cells.add(("p", it))
                if cell == KILL_CELL:
                    raise _FleetDied(f"ps shard kill at {it}")
                time.sleep(cell)
            _apply_commit(store, it, wal_meta, wal_arrays)
            events.append(_commit_event(it, wal_meta))
            it = store.version
        except _FleetDied:
            restarts += 1
            recoveries += 1
            tevents.counter("cluster.recoveries")
            if restarts > cfg.max_restarts:
                raise
            if wal is None:
                raise RuntimeError(
                    "a coordinator/ps kill fired without a wal_dir — "
                    "nothing to recover from")
            store, _ver, events = recover()
            wal.open_segment(0, {"workload": "pagerank",
                                 "n_iterations": n_iters})
            it = store.version

    if wal is not None:
        wal.close()
    elapsed = time.monotonic() - t0
    return {
        "ranks": store.snapshot()["ranks"],
        "version": store.version,
        "events": events,
        "event_digest": pagerank_event_digest(events),
        "recoveries": recoveries,
        "elapsed_s": elapsed,
        "iters_per_sec": (n_iters / elapsed if elapsed > 0
                          else float("inf")),
        "sparse_pull_fraction": sparse_fraction,
        "peak_pull_rows": int(peak_pull),
        "n_vertices": V,
        "n_workers": S,
    }
