"""Worker process — the existing SGD-family trainers behind push/pull.

A worker owns one SLOT of the cluster's data (a contiguous row block
of the coordinator-described task), builds its OWN local mesh
(``get_mesh(data=1)`` over its host devices), and runs the EXISTING
trainers' compiled window loops — ``ssgd.make_train_fn`` (per-tick
minibatch SGD) or ``local_sgd.make_train_fn`` (the MA-family local
rounds) — between push/pull seams: at each window boundary it pushes
its accumulated center delta (``w_local − w_base``) with the base
version it trained against, and the deferred ack returns the
post-commit center it adopts next. Staleness weighting happens at the
PS (``decay**age``); the worker's only clock duty is the GATE: it may
not start window ``k`` until ``k − version ≤ s`` (the cross-process
spelling of ``parallel/ssp.py``'s conservative bound).

Fault schedule (plan-pure, like ``ssp.compile_straggle_schedule``):
:func:`compile_worker_schedule` probes ``cluster:worker`` once per
(window, slot) cell in row-major order against a fresh quiet registry
— the same plan compiles the same schedule in every process, which is
what makes a chaos run replayable. Cell kinds:

  * ``straggle:u`` — the worker announces a SKIP for the window at its
    START (so peers' commit never waits on the interference), then
    pays ``u`` units of real compute (``ssp.straggle_work``) on top of
    the window's ticks; its delta rides a later boundary, staler.
  * ``kill`` — the worker runs HALF the window's ticks and then
    ``kill -9``\\ s itself (``os.kill(getpid(), SIGKILL)``); in thread
    mode the injected ``die`` slams the sockets instead, which is the
    same observable (EOF at the coordinator).

Liveness: a ``telemetry/heartbeat.py`` ``Heartbeat`` thread beats over
a SECOND connection (``emit_fn`` both records the event and sends the
frame), so a worker wedged in compute is still visibly alive and a
partitioned one goes visibly silent. The beat loop survives transient
send failures: a broken heartbeat connection is re-dialed with a
short bounded retry (``cluster.heartbeat_retries``) instead of
leaving the socket dead while the main loop lives.

RECONNECT (coordinator crash tolerance): ``TransportClosed``/
``TransportTimeout`` on the control connection no longer kills the
worker. :class:`_Link` wraps every control-plane round trip in a
bounded retry/backoff/jitter loop (``telemetry.supervisor.supervised``
— the same generalized core behind backend init and checkpoint
writes): it re-dials, re-presents its slot + incarnation token
(``resume`` join), and re-sends the request. A recovered coordinator
re-admits a matching incarnation WITHOUT burning a membership epoch;
a push whose window was committed before the crash (the ack died with
the coordinator) is deduped by the WAL's commit digest, and a push
whose window was rolled back simply re-delivers — either way the
worker cannot tell a recovered coordinator from one that never died,
which is the whole determinism story. Only if the coordinator
declared this incarnation dead during the outage does the worker get
a FRESH admission (a ``reset``): it adopts the new center at the new
admission window, exactly like a replacement process would.
"""

from __future__ import annotations

import os
import signal
import threading
import time

import numpy as np

from tpu_distalg.cluster import transport
from tpu_distalg.faults import registry as fregistry
from tpu_distalg.parallel import ssp as pssp
from tpu_distalg.telemetry import events as tevents
from tpu_distalg.telemetry import heartbeat as theartbeat
from tpu_distalg.telemetry.supervisor import supervised

#: per-slot sampling-seed stride: slots draw independent minibatches
SLOT_SEED_STRIDE = 1_000_003
#: how long the gate polls before giving up on a wedged coordinator
GATE_DEADLINE_SECONDS = 300.0
GATE_POLL_SECONDS = 0.02

#: schedule cell code for a kill (straggle cells hold their +units)
KILL = -1

#: control-connection reconnect budget: retries × capped backoff must
#: comfortably cover a coordinator respawn (process spawn + checkpoint
#: restore + WAL replay + bind) — exhaustion is a real outage
RECONNECT_RETRIES = 20
RECONNECT_BACKOFF_SECONDS = 0.1
RECONNECT_BACKOFF_CAP_SECONDS = 1.0
RECONNECT_JITTER = 0.25


class _Link:
    """The worker's control connection with crash-tolerant round
    trips: every request retries through re-dial + resume-join on a
    closed/timed-out transport, with bounded exponential backoff +
    jitter. A resume that comes back as a FRESH admission (the
    coordinator declared this incarnation dead during the outage)
    surfaces as a synthetic ``("reset", welcome, center)`` reply the
    main loop adopts like a new join."""

    def __init__(self, host, port, sock, connect, ident, rpc_deadline,
                 stats, log):
        self.host, self.port = host, port
        self.sock = sock
        self.connect = connect
        self.ident = ident          # shared with the caller: a fresh
        #                             admission swaps the token in place
        self.rpc_deadline = rpc_deadline
        self.stats = stats
        self.log = log
        self._pending_reset = None

    def drop(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def _resume(self, *, dial_attempts: int = 200,
                resume_only: bool = False):
        """Re-dial and re-present the incarnation token. Sets
        ``_pending_reset`` when the coordinator hands out a fresh
        admission instead of a resume; ``resume_only`` forbids that
        fallback (the bye's mode — a dead incarnation's farewell must
        not be answered with a GHOST admission nobody will drive)."""
        # fine-grained dial: the recovery metric is detect→recover→
        # first-recommitted-window, and a coarse retry sleep here
        # would put its floor at the sleep, not at the real respawn
        sock = self.connect(self.host, self.port,
                            attempts=dial_attempts,
                            retry_sleep=0.05)
        try:
            k, m, arrs = transport.request(
                sock, "join",
                {"slot": self.ident["slot"], "inc": self.ident["inc"],
                 "resume": True, "rejoin": True,
                 "resume_only": resume_only},
                deadline=self.rpc_deadline)
        except transport.TransportError:
            try:
                sock.close()
            except OSError:
                pass
            raise
        if k != "welcome":
            try:
                sock.close()
            except OSError:
                pass
            raise transport.TransportClosed(
                f"resume-join rejected: {m.get('error', k)}")
        self.sock = sock
        self.stats["reconnects"] += 1
        tevents.counter("cluster.reconnects")
        tevents.emit("cluster_worker_reconnect",
                     slot=self.ident["slot"],
                     resumed=bool(m.get("resume")))
        if m.get("resume"):
            return
        # fencing moved on: fresh incarnation, fresh admission — the
        # old incarnation's unpushed work is dropped, like a dead
        # worker's would be
        self.ident["inc"] = int(m["incarnation"])
        self.stats["readmissions"] += 1
        tevents.counter("cluster.readmissions")
        self._pending_reset = (dict(m), dict(arrs))

    def request(self, kind, meta, arrays=None, *, deadline=None,
                retries=RECONNECT_RETRIES):
        """One crash-tolerant round trip; may return the synthetic
        ``reset`` reply instead of the requested one. ``retries``
        trims the whole budget for best-effort frames — the re-dial
        inside the retry shrinks with it, so a bye against a
        coordinator that already exited fails in seconds, not
        minutes — and a trimmed-budget frame is also RESUME-ONLY (a
        farewell must never be answered with a fresh admission)."""
        deadline = deadline if deadline is not None \
            else self.rpc_deadline
        best_effort = retries < RECONNECT_RETRIES

        def attempt():
            if self.sock is None:
                self._resume(
                    dial_attempts=20 if best_effort else 200,
                    resume_only=best_effort)
                if self._pending_reset is not None:
                    m, arrs = self._pending_reset
                    self._pending_reset = None
                    return ("reset", m, arrs)
            try:
                return transport.request(self.sock, kind, meta,
                                         arrays, deadline=deadline)
            except (transport.TransportClosed,
                    transport.TransportTimeout):
                self.drop()
                raise

        return supervised(
            attempt, phase="cluster_rpc",
            retries=retries,
            backoff=RECONNECT_BACKOFF_SECONDS,
            backoff_cap=RECONNECT_BACKOFF_CAP_SECONDS,
            jitter=RECONNECT_JITTER,
            retry_on=(transport.TransportClosed,
                      transport.TransportTimeout),
            event="cluster_reconnect",
            failure_counter="cluster.rpc_failures",
            log=self.log)


class _HbLink:
    """The heartbeat connection with transient-failure survival: a
    failed beat drops + re-dials the socket with a short in-beat
    retry and bumps ``cluster.heartbeat_retries`` — the beat thread
    itself never dies of an I/O error (the main loop may be healthy
    and compute-bound; a silently dead beat loop would get it
    declared dead by the coordinator's heartbeat scan)."""

    RETRIES = 2

    def __init__(self, host, port, connect, ident, deadline, stats):
        self.host, self.port = host, port
        self.connect = connect
        self.ident = ident
        self.deadline = deadline
        self.stats = stats
        self.sock = None
        self.lock = threading.Lock()

    def _drop(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def beat(self) -> None:
        with self.lock:
            for attempt in range(self.RETRIES + 1):
                try:
                    if self.sock is None:
                        # short-fused dial: a beat must not wedge the
                        # beat thread for the full connect budget —
                        # the NEXT interval retries anyway
                        self.sock = self.connect(
                            self.host, self.port, attempts=2,
                            retry_sleep=0.05)
                    transport.send_frame(self.sock, "beat",
                                         dict(self.ident),
                                         deadline=self.deadline)
                    transport.recv_frame(self.sock,
                                         deadline=self.deadline)
                    return
                except (transport.TransportError, OSError):
                    self._drop()
                    self.stats["heartbeat_retries"] += 1
                    tevents.counter("cluster.heartbeat_retries")
                    if attempt < self.RETRIES:
                        time.sleep(0.05 * (attempt + 1))
            # still down after the in-beat retries: stay alive — the
            # next interval's beat re-dials again

    def close(self):
        with self.lock:
            self._drop()


class WorkerKilled(Exception):
    """Thread-mode stand-in for SIGKILL (the real worker never raises
    this — it is gone)."""


def compile_worker_schedule(n_windows: int, n_slots: int, *,
                            plan=None) -> np.ndarray:
    """The (n_windows, n_slots) int32 cluster fault schedule from the
    plan's ``cluster:worker`` rules: cell > 0 = straggle units, cell
    == -1 = kill. One probe per cell in row-major order against a
    FRESH quiet registry (a pure function of the plan — every process
    compiles the identical schedule); fires mirror into the live
    ledger exactly once, like the SSP compilers."""
    live = fregistry.active()
    if plan is None:
        plan = live.plan if live is not None else None
    out = np.zeros((n_windows, n_slots), np.int32)
    if plan is None or not any(
            r.point == "cluster:worker" for r in plan.rules):
        return out
    reg = fregistry.FaultRegistry(plan, quiet=True)
    for w in range(n_windows):
        for k in range(n_slots):
            hit = reg.probe("cluster:worker")
            if hit is None:
                continue
            kind, arg = hit
            if kind == "kill":
                out[w, k] = KILL
            else:
                out[w, k] = int(arg if arg is not None
                                else fregistry.DEFAULT_STRAGGLE_UNITS)
    if live is not None and live.plan == plan:
        live.record(reg.fired)
    return out


def strip_kills(plan_spec: str | None,
                points: tuple[str, ...] = ("cluster:worker",)
                ) -> str | None:
    """The plan with its KILL rules at ``points`` removed — what a
    respawned incarnation runs under (the fault was transient: a
    restarted executor — or a recovered coordinator, with
    ``points=('cluster:coordinator',)`` — re-dying on the same
    deterministic cell would loop forever, in both the elastic and
    the restart-baseline arms)."""
    if not plan_spec:
        return plan_spec
    plan = fregistry.FaultPlan.parse(plan_spec)
    rules = tuple(r for r in plan.rules
                  if not (r.point in points and r.kind == "kill"))
    return fregistry.FaultPlan(seed=plan.seed, rules=rules).spec()


def _slot_rows(task: dict, slot: int, n_slots: int):
    """This slot's contiguous row block of the shared synthetic task
    (the whole-task generation is deterministic in the data seed, so
    every incarnation of a slot sees identical rows)."""
    from tpu_distalg.utils import datasets

    n_rows = int(task["n_rows"])
    X, y = datasets.synthetic_two_class(
        n_rows + int(task["test_rows"]), int(task["n_features"]),
        seed=int(task["data_seed"]))
    X = datasets.add_bias_column(X)
    per = -(-n_rows // n_slots)
    lo = min(slot * per, n_rows)
    hi = min(lo + per, n_rows)
    if hi <= lo:
        raise ValueError(
            f"slot {slot} owns no rows: {n_rows} rows over "
            f"{n_slots} slots")
    return (np.ascontiguousarray(X[lo:hi]),
            np.ascontiguousarray(y[lo:hi]))


class LocalTrainer:
    """One slot's compiled window loops over the EXISTING trainers, on
    the worker's own local mesh. ``run(w, window, n_ticks)`` executes
    ``n_ticks`` local ticks starting at the window's absolute first
    tick and returns the new local weights (host ndarray)."""

    def __init__(self, task: dict, slot: int, n_slots: int, s: int):
        import jax
        import jax.numpy as jnp

        from tpu_distalg.parallel import get_mesh

        self.s = s
        self.slot = slot
        self.algo = task.get("algo", "ssgd")
        X, y = _slot_rows(task, slot, n_slots)
        self.X = jnp.asarray(X)
        self.y = jnp.asarray(y)
        self.valid = jnp.ones((X.shape[0],), jnp.float32)
        d = X.shape[1]
        self.dummy_te = (jnp.zeros((1, d), jnp.float32),
                         jnp.zeros((1,), jnp.float32))
        self.mesh = get_mesh(data=1, devices=jax.devices()[:1])
        seed = int(task["seed"]) + SLOT_SEED_STRIDE * slot
        self._fns: dict[int, object] = {}
        if self.algo == "local_sgd":
            from tpu_distalg.models import local_sgd as lsgd

            def make(n_ticks):
                cfg = lsgd.LocalSGDConfig(
                    n_iterations=1, n_local_iterations=n_ticks,
                    eta=float(task["eta"]),
                    mini_batch_fraction=float(
                        task["mini_batch_fraction"]),
                    seed=seed, eval_test=False)
                return lsgd.make_train_fn(self.mesh, cfg,
                                          X.shape[0])
        elif self.algo == "ssgd":
            from tpu_distalg.models import ssgd

            def make(n_ticks):
                cfg = ssgd.SSGDConfig(
                    n_iterations=n_ticks, eta=float(task["eta"]),
                    mini_batch_fraction=float(
                        task["mini_batch_fraction"]),
                    lam=float(task["lam"]),
                    reg_type=task.get("reg_type", "l2"),
                    seed=seed, eval_test=False)
                return ssgd.make_train_fn(self.mesh, cfg, X.shape[0])
        else:
            raise ValueError(
                f"unknown cluster algo {self.algo!r}: 'ssgd' or "
                f"'local_sgd'")
        self._make = make

    def run(self, w: np.ndarray, window: int, n_ticks: int
            ) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        if n_ticks not in self._fns:
            self._fns[n_ticks] = self._make(n_ticks)
        fn = self._fns[n_ticks]
        w_j = jnp.asarray(w, jnp.float32)
        if self.algo == "local_sgd":
            # one MA round of n_ticks local steps; t0 = the absolute
            # ROUND id (the round scan's sampling key unit)
            w_out, _ws, _delta, _accs = fn(
                self.X, self.y, self.valid, *self.dummy_te,
                w_j, w_j[None, :],
                jnp.zeros_like(w_j), t0=window)
        else:
            # absolute tick ids thread the PRNG, so a window replay
            # (or a respawned incarnation) samples identically
            w_out, _accs = fn(self.X, self.y, self.valid,
                              *self.dummy_te, w_j,
                              t0=window * self.s)
        return np.asarray(jax.block_until_ready(w_out), np.float32)

    def straggle(self, units: int) -> None:
        """Pay real interference compute (the compiled-in straggler of
        ``parallel/ssp.py``, here an honest host-device burn)."""
        import jax

        jax.block_until_ready(
            _straggle_fn()(np.int32(units * 50)))


_STRAGGLE_CACHE: dict = {}


def _straggle_fn():
    import jax

    fn = _STRAGGLE_CACHE.get("fn")
    if fn is None:
        fn = _STRAGGLE_CACHE["fn"] = jax.jit(
            lambda u: pssp.straggle_work(u, 1.0))
    return fn


def _default_die():
    os.kill(os.getpid(), signal.SIGKILL)


def run_worker(host: str, port: int, *, slot: int | None = None,
               rejoin: bool = False, admit_at: int | None = None,
               die=None, connect=None, logger=None) -> dict:
    """The worker main loop: join → (gate → train window → push/skip)*
    → bye. Returns its stats dict (the real process also reports them
    in the ``bye`` frame and via its telemetry dir). ``die`` overrides
    the kill-cell action for thread-mode tests (default: a real
    ``SIGKILL`` on this process); ``connect`` overrides the dialer
    (thread mode tracks its sockets through it). ``admit_at`` pins a
    rejoiner's first window (the launcher's plan-determined admission
    — the coordinator holds that window's commit for it)."""
    log = logger or (lambda m: None)
    die = die or _default_die
    connect = connect or transport.connect
    sock = None
    last_err: Exception | None = None
    for attempt in range(80):
        try:
            if sock is None:
                sock = connect(host, port)
            kind, meta, center = transport.request(
                sock, "join",
                {"slot": slot, "rejoin": rejoin,
                 "admit_at": admit_at})
        except transport.TransportError as e:
            # a torn dial/handshake (an rpc-storm fault, or the
            # coordinator mid-recovery): re-dial, like every later
            # round trip does through the link
            last_err = e
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
                sock = None
            time.sleep(0.25)
            continue
        if kind == "welcome":
            break
        if "slots active" in str(meta.get("error", "")) \
                and attempt < 79:
            # a replacement racing the coordinator's EOF processing of
            # its predecessor: the slot reads ACTIVE for a beat after
            # the old process died — retry briefly instead of wedging
            # the admission hold forever
            time.sleep(0.25)
            continue
        sock.close()
        raise RuntimeError(
            f"join rejected: {meta.get('error', kind)}")
    else:
        raise transport.TransportClosed(
            f"could not join the coordinator at {host}:{port} after "
            f"80 attempts: {last_err}")
    slot = int(meta["slot"])
    inc = int(meta.get("incarnation", 0))
    # the fencing token: every frame this incarnation sends carries it,
    # so a replacement can never be confused with its zombie (the link
    # shares this dict — a fresh re-admission swaps the token in place)
    ident = {"slot": slot, "inc": inc}
    s = int(meta["s"])
    n_windows = int(meta["n_windows"])
    n_slots = int(meta["n_slots"])
    rpc_deadline = float(meta.get("rpc_deadline", 30.0))
    task = meta["train"]
    plan = meta.get("plan")
    schedule = compile_worker_schedule(
        n_windows, n_slots,
        plan=fregistry.FaultPlan.parse(plan) if plan else None)
    trainer = LocalTrainer(task, slot, n_slots, s)
    tevents.emit("cluster_worker_start", slot=slot,
                 admit=meta["admit"], gen=meta["gen"])
    tevents.mark(f"cluster:worker{slot}", emit_event=False)

    stats = {"pushes": 0, "skips": 0, "gated_ms": 0.0,
             "push_pull_ms_total": 0.0, "push_pull_ms": [],
             "ages": [], "windows": 0, "undelivered_windows": 0,
             "reconnects": 0, "readmissions": 0,
             "heartbeat_retries": 0}
    link = _Link(host, port, sock, connect, ident, rpc_deadline,
                 stats, log)

    # liveness: the shared Heartbeat thread, its emit_fn ALSO framing a
    # beat to the coordinator over its own crash-tolerant link —
    # compute-bound windows stay visibly alive, a partition goes
    # visibly silent, and one broken beat never ends the loop
    hb_link = _HbLink(host, port, connect, ident, rpc_deadline, stats)

    def hb_emit(ev, **fields):
        tevents.emit(ev, **fields)
        if ev == "heartbeat":
            hb_link.beat()

    hb = theartbeat.Heartbeat(
        interval=float(meta.get("heartbeat_interval", 0.5)),
        stall_after=None, emit_fn=hb_emit)
    hb.start()

    pending_windows = 0   # trained-but-not-yet-pushed (busy skips)
    version = int(meta["version"])
    w_base = np.asarray(center["w"], np.float32)
    w_local = w_base.copy()
    base = version
    window = int(meta["admit"])
    done = bool(meta.get("done"))
    restart = False
    killed = False

    def adopt_reset(m, arrays):
        """A fresh re-admission (the old incarnation was declared
        dead during a coordinator outage): adopt the welcome like a
        brand-new join — new admission window, the current center,
        zero pending work."""
        nonlocal version, done, restart, window, w_base, w_local, \
            base, pending_windows
        version = int(m["version"])
        done = bool(m.get("done"))
        restart = bool(m.get("restart"))
        window = int(m["admit"])
        w_base = np.asarray(arrays["w"], np.float32)
        w_local = w_base.copy()
        base = version
        pending_windows = 0

    def rpc(kind, meta_, arrays=None, deadline=None):
        """One crash-tolerant round trip; folds a ``reset`` into the
        loop state and reports it so call sites can restart their
        iteration."""
        nonlocal version, done, restart
        k, m, arrs = link.request(kind, meta_, arrays,
                                  deadline=deadline)
        if k == "reset":
            adopt_reset(m, arrs)
            return k, m, arrs
        version = int(m.get("version", version))
        done = bool(m.get("done", done))
        restart = bool(m.get("restart", restart))
        return k, m, arrs

    try:
        if window > version:
            # pinned late admission: wait for the clock to reach the
            # admission window, then re-pull — the first delivery's
            # base (and so its age/weight) is plan-determined, not
            # join-timing-determined
            t_gate = time.monotonic()
            while version < window and not done and not restart:
                if time.monotonic() - t_gate > GATE_DEADLINE_SECONDS:
                    raise transport.TransportTimeout(
                        f"admission starved: version {version} never "
                        f"reached admit window {window}")
                time.sleep(GATE_POLL_SECONDS)
                rpc("poll", dict(ident))
            if not done and not restart:
                k, m, arrays = rpc("pull", dict(ident))
                if k != "reset":
                    w_base = np.asarray(arrays["w"], np.float32)
                    w_local = w_base.copy()
                    base = version
        while window < n_windows and not done and not restart:
            # the SSP gate: never more than s windows past the clock
            t_gate = time.monotonic()
            while window - version > s:
                if time.monotonic() - t_gate > GATE_DEADLINE_SECONDS:
                    raise transport.TransportTimeout(
                        f"gate starved: window {window} vs version "
                        f"{version} for {GATE_DEADLINE_SECONDS}s")
                time.sleep(GATE_POLL_SECONDS)
                k, _, _ = rpc("poll", dict(ident))
                if k == "reset" or done or restart:
                    break
            if done or restart:
                break
            if time.monotonic() - t_gate > 2 * GATE_POLL_SECONDS:
                stats["gated_ms"] += (time.monotonic() - t_gate) * 1e3
            cell = int(schedule[window, slot]) \
                if window < schedule.shape[0] else 0
            tevents.mark(f"cluster:worker{slot}@w{window}",
                         emit_event=False)
            if cell == KILL:
                # kill -9 MID-WINDOW: half the ticks land, the push
                # never happens, the sockets slam shut (EOF is the
                # coordinator's fastest death signal)
                w_local = trainer.run(w_local, window,
                                      max(1, s // 2))
                tevents.emit("cluster_worker_kill", slot=slot,
                             window=window)
                killed = True
                die()
                return stats          # thread-mode die() returns
            busy = cell > 0
            if busy:
                # pre-announced skip: peers' commit of THIS window
                # must not wait out the interference
                k, _, _ = rpc("skip", dict(ident, window=window))
                if k == "reset":
                    continue
                stats["skips"] += 1
                tevents.counter("cluster.skips")
            w_local = trainer.run(w_local, window, s)
            stats["windows"] += 1
            if busy:
                trainer.straggle(cell)
                pending_windows += 1
                window += 1
                continue
            delta = w_local - w_base
            t0 = time.monotonic()
            # the ack is DEFERRED until this window commits — which
            # can legitimately wait out an admission hold (a respawned
            # PROCESS worker pays spawn + jax import + first compile),
            # so the recv deadline is the gate's, not the rpc's
            k2, m, arrays = rpc(
                "push",
                dict(ident, window=window, base=base),
                {"w": delta},
                deadline=max(rpc_deadline, GATE_DEADLINE_SECONDS))
            rtt = (time.monotonic() - t0) * 1e3
            if k2 == "reset":
                continue
            if k2 == "error":
                raise transport.TransportClosed(
                    f"push rejected: {m.get('error')}")
            stats["pushes"] += 1
            stats["push_pull_ms"].append(round(rtt, 3))
            stats["push_pull_ms_total"] += rtt
            stats["ages"].append(max(0, window - base))
            tevents.counter("cluster.pushes")
            # adopt the post-commit center: fresh base, zero delta
            w_base = np.asarray(arrays["w"], np.float32)
            w_local = w_base.copy()
            base = version
            pending_windows = 0
            window += 1
    finally:
        hb.stop()
        hb_link.close()
        if not killed:
            if pending_windows:
                # a straggle cell on the FINAL window(s) leaves
                # trained work with no later boundary to ride — the
                # in-process SSP drops a boundary-busy final window's
                # pending delta the same way (the scan ends); record
                # the loss instead of letting it pass silently
                stats["undelivered_windows"] = pending_windows
                tevents.counter("cluster.undelivered_windows",
                                pending_windows)
                tevents.emit("cluster_undelivered", slot=slot,
                             windows=pending_windows)
            ages = stats.pop("ages", [])
            stats["mean_age"] = (round(float(np.mean(ages)), 4)
                                 if ages else 0.0)
            stats["max_age"] = int(max(ages)) if ages else 0
            rtts = stats.pop("push_pull_ms", [])
            stats["push_pull_ms_p50"] = (
                round(float(np.percentile(rtts, 50)), 3)
                if rtts else 0.0)
            try:
                link.request("bye", dict(ident, stats=stats),
                             retries=1)
            except transport.TransportError:
                pass
            pssp.emit_ssp_counters(
                pssp.SyncSpec(mode="ssp", staleness=s),
                {"merges": stats["pushes"],
                 "max_staleness": stats["max_age"],
                 "mean_staleness": stats["mean_age"]},
                straggle_ticks=stats["skips"] * s)
            tevents.counter("cluster.gated_ms",
                            int(stats["gated_ms"]))
            tevents.emit("cluster_worker_done", slot=slot, **{
                k: v for k, v in stats.items()
                if not isinstance(v, list)})
            log(f"[cluster] worker {slot} done: {stats['pushes']} "
                f"push(es), {stats['skips']} skip(s)")
            link.drop()
    stats["restart"] = restart
    return stats
