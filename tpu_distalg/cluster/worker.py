"""Worker process — the existing SGD-family trainers behind push/pull.

A worker owns one SLOT of the cluster's data (a contiguous row block
of the coordinator-described task), builds its OWN local mesh
(``get_mesh(data=1)`` over its host devices), and runs the EXISTING
trainers' compiled window loops — ``ssgd.make_train_fn`` (per-tick
minibatch SGD) or ``local_sgd.make_train_fn`` (the MA-family local
rounds) — between push/pull seams: at each window boundary it pushes
its accumulated center delta (``w_local − w_base``) with the base
version it trained against, and the deferred ack returns the
post-commit center it adopts next. Staleness weighting happens at the
PS (``decay**age``); the worker's only clock duty is the GATE: it may
not start window ``k`` until ``k − version ≤ s`` (the cross-process
spelling of ``parallel/ssp.py``'s conservative bound).

Fault schedule (plan-pure, like ``ssp.compile_straggle_schedule``):
:func:`compile_worker_schedule` probes ``cluster:worker`` once per
(window, slot) cell in row-major order against a fresh quiet registry
— the same plan compiles the same schedule in every process, which is
what makes a chaos run replayable. Cell kinds:

  * ``straggle:u`` — the worker announces a SKIP for the window at its
    START (so peers' commit never waits on the interference), then
    pays ``u`` units of real compute (``ssp.straggle_work``) on top of
    the window's ticks; its delta rides a later boundary, staler.
  * ``kill`` — the worker runs HALF the window's ticks and then
    ``kill -9``\\ s itself (``os.kill(getpid(), SIGKILL)``); in thread
    mode the injected ``die`` slams the sockets instead, which is the
    same observable (EOF at the coordinator).

Liveness: a ``telemetry/heartbeat.py`` ``Heartbeat`` thread beats over
a SECOND connection (``emit_fn`` both records the event and sends the
frame), so a worker wedged in compute is still visibly alive and a
partitioned one goes visibly silent. The beat loop survives transient
send failures: a broken heartbeat connection is re-dialed with a
short bounded retry (``cluster.heartbeat_retries``) instead of
leaving the socket dead while the main loop lives.

COMPRESSED WIRE (``--comm int8[:seed]``/``topk[:frac]``, from the
welcome frame): the window delta is host-encoded BEFORE transport
framing (``parallel/comms.py`` codecs — seeded per (slot, window), so
replays are bitwise), with the error-feedback residual carried in
THIS loop's state: what the wire did not carry rides into the next
window's encode, and a fresh re-admission (reset) zeroes it with the
rest of the local state. Pulls are version deltas: the worker caches
``center@have`` and the deferred ack ships only the compressed diff
to the push's own commit version (dense snapshot on rejoin/deep
recovery). Unless ``@seq``, the push runs ASYNCHRONOUSLY on a
background sender over a second crash-tolerant :class:`_Link`, so
the next window's ticks start immediately; the next boundary
harvests the ack and REBASES the local weights onto the fresher
center (stale-model SSP — the gate's ``window − version ≤ s`` bound
is unchanged, because the version still only advances at commit).

RECONNECT (coordinator crash tolerance): ``TransportClosed``/
``TransportTimeout`` on the control connection no longer kills the
worker. :class:`_Link` wraps every control-plane round trip in a
bounded retry/backoff/jitter loop (``telemetry.supervisor.supervised``
— the same generalized core behind backend init and checkpoint
writes): it re-dials, re-presents its slot + incarnation token
(``resume`` join), and re-sends the request. A recovered coordinator
re-admits a matching incarnation WITHOUT burning a membership epoch;
a push whose window was committed before the crash (the ack died with
the coordinator) is deduped by the WAL's commit digest, and a push
whose window was rolled back simply re-delivers — either way the
worker cannot tell a recovered coordinator from one that never died,
which is the whole determinism story. Only if the coordinator
declared this incarnation dead during the outage does the worker get
a FRESH admission (a ``reset``): it adopts the new center at the new
admission window, exactly like a replacement process would.
"""

from __future__ import annotations

import os
import signal
import threading
import time

import numpy as np

from tpu_distalg.cluster import transport
from tpu_distalg.faults import registry as fregistry
from tpu_distalg.parallel import comms as pcomms
from tpu_distalg.parallel import ssp as pssp
from tpu_distalg.telemetry import events as tevents
from tpu_distalg.telemetry import heartbeat as theartbeat
from tpu_distalg.telemetry.supervisor import supervised
from tpu_distalg.tune import defaults as tune_defaults

#: per-slot sampling-seed stride: slots draw independent minibatches
SLOT_SEED_STRIDE = 1_000_003
#: how long the gate polls before giving up on a wedged coordinator
GATE_DEADLINE_SECONDS = 300.0
GATE_POLL_SECONDS = 0.02

#: schedule cell code for a kill (straggle cells hold their +units)
KILL = -1

#: control-connection reconnect budget: retries × capped backoff must
#: comfortably cover a coordinator respawn (process spawn + checkpoint
#: restore + WAL replay + bind) — exhaustion is a real outage
RECONNECT_RETRIES = 20
RECONNECT_BACKOFF_SECONDS = 0.1
RECONNECT_BACKOFF_CAP_SECONDS = 1.0
RECONNECT_JITTER = 0.25


class LinkClosed(RuntimeError):
    """The link was closed on purpose (worker shutdown / kill cell):
    NOT a transport fault, so the retry loop never re-dials — a
    background pusher outliving a thread-mode kill must not
    resume-join and resurrect the slot."""


class _Link:
    """The worker's control connection with crash-tolerant round
    trips: every request retries through re-dial + resume-join on a
    closed/timed-out transport, with bounded exponential backoff +
    jitter. A resume that comes back as a FRESH admission (the
    coordinator declared this incarnation dead during the outage)
    surfaces as a synthetic ``("reset", welcome, center)`` reply the
    main loop adopts like a new join."""

    def __init__(self, host, port, sock, connect, ident, rpc_deadline,
                 stats, log):
        self.host, self.port = host, port
        self.sock = sock
        self.connect = connect
        self.ident = ident          # shared with the caller: a fresh
        #                             admission swaps the token in place
        self.rpc_deadline = rpc_deadline
        self.stats = stats
        self.log = log
        self.closed = False
        self._pending_reset = None

    def drop(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def close(self):
        self.closed = True
        self.drop()

    def _resume(self, *, dial_attempts: int = 200,
                resume_only: bool = False):
        """Re-dial and re-present the incarnation token. Sets
        ``_pending_reset`` when the coordinator hands out a fresh
        admission instead of a resume; ``resume_only`` forbids that
        fallback (the bye's mode — a dead incarnation's farewell must
        not be answered with a GHOST admission nobody will drive)."""
        # fine-grained dial: the recovery metric is detect→recover→
        # first-recommitted-window, and a coarse retry sleep here
        # would put its floor at the sleep, not at the real respawn
        sock = self.connect(self.host, self.port,
                            attempts=dial_attempts,
                            retry_sleep=0.05)
        try:
            k, m, arrs = transport.request(
                sock, "join",
                {"slot": self.ident["slot"], "inc": self.ident["inc"],
                 "resume": True, "rejoin": True,
                 "resume_only": resume_only},
                deadline=self.rpc_deadline)
        except transport.TransportError:
            try:
                sock.close()
            except OSError:
                pass
            raise
        if k != "welcome":
            try:
                sock.close()
            except OSError:
                pass
            raise transport.TransportClosed(
                f"resume-join rejected: {m.get('error', k)}")
        self.sock = sock
        self.stats["reconnects"] += 1
        tevents.counter("cluster.reconnects")
        tevents.emit("cluster_worker_reconnect",
                     slot=self.ident["slot"],
                     resumed=bool(m.get("resume")))
        if m.get("resume"):
            return
        # fencing moved on: fresh incarnation, fresh admission — the
        # old incarnation's unpushed work is dropped, like a dead
        # worker's would be
        self.ident["inc"] = int(m["incarnation"])
        self.stats["readmissions"] += 1
        tevents.counter("cluster.readmissions")
        self._pending_reset = (dict(m), dict(arrs))

    def request(self, kind, meta, arrays=None, *, deadline=None,
                retries=RECONNECT_RETRIES):
        """One crash-tolerant round trip; may return the synthetic
        ``reset`` reply instead of the requested one. ``retries``
        trims the whole budget for best-effort frames — the re-dial
        inside the retry shrinks with it, so a bye against a
        coordinator that already exited fails in seconds, not
        minutes — and a trimmed-budget frame is also RESUME-ONLY (a
        farewell must never be answered with a fresh admission)."""
        deadline = deadline if deadline is not None \
            else self.rpc_deadline
        best_effort = retries < RECONNECT_RETRIES

        def attempt():
            if self.closed:
                raise LinkClosed("link closed — no further round "
                                 "trips (worker shutting down)")
            if self.sock is None:
                self._resume(
                    dial_attempts=20 if best_effort else 200,
                    resume_only=best_effort)
                if self._pending_reset is not None:
                    m, arrs = self._pending_reset
                    self._pending_reset = None
                    return ("reset", m, arrs)
            try:
                return transport.request(self.sock, kind, meta,
                                         arrays, deadline=deadline)
            except (transport.TransportClosed,
                    transport.TransportTimeout):
                self.drop()
                raise

        return supervised(
            attempt, phase="cluster_rpc",
            retries=retries,
            backoff=RECONNECT_BACKOFF_SECONDS,
            backoff_cap=RECONNECT_BACKOFF_CAP_SECONDS,
            jitter=RECONNECT_JITTER,
            retry_on=(transport.TransportClosed,
                      transport.TransportTimeout),
            event="cluster_reconnect",
            failure_counter="cluster.rpc_failures",
            log=self.log)


class _HbLink:
    """The heartbeat connection with transient-failure survival: a
    failed beat drops + re-dials the socket with a short in-beat
    retry and bumps ``cluster.heartbeat_retries`` — the beat thread
    itself never dies of an I/O error (the main loop may be healthy
    and compute-bound; a silently dead beat loop would get it
    declared dead by the coordinator's heartbeat scan)."""

    RETRIES = 2

    def __init__(self, host, port, connect, ident, deadline, stats):
        self.host, self.port = host, port
        self.connect = connect
        self.ident = ident
        self.deadline = deadline
        self.stats = stats
        self.sock = None
        self.lock = threading.Lock()

    def _drop(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def beat(self) -> None:
        with self.lock:
            for attempt in range(self.RETRIES + 1):
                try:
                    if self.sock is None:
                        # short-fused dial: a beat must not wedge the
                        # beat thread for the full connect budget —
                        # the NEXT interval retries anyway
                        self.sock = self.connect(
                            self.host, self.port, attempts=2,
                            retry_sleep=0.05)
                    # tda: ignore[TDA112] -- the beat is a pure
                    # liveness signal on its own link; the reply is
                    # drained only to keep the socket frame-aligned,
                    # and a stale-slot error must not kill the beat
                    # thread — the MAIN link surfaces fencing on the
                    # next rpc
                    transport.send_frame(self.sock, "beat",
                                         dict(self.ident),
                                         deadline=self.deadline)
                    transport.recv_frame(self.sock,
                                         deadline=self.deadline)
                    return
                except (transport.TransportError, OSError):
                    self._drop()
                    self.stats["heartbeat_retries"] += 1
                    tevents.counter("cluster.heartbeat_retries")
                    if attempt < self.RETRIES:
                        time.sleep(0.05 * (attempt + 1))
            # still down after the in-beat retries: stay alive — the
            # next interval's beat re-dials again

    def close(self):
        with self.lock:
            self._drop()


class _DonePush:
    """An already-completed push round trip wearing the
    :class:`_PendingPush` interface, so the synchronous (``@seq`` /
    dense) path folds its ack through the SAME ``harvest`` code as
    the overlapped one — one implementation of the deferred-ack
    contract, no drift between the two spellings."""

    def __init__(self, window: int, base: int, result, rtt_ms: float):
        self.window = window
        self.base = base
        self.rtt_ms = rtt_ms
        self._result = result

    def wait(self):
        return self._result


class _PendingPush:
    """One in-flight push: the full crash-tolerant round trip (send →
    deferred commit → pull reply) runs on a background thread over a
    DEDICATED link, so the next window's ticks start immediately —
    the push/pull overlap. ``rtt_ms`` is measured inside the thread
    (send to reply), so the reported push→commit→pull latency never
    absorbs the overlapped compute. At most one is in flight: the
    next boundary harvests it before sending again, which keeps the
    SSP gate's bound the only staleness authority."""

    def __init__(self, link: _Link, window: int, base: int,
                 meta: dict, arrays: dict, deadline: float):
        self.window = window
        self.base = base
        self.rtt_ms = 0.0
        self._lock = threading.Lock()
        self._result = None
        self._error: BaseException | None = None

        def _send():
            t0 = time.monotonic()
            try:
                # tda: ignore[TDA112] -- the async push's reply is
                # consumed by harvest(), which raises on an error
                # reply; this sender closure only parks it
                reply = link.request("push", meta, arrays,
                                     deadline=deadline)
                with self._lock:
                    self._result = reply
            except BaseException as e:  # noqa: BLE001 — re-raised in wait()
                with self._lock:
                    self._error = e
            finally:
                with self._lock:
                    self.rtt_ms = (time.monotonic() - t0) * 1e3

        self._t = threading.Thread(
            target=_send, name="tda-cluster-push", daemon=True)
        self._t.start()

    def wait(self):
        self._t.join()
        with self._lock:
            if self._error is not None:
                raise self._error
            return self._result


class WorkerKilled(Exception):
    """Thread-mode stand-in for SIGKILL (the real worker never raises
    this — it is gone)."""


def compile_worker_schedule(n_windows: int, n_slots: int, *,
                            plan=None) -> np.ndarray:
    """The (n_windows, n_slots) int32 cluster fault schedule from the
    plan's ``cluster:worker`` rules: cell > 0 = straggle units, cell
    == -1 = kill. One probe per cell in row-major order against a
    FRESH quiet registry (a pure function of the plan — every process
    compiles the identical schedule); fires mirror into the live
    ledger exactly once, like the SSP compilers."""
    live = fregistry.active()
    if plan is None:
        plan = live.plan if live is not None else None
    out = np.zeros((n_windows, n_slots), np.int32)
    if plan is None or not any(
            r.point == "cluster:worker" for r in plan.rules):
        return out
    reg = fregistry.FaultRegistry(plan, quiet=True)
    for w in range(n_windows):
        for k in range(n_slots):
            hit = reg.probe("cluster:worker")
            if hit is None:
                continue
            kind, arg = hit
            if kind == "kill":
                out[w, k] = KILL
            else:
                out[w, k] = int(arg if arg is not None
                                else fregistry.DEFAULT_STRAGGLE_UNITS)
    if live is not None and live.plan == plan:
        live.record(reg.fired)
    return out


def strip_kills(plan_spec: str | None,
                points: tuple[str, ...] = ("cluster:worker",)
                ) -> str | None:
    """The plan with its KILL rules at ``points`` removed — what a
    respawned incarnation runs under (the fault was transient: a
    restarted executor — or a recovered coordinator, with
    ``points=('cluster:coordinator',)`` — re-dying on the same
    deterministic cell would loop forever, in both the elastic and
    the restart-baseline arms)."""
    if not plan_spec:
        return plan_spec
    plan = fregistry.FaultPlan.parse(plan_spec)
    rules = tuple(r for r in plan.rules
                  if not (r.point in points and r.kind == "kill"))
    return fregistry.FaultPlan(seed=plan.seed, rules=rules).spec()


def _slot_rows(task: dict, slot: int, n_slots: int):
    """This slot's contiguous row block of the shared synthetic task
    (the whole-task generation is deterministic in the data seed, so
    every incarnation of a slot sees identical rows)."""
    from tpu_distalg.utils import datasets

    n_rows = int(task["n_rows"])
    X, y = datasets.synthetic_two_class(
        n_rows + int(task["test_rows"]), int(task["n_features"]),
        seed=int(task["data_seed"]))
    X = datasets.add_bias_column(X)
    per = -(-n_rows // n_slots)
    lo = min(slot * per, n_rows)
    hi = min(lo + per, n_rows)
    if hi <= lo:
        raise ValueError(
            f"slot {slot} owns no rows: {n_rows} rows over "
            f"{n_slots} slots")
    return (np.ascontiguousarray(X[lo:hi]),
            np.ascontiguousarray(y[lo:hi]))


class LocalTrainer:
    """One slot's compiled window loops over the EXISTING trainers, on
    the worker's own local mesh. ``run(w, window, n_ticks)`` executes
    ``n_ticks`` local ticks starting at the window's absolute first
    tick and returns the new local weights (host ndarray)."""

    def __init__(self, task: dict, slot: int, n_slots: int, s: int):
        import jax
        import jax.numpy as jnp

        from tpu_distalg.parallel import get_mesh

        self.s = s
        self.slot = slot
        self.algo = task.get("algo", "ssgd")
        X, y = _slot_rows(task, slot, n_slots)
        self.X = jnp.asarray(X)
        self.y = jnp.asarray(y)
        self.valid = jnp.ones((X.shape[0],), jnp.float32)
        d = X.shape[1]
        self.dummy_te = (jnp.zeros((1, d), jnp.float32),
                         jnp.zeros((1,), jnp.float32))
        self.mesh = get_mesh(data=1, devices=jax.devices()[:1])
        seed = int(task["seed"]) + SLOT_SEED_STRIDE * slot
        self._fns: dict[int, object] = {}
        if self.algo == "local_sgd":
            from tpu_distalg.models import local_sgd as lsgd

            def make(n_ticks):
                cfg = lsgd.LocalSGDConfig(
                    n_iterations=1, n_local_iterations=n_ticks,
                    eta=float(task["eta"]),
                    mini_batch_fraction=float(
                        task["mini_batch_fraction"]),
                    seed=seed, eval_test=False)
                return lsgd.make_train_fn(self.mesh, cfg,
                                          X.shape[0])
        elif self.algo == "ssgd":
            from tpu_distalg.models import ssgd

            def make(n_ticks):
                cfg = ssgd.SSGDConfig(
                    n_iterations=n_ticks, eta=float(task["eta"]),
                    mini_batch_fraction=float(
                        task["mini_batch_fraction"]),
                    lam=float(task["lam"]),
                    reg_type=task.get("reg_type", "l2"),
                    seed=seed, eval_test=False)
                return ssgd.make_train_fn(self.mesh, cfg, X.shape[0])
        else:
            raise ValueError(
                f"unknown cluster algo {self.algo!r}: 'ssgd' or "
                f"'local_sgd'")
        self._make = make

    def run(self, w: np.ndarray, window: int, n_ticks: int
            ) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        if n_ticks not in self._fns:
            self._fns[n_ticks] = self._make(n_ticks)
        fn = self._fns[n_ticks]
        w_j = jnp.asarray(w, jnp.float32)
        if self.algo == "local_sgd":
            # one MA round of n_ticks local steps; t0 = the absolute
            # ROUND id (the round scan's sampling key unit)
            w_out, _ws, _delta, _accs = fn(
                self.X, self.y, self.valid, *self.dummy_te,
                w_j, w_j[None, :],
                jnp.zeros_like(w_j), t0=window)
        else:
            # absolute tick ids thread the PRNG, so a window replay
            # (or a respawned incarnation) samples identically
            w_out, _accs = fn(self.X, self.y, self.valid,
                              *self.dummy_te, w_j,
                              t0=window * self.s)
        return np.asarray(jax.block_until_ready(w_out), np.float32)

    def straggle(self, units: int) -> None:
        """Pay real interference compute (the compiled-in straggler of
        ``parallel/ssp.py``, here an honest host-device burn)."""
        import jax

        jax.block_until_ready(
            _straggle_fn()(np.int32(units * 50)))


_STRAGGLE_CACHE: dict = {}


def _straggle_fn():
    import jax

    fn = _STRAGGLE_CACHE.get("fn")
    if fn is None:
        fn = _STRAGGLE_CACHE["fn"] = jax.jit(
            lambda u: pssp.straggle_work(u, 1.0))
    return fn


def _default_die():
    os.kill(os.getpid(), signal.SIGKILL)


def run_worker(host: str, port: int, *, slot: int | None = None,
               rejoin: bool = False, admit_at: int | None = None,
               die=None, connect=None, logger=None) -> dict:
    """The worker main loop: join → (gate → train window → push/skip)*
    → bye. Returns its stats dict (the real process also reports them
    in the ``bye`` frame and via its telemetry dir). ``die`` overrides
    the kill-cell action for thread-mode tests (default: a real
    ``SIGKILL`` on this process); ``connect`` overrides the dialer
    (thread mode tracks its sockets through it). ``admit_at`` pins a
    rejoiner's first window (the launcher's plan-determined admission
    — the coordinator holds that window's commit for it)."""
    log = logger or (lambda m: None)
    die = die or _default_die
    connect = connect or transport.connect
    sock = None
    last_err: Exception | None = None
    for attempt in range(80):
        try:
            if sock is None:
                sock = connect(host, port)
            # tda: ignore[TDA112] -- the join loop breaks only on
            # welcome; every non-welcome fall-through below retries
            # or raises "join rejected" with the error payload — the
            # error reply IS the handled rejection path
            kind, meta, center = transport.request(
                sock, "join",
                {"slot": slot, "rejoin": rejoin,
                 "admit_at": admit_at})
        except transport.TransportError as e:
            # a torn dial/handshake (an rpc-storm fault, or the
            # coordinator mid-recovery): re-dial, like every later
            # round trip does through the link
            last_err = e
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
                sock = None
            time.sleep(0.25)
            continue
        if kind == "welcome":
            break
        if "slots active" in str(meta.get("error", "")) \
                and attempt < 79:
            # a replacement racing the coordinator's EOF processing of
            # its predecessor: the slot reads ACTIVE for a beat after
            # the old process died — retry briefly instead of wedging
            # the admission hold forever
            time.sleep(0.25)
            continue
        sock.close()
        raise RuntimeError(
            f"join rejected: {meta.get('error', kind)}")
    else:
        raise transport.TransportClosed(
            f"could not join the coordinator at {host}:{port} after "
            f"80 attempts: {last_err}")
    slot = int(meta["slot"])
    inc = int(meta.get("incarnation", 0))
    # the fencing token: every frame this incarnation sends carries it,
    # so a replacement can never be confused with its zombie (the link
    # shares this dict — a fresh re-admission swaps the token in place)
    ident = {"slot": slot, "inc": inc}
    s = int(meta["s"])
    n_windows = int(meta["n_windows"])
    n_slots = int(meta["n_slots"])
    rpc_deadline = float(meta.get("rpc_deadline", 30.0))
    task = meta["train"]
    plan = meta.get("plan")
    schedule = compile_worker_schedule(
        n_windows, n_slots,
        plan=fregistry.FaultPlan.parse(plan) if plan else None)
    trainer = LocalTrainer(task, slot, n_slots, s)
    tevents.emit("cluster_worker_start", slot=slot,
                 admit=meta["admit"], gen=meta["gen"])
    tevents.mark(f"cluster:worker{slot}", emit_event=False)

    stats = {"pushes": 0, "skips": 0, "gated_ms": 0.0,
             "push_pull_ms_total": 0.0, "push_pull_ms": [],
             "ages": [], "windows": 0, "undelivered_windows": 0,
             "reconnects": 0, "readmissions": 0,
             "heartbeat_retries": 0, "delta_pulls": 0,
             "dense_pulls": 0, "async_pushes": 0}
    link = _Link(host, port, sock, connect, ident, rpc_deadline,
                 stats, log)

    # the cluster wire schedule (the coordinator's welcome carries the
    # one spelling every process runs under): dense keeps the verbatim
    # f32 snapshot protocol; int8/topk compress the push delta (EF
    # residual carried HERE, in the loop state) and receive
    # version-delta pulls against the cached center view. @seq forces
    # the synchronous push; otherwise compressed pushes overlap the
    # next window's compute on a background sender
    comm_spec = pcomms.CommSpec.parse(meta.get("comm") or "dense")
    codec = pcomms.make_host_codec(comm_spec)
    pull_codec = pcomms.make_host_pull_codec(comm_spec)
    # rowstore PS mode (the welcome carries it, like the comm spec):
    # every push names the rows it moves via a ``w.rows`` index array
    # so the PS merges row-wise. The SGD window HONESTLY touches every
    # row of the dense LR weight vector, so the index is the full
    # range — which is exactly what pins rowstore-mode SSP bitwise to
    # the replicated path; genuine sparsity belongs to the row-store's
    # graph/ALS workloads (``rowstore.run_cluster_pagerank``,
    # ``models/als.fit_rowstore``)
    ps_mode = meta.get("ps_mode") or "replicated"
    # the welcome also names the tuned geometry this run was resolved
    # under — the pull-refresh cadence (the coordinator enforces it;
    # recorded here so worker stats say what wire they measured) and
    # the rig-profile id (or None for untuned table defaults)
    stats["pull_refresh"] = int(meta.get("pull_refresh")
                                or tune_defaults.PULL_REFRESH_WINDOWS)
    if meta.get("tune_profile"):
        stats["tune_profile"] = str(meta["tune_profile"])
    overlap_push = codec is not None and comm_spec.overlap
    push_link = (_Link(host, port, None, connect, ident, rpc_deadline,
                       stats, log) if overlap_push else None)

    # liveness: the shared Heartbeat thread, its emit_fn ALSO framing a
    # beat to the coordinator over its own crash-tolerant link —
    # compute-bound windows stay visibly alive, a partition goes
    # visibly silent, and one broken beat never ends the loop
    hb_link = _HbLink(host, port, connect, ident, rpc_deadline, stats)

    def hb_emit(ev, **fields):
        tevents.emit(ev, **fields)
        if ev == "heartbeat":
            hb_link.beat()

    hb = theartbeat.Heartbeat(
        interval=float(meta.get("heartbeat_interval", 0.5)),
        stall_after=None, emit_fn=hb_emit)
    hb.start()

    pending_windows = 0   # trained-but-not-yet-pushed (busy skips)
    version = int(meta["version"])
    w_base = np.asarray(center["w"], np.float32)   # cached center view
    w_local = w_base.copy()
    cut = w_local            # progress in (cut -> w_local) is unpushed
    base = version           # version underlying w_local's training
    have = version           # version of the cached center view
    residual = (pcomms.zero_residuals({"w": w_base})
                if codec is not None else None)
    window = int(meta["admit"])
    done = bool(meta.get("done"))
    restart = False
    killed = False
    pending: _PendingPush | None = None   # the one in-flight push

    def adopt_reset(m, arrays):
        """A fresh re-admission (the old incarnation was declared
        dead during a coordinator outage): adopt the welcome like a
        brand-new join — new admission window, the current center,
        zero pending work, a zero EF residual."""
        nonlocal version, done, restart, window, w_base, w_local, \
            base, have, cut, residual, pending_windows, pending, \
            push_link
        # an in-flight push predates the reset: its reply (if any) is
        # for a dead incarnation — abandoned, never harvested. Its
        # sender thread may still hold the push link mid-retry, so
        # the link is CLOSED (the thread exits on LinkClosed instead
        # of re-dialing) and a fresh one minted: the re-admitted
        # incarnation's next push must never interleave frames with
        # the zombie on one socket
        pending = None
        if push_link is not None:
            push_link.close()
            push_link = _Link(push_link.host, push_link.port, None,
                              push_link.connect, ident, rpc_deadline,
                              stats, log)
        version = int(m["version"])
        done = bool(m.get("done"))
        restart = bool(m.get("restart"))
        window = int(m["admit"])
        w_base = np.asarray(arrays["w"], np.float32)
        w_local = w_base.copy()
        cut = w_local
        base = version
        have = version
        if codec is not None:
            residual = pcomms.zero_residuals({"w": w_base})
        pending_windows = 0

    def adopt_pull(m, arrays):
        """Fold one pull payload into the cached center view: a
        ``delta`` reply applies the compressed ``center@cv −
        center@have`` diff to the view (the worker-side half of the
        version-delta protocol — both ends decode the same bytes), a
        ``dense`` reply (resume/rejoin fallback, and the whole dense
        schedule) replaces it. ``base`` pins to the reply's center
        version — under a codec that is the push's own commit
        (``cv``), a pure function of the plan, never the live clock a
        concurrently-committing peer may already have advanced."""
        nonlocal w_base, have, base
        mode = m.get("mode")
        if mode == "delta":
            delta = pcomms.decode_tree(pull_codec, arrays,
                                       {"w": w_base})["w"]
            w_base = w_base + delta
            have = int(m["cv"])
            stats["delta_pulls"] += 1
        elif mode == "dense":
            w_base = np.asarray(arrays["w"], np.float32)
            have = int(m["cv"])
            stats["dense_pulls"] += 1
        else:   # legacy dense reply (no codec): live center + version
            w_base = np.asarray(arrays["w"], np.float32)
            have = int(m.get("version", have))
        base = have

    def harvest(p: _PendingPush, transplant):
        """Fold an in-flight push's deferred ack into the loop state:
        record the round trip, refresh the cached view, and REBASE
        the local weights onto the fresher center — transplanting
        ``transplant`` (the progress trained while the push was in
        flight; ``None`` = the synchronous path, nothing trained
        since). Returns ``False`` on a reset (the caller restarts its
        iteration)."""
        nonlocal version, done, restart, w_local
        k, m, arrs = p.wait()
        if k == "reset":
            adopt_reset(m, arrs)
            return False
        version = int(m.get("version", version))
        done = bool(m.get("done", done))
        restart = bool(m.get("restart", restart))
        if k == "error":
            raise transport.TransportClosed(
                f"push rejected: {m.get('error')}")
        stats["pushes"] += 1
        stats["push_pull_ms"].append(round(p.rtt_ms, 3))
        stats["push_pull_ms_total"] += p.rtt_ms
        stats["ages"].append(max(0, p.window - p.base))
        tevents.counter("cluster.pushes")
        adopt_pull(m, arrs)
        w_local = (w_base + transplant if transplant is not None
                   else w_base.copy())
        return True

    def rpc(kind, meta_, arrays=None, deadline=None):
        """One crash-tolerant round trip; folds a ``reset`` into the
        loop state and reports it so call sites can restart their
        iteration."""
        nonlocal version, done, restart
        k, m, arrs = link.request(kind, meta_, arrays,
                                  deadline=deadline)
        if k == "reset":
            adopt_reset(m, arrs)
            return k, m, arrs
        if k == "error":
            # a fenced-out slot's poll/skip gets ("error", "stale
            # slot") back — adopting it as data keeps a zombie
            # training silently; surface it like any other link
            # failure so the supervised path rejoins
            raise transport.TransportClosed(
                f"{kind} rejected: {m.get('error', 'unknown')}")
        version = int(m.get("version", version))
        done = bool(m.get("done", done))
        restart = bool(m.get("restart", restart))
        return k, m, arrs

    try:
        if window > version:
            # pinned late admission: wait for the clock to reach the
            # admission window, then re-pull — the first delivery's
            # base (and so its age/weight) is plan-determined, not
            # join-timing-determined
            t_gate = time.monotonic()
            while version < window and not done and not restart:
                if time.monotonic() - t_gate > GATE_DEADLINE_SECONDS:
                    raise transport.TransportTimeout(
                        f"admission starved: version {version} never "
                        f"reached admit window {window}")
                time.sleep(GATE_POLL_SECONDS)
                rpc("poll", dict(ident))
            if not done and not restart:
                k, m, arrays = rpc("pull", dict(ident))
                if k != "reset":
                    adopt_pull(m, arrays)
                    w_local = w_base.copy()
                    cut = w_local
        while window < n_windows and not done and not restart:
            # the SSP gate: never more than s windows past the clock —
            # UNCHANGED under the push/pull overlap (an async push for
            # window w−1 still counts against the same bound: the
            # version only advances when that window commits)
            t_gate = time.monotonic()
            while window - version > s:
                if time.monotonic() - t_gate > GATE_DEADLINE_SECONDS:
                    raise transport.TransportTimeout(
                        f"gate starved: window {window} vs version "
                        f"{version} for {GATE_DEADLINE_SECONDS}s")
                time.sleep(GATE_POLL_SECONDS)
                k, _, _ = rpc("poll", dict(ident))
                if k == "reset" or done or restart:
                    break
            if done or restart:
                break
            if time.monotonic() - t_gate > 2 * GATE_POLL_SECONDS:
                stats["gated_ms"] += (time.monotonic() - t_gate) * 1e3
            cell = int(schedule[window, slot]) \
                if window < schedule.shape[0] else 0
            tevents.mark(f"cluster:worker{slot}@w{window}",
                         emit_event=False)
            if cell == KILL:
                # kill -9 MID-WINDOW: half the ticks land, the push
                # never happens, the sockets slam shut (EOF is the
                # coordinator's fastest death signal). A pusher link
                # closes FIRST: its background retry loop must not
                # resume-join and resurrect the dead incarnation in
                # thread mode
                w_local = trainer.run(w_local, window,
                                      max(1, s // 2))
                tevents.emit("cluster_worker_kill", slot=slot,
                             window=window)
                killed = True
                if push_link is not None:
                    push_link.close()
                die()
                return stats          # thread-mode die() returns
            busy = cell > 0
            if busy:
                # pre-announced skip: peers' commit of THIS window
                # must not wait out the interference
                k, _, _ = rpc("skip", dict(ident, window=window))
                if k == "reset":
                    continue
                stats["skips"] += 1
                tevents.counter("cluster.skips")
            w_local = trainer.run(w_local, window, s)
            stats["windows"] += 1
            if busy:
                trainer.straggle(cell)
                pending_windows += 1
                window += 1
                continue
            # -- push boundary -----------------------------------
            # cut the un-pushed progress (this window's training,
            # plus any busy windows' riding along), harvest the
            # previous in-flight ack — the overlap: that ack's
            # commit ran UNDER this window's compute — rebase onto
            # the fresher center, then send
            progress = w_local - cut
            push_base = base       # version this progress trained on
            if pending is not None:
                p, pending = pending, None
                if not harvest(p, progress):
                    continue       # reset adopted: restart the loop
            if codec is None:
                arrays_out = {"w": progress}
                push_meta = dict(ident, window=window,
                                 base=push_base)
            else:
                # EF: compress (progress + residual), carry the rest
                arrays_out, residual = pcomms.encode_tree(
                    codec, {"w": progress}, residual,
                    pcomms.PUSH_SEED_TAG, slot, window)
                push_meta = dict(ident, window=window,
                                 base=push_base, have=have)
            if ps_mode == "rowstore":
                # the row index rides OUTSIDE the codec (exact int64
                # structure; the coordinator detaches it before the
                # value decode) and INSIDE the push digest — replay
                # and re-push dedup cover it like any other byte
                arrays_out["w.rows"] = np.arange(
                    progress.shape[0], dtype=np.int64)
                tevents.counter("rowstore.rows_pushed",
                                int(progress.shape[0]))
            # the ack is DEFERRED until this window commits — which
            # can legitimately wait out an admission hold (a respawned
            # PROCESS worker pays spawn + jax import + first compile),
            # so the recv deadline is the gate's, not the rpc's
            push_deadline = max(rpc_deadline, GATE_DEADLINE_SECONDS)
            if overlap_push:
                pending = _PendingPush(push_link, window, push_base,
                                       push_meta, arrays_out,
                                       push_deadline)
                stats["async_pushes"] += 1
                tevents.counter("cluster.async_pushes")
                cut = w_local
            else:
                t0 = time.monotonic()
                reply = link.request("push", push_meta, arrays_out,
                                     deadline=push_deadline)
                p = _DonePush(window, push_base, reply,
                              (time.monotonic() - t0) * 1e3)
                if not harvest(p, None):
                    continue
                cut = w_local
            pending_windows = 0
            window += 1
    finally:
        if pending is not None:
            # drain the final in-flight ack (its commit is the run's
            # last window; losing it would drop the round trip from
            # the stats and leave the handler blocked on our socket)
            try:
                harvest(pending, None)
            except (transport.TransportError, LinkClosed):
                pass
            pending = None
        hb.stop()
        hb_link.close()
        if push_link is not None:
            push_link.close()
        if not killed:
            if pending_windows:
                # a straggle cell on the FINAL window(s) leaves
                # trained work with no later boundary to ride — the
                # in-process SSP drops a boundary-busy final window's
                # pending delta the same way (the scan ends); record
                # the loss instead of letting it pass silently
                stats["undelivered_windows"] = pending_windows
                tevents.counter("cluster.undelivered_windows",
                                pending_windows)
                tevents.emit("cluster_undelivered", slot=slot,
                             windows=pending_windows)
            ages = stats.pop("ages", [])
            stats["mean_age"] = (round(float(np.mean(ages)), 4)
                                 if ages else 0.0)
            stats["max_age"] = int(max(ages)) if ages else 0
            rtts = stats.pop("push_pull_ms", [])
            stats["push_pull_ms_p50"] = (
                round(float(np.percentile(rtts, 50)), 3)
                if rtts else 0.0)
            try:
                # tda: ignore[TDA112] -- fire-and-forget farewell:
                # an error from a dying coordinator changes nothing
                # about a worker that is already leaving
                link.request("bye", dict(ident, stats=stats),
                             retries=1)
            except transport.TransportError:
                pass
            pssp.emit_ssp_counters(
                pssp.SyncSpec(mode="ssp", staleness=s),
                {"merges": stats["pushes"],
                 "max_staleness": stats["max_age"],
                 "mean_staleness": stats["mean_age"]},
                straggle_ticks=stats["skips"] * s)
            tevents.counter("cluster.gated_ms",
                            int(stats["gated_ms"]))
            tevents.emit("cluster_worker_done", slot=slot, **{
                k: v for k, v in stats.items()
                if not isinstance(v, list)})
            log(f"[cluster] worker {slot} done: {stats['pushes']} "
                f"push(es), {stats['skips']} skip(s)")
            link.drop()
    stats["restart"] = restart
    return stats
