"""Router — the serving plane's front end.

One router process fronts N :class:`~tpu_distalg.cluster.serve.Replica`
processes. Per replica it keeps TWO framed-TCP connections — a score
socket owned by that replica's ``serve/batcher.MicroBatcher`` dispatch
thread (requests micro-batch per replica, exactly the in-process
serving shape, lifted onto the wire) and a control socket shared by the
heartbeat prober and the hot-swap publisher under a per-link lock — and
dispatches with a pluggable policy:

* **least-loaded** — fewest in-flight requests wins; ties break by a
  seeded RNG so a replayed request sequence routes identically.
* **consistent-hash** — an sha256 vnode ring over the ALIVE members;
  a death only remaps the dead replica's arcs, every other key keeps
  its home (the property the policy tests pin).

Sharded mode fans each request at every shard and merges the candidate
pairs with ``comms.merge_topk_pairs_host`` — the cross-process spelling
of the in-process ring-all-gather pair merge, same two-key sort order —
or reassembles dense score blocks (the A/B kept from PR 8). Both merges
are bitwise-identical to a single replica holding the whole catalogue.

Failure story, mirrored from the coordinator (PR 13):

* A replica death (kill -9, hang) surfaces as EOF on the score socket
  or a missed heartbeat; the router marks it dead, journals the
  membership change, and re-routes — in-flight requests retry on a
  surviving replica, a full fleet sheds honestly.
* The router itself journals admission/routing state in the PR 13
  write-ahead log: the base snapshot (port, membership, policy, seed),
  every published center (the hot-swap redo log), every death. A
  restarted router replays the WAL, rebinds the SAME port, reconnects
  the surviving fleet, and idempotently re-publishes the newest center.
"""

from __future__ import annotations

import bisect
import collections
import dataclasses
import hashlib
import random
import socket
import threading
import time

import numpy as np

from tpu_distalg.cluster import transport
from tpu_distalg.cluster import wal as cluster_wal
from tpu_distalg.parallel import comms as pcomms
from tpu_distalg.serve.batcher import (MicroBatcher, ServeClosedError,
                                       ServeOverloadError)
from tpu_distalg.telemetry import events as tevents

POLL_SECONDS = 0.05

#: same-port rebind discipline (the coordinator's recovery shape)
REBIND_ATTEMPTS = 100
REBIND_SLEEP = 0.05


class NoReplicaError(RuntimeError):
    """No alive replica can take this request (fleet dead, or a shard
    of a sharded fleet is gone — sharding has no redundancy)."""


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """The router's wiring (persisted in the WAL base snapshot)."""

    replicas: tuple = ()          # ((host, port), ...)
    mode: str = "routed"          # routed | sharded
    policy: str = "least_loaded"  # least_loaded | consistent_hash
    comm: str = "dense"           # hot-swap delta schedule
    port: int = 0                 # client port (0 = ephemeral)
    wal_dir: str | None = None    # durable routing state (recovery)
    max_batch: int = 16
    max_delay_ms: float = 2.0
    queue_depth: int = 128
    hb_interval: float = 0.2
    hb_timeout: float = 2.0
    rpc_deadline: float = 30.0
    history_depth: int = 8        # published centers kept for deltas
    seed: int = 0
    k_top: int = 10
    merge: str = "sparse"         # sharded ALS: sparse pairs | dense

    def __post_init__(self):
        if self.mode not in ("routed", "sharded"):
            raise ValueError(f"mode must be routed|sharded, "
                             f"got {self.mode!r}")
        if self.policy not in ("least_loaded", "consistent_hash"):
            raise ValueError(f"unknown dispatch policy {self.policy!r}")


# -------------------------------------------------------------- policies


class LeastLoadedPolicy:
    """Fewest in-flight requests wins; ties break via a seeded RNG so
    identical request/load sequences dispatch identically."""

    name = "least_loaded"

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)

    def pick(self, alive: list, loads: dict, key=None) -> int:
        lo = min(loads[r] for r in alive)
        ties = [r for r in alive if loads[r] == lo]
        if len(ties) == 1:
            return ties[0]
        return ties[self._rng.randrange(len(ties))]


class ConsistentHashPolicy:
    """sha256 vnode ring over the ALIVE membership: a death remaps only
    the dead replica's arcs. Keyless requests ride a deterministic
    sequence counter so they still spread (and replay identically)."""

    name = "consistent_hash"

    def __init__(self, seed: int = 0, vnodes: int = 64):
        self.vnodes = int(vnodes)
        self._hash_seed = int(seed)
        self._members: tuple = ()
        self._points: list = []
        self._owners: list = []
        self._seq = 0

    @staticmethod
    def _point(token: str) -> int:
        digest = hashlib.sha256(token.encode()).digest()
        return int.from_bytes(digest[:8], "big")

    def _rebuild(self, members: tuple) -> None:
        ring = sorted((self._point(f"r{rid}#{v}"), rid)
                      for rid in members for v in range(self.vnodes))
        self._points = [p for p, _ in ring]
        self._owners = [rid for _, rid in ring]
        self._members = members

    def pick(self, alive: list, loads: dict, key=None) -> int:
        members = tuple(sorted(alive))
        if members != self._members:
            self._rebuild(members)
        if key is None:
            key = f"seq:{self._hash_seed}:{self._seq}"
            self._seq += 1
        h = self._point(f"k:{key}")
        i = bisect.bisect_left(self._points, h)
        if i == len(self._points):
            i = 0  # wrap past the top of the ring
        return self._owners[i]


def make_policy(name: str, seed: int = 0):
    if name == "consistent_hash":
        return ConsistentHashPolicy(seed)
    return LeastLoadedPolicy(seed)


# --------------------------------------------------------------- history


class _CenterHistory:
    """Bounded ``{version: center}`` ring — the router-side mirror of
    ``ps.ParameterServer``'s delta history. Both endpoints present →
    an exact f32 leafwise delta; either fell out → dense fallback."""

    def __init__(self, depth: int = 8):
        self.depth = int(depth)
        self._h: dict = {}

    def record(self, version: int, center: dict) -> None:
        self._h[int(version)] = {k: np.asarray(v, np.float32).copy()
                                 for k, v in center.items()}
        while len(self._h) > self.depth:
            del self._h[min(self._h)]

    def delta_since(self, have, version) -> dict | None:
        if have is None:
            return None
        a = self._h.get(int(have))
        b = self._h.get(int(version))
        if a is None or b is None or a.keys() != b.keys():
            return None
        return {k: b[k] - a[k] for k in b}

    def newest(self):
        if not self._h:
            return None
        v = max(self._h)
        return v, self._h[v]


# ----------------------------------------------------------------- links


class _ReplicaLink:
    """The router's view of one replica: score socket + batcher (the
    per-replica micro-batch lane) and a lock-shared control socket
    (heartbeat + hot-swap)."""

    def __init__(self, rid: int, addr: tuple, cfg: RouterConfig,
                 *, count_merge_bytes: bool = False):
        self.rid = int(rid)
        self.addr = (addr[0], int(addr[1]))
        self.cfg = cfg
        self.count_merge_bytes = count_merge_bytes
        self.alive = False
        self.version: int | None = None
        self.last_beat = time.monotonic()
        self.meta: dict = {}
        self.pending = 0            # guarded by the router's lock
        self.ctrl_lock = threading.Lock()
        self._score_sock: socket.socket | None = None
        self._ctrl_sock: socket.socket | None = None
        self.batcher: MicroBatcher | None = None

    def _dial(self) -> socket.socket:
        """One fresh connection + hello handshake. Short retry budget:
        a dead replica must surface as a TransportError in well under
        a heartbeat period, not after transport.connect's default
        10-second patience."""
        sock = transport.connect(*self.addr,
                                 deadline=self.cfg.rpc_deadline,
                                 attempts=2, retry_sleep=0.05)
        kind, meta, _ = transport.request(
            sock, "hello", deadline=self.cfg.rpc_deadline)
        if kind != "welcome":
            raise transport.TransportError(
                f"replica {self.rid} answered hello with {kind!r}")
        self.meta = meta or {}
        return sock

    def connect(self) -> None:
        cfg = self.cfg
        self._score_sock = self._dial()
        self._ctrl_sock = transport.connect(
            *self.addr, deadline=cfg.rpc_deadline)
        self.version = int(self.meta.get("version", 0))
        self.alive = True
        self.batcher = MicroBatcher(
            f"replica{self.rid}", self._predict,
            max_batch=cfg.max_batch, max_delay_ms=cfg.max_delay_ms,
            queue_depth=cfg.queue_depth)

    def _redial_score(self) -> None:
        try:
            self._score_sock.close()
        except OSError:
            pass
        self._score_sock = self._dial()

    def redial_ctrl(self) -> None:
        """Replace the control connection (heartbeat/swap retry path —
        callers hold ``ctrl_lock``)."""
        try:
            self._ctrl_sock.close()
        except OSError:
            pass
        self._ctrl_sock = transport.connect(
            *self.addr, deadline=self.cfg.rpc_deadline,
            attempts=2, retry_sleep=0.05)

    def _predict(self, payloads: list) -> list:
        """One micro-batch -> one ``score`` round trip. Returns one
        ``(value, version)`` per payload; a transport failure redials
        ONCE (scoring is pure, so replaying the frame is safe — a
        transient wire fault must not read as a replica death) and
        only then raises, failing exactly this batch's replies (the
        router re-routes them)."""
        X = np.stack([np.asarray(p) for p in payloads])
        try:
            kind, meta, arrays = transport.request(
                self._score_sock, "score", {"n": len(payloads)},
                {"x": X}, deadline=self.cfg.rpc_deadline)
        except (transport.TransportError, OSError):
            self._redial_score()
            kind, meta, arrays = transport.request(
                self._score_sock, "score", {"n": len(payloads)},
                {"x": X}, deadline=self.cfg.rpc_deadline)
        if kind != "scored":
            raise transport.TransportError(
                f"replica {self.rid} answered score with {kind!r}")
        version = int(meta["version"])
        if self.count_merge_bytes:
            tevents.counter(
                "serve.cluster_merge_bytes_wire",
                int(sum(np.asarray(a).nbytes
                        for a in arrays.values())))
        if "y" in arrays:           # routed lr/kmeans: final values
            y = arrays["y"]
            return [(y[i], version) for i in range(len(payloads))]
        if "vals" in arrays:        # ALS sparse candidates
            vals, idx = arrays["vals"], arrays["idx"]
            return [((vals[i], idx[i]), version)
                    for i in range(len(payloads))]
        scores = arrays["scores"]   # ALS dense block
        off = int(self.meta.get("off", 0))
        return [((scores[i], off), version)
                for i in range(len(payloads))]

    def close(self) -> None:
        for sock in (self._score_sock, self._ctrl_sock):
            if sock is None:
                continue
            for fn in (lambda s=sock: s.shutdown(2),
                       lambda s=sock: s.close()):
                try:
                    fn()
                except OSError:
                    pass
        if self.batcher is not None:
            self.batcher.close(timeout=1.0)


# ---------------------------------------------------------------- router


class Router:
    """The serving plane's dispatcher + hot-swap publisher + WAL'd
    control state. In-process callers use :meth:`request` /
    :meth:`publish`; remote clients speak ``route`` frames on
    :attr:`port` (see :class:`RouterClient`)."""

    def __init__(self, config: RouterConfig, *, logger=None):
        self.cfg = config
        self.log = logger or (lambda *_: None)
        self.port = int(config.port)
        self.version = 0
        self._links: dict[int, _ReplicaLink] = {}
        self._dead: set[int] = set()
        self._lock = threading.Lock()
        self._pub_lock = threading.Lock()
        self._wal_lock = threading.Lock()
        self._stop = threading.Event()
        self._listener: socket.socket | None = None
        self._conns: set = set()
        self._threads: list = []
        self._wal: cluster_wal.WriteAheadLog | None = None
        self._pull_codec = pcomms.make_host_pull_codec(config.comm)
        self._history = _CenterHistory(config.history_depth)
        self._policy = make_policy(config.policy, config.seed)
        self._latencies = collections.deque(maxlen=4096)
        self._n = {"replies": 0, "sheds": 0, "reroutes": 0,
                   "swaps": 0}
        self._t0 = time.monotonic()
        self.recovered = False

    # ---------------------------------------------------- lifecycle

    def start(self) -> "Router":
        replicas = [tuple(a) for a in self.cfg.replicas]
        if self.cfg.wal_dir:
            records, replay_base = cluster_wal.WriteAheadLog.replay(
                self.cfg.wal_dir, 1 << 60)
        else:
            records, replay_base = [], None
        if records:
            replicas = self._recover(records)
            self.recovered = True
        self._bind(retry=self.recovered)
        if self.cfg.wal_dir:
            self._wal = cluster_wal.WriteAheadLog(self.cfg.wal_dir)
            snapshot = {
                # tda: ignore[TDA100] -- the base snapshot is NOT a
                # full-config checkpoint: it persists only what a
                # recovering router cannot re-derive — the bound port
                # (same-port rebind contract) and the replica roster —
                # plus mode/policy/seed so operators can audit what
                # the dead process was running.  Batching knobs,
                # comms codec, k_top/merge and deadlines are process
                # CONFIG, re-supplied by the fresh RouterConfig at
                # recovery (see _recover: it reads only port/replicas
                # from base); carrying them would let a stale segment
                # silently override the operator's restart flags.
                "port": self.port, "mode": self.cfg.mode,
                "policy": self.cfg.policy,
                "seed": self.cfg.seed,
                "replicas": [list(a) for a in replicas]}
            self._wal.open_segment(replay_base or 0, snapshot)
        count_merge = self.cfg.mode == "sharded"
        for rid, addr in enumerate(replicas):
            link = _ReplicaLink(rid, addr, self.cfg,
                                count_merge_bytes=count_merge)
            self._links[rid] = link
            if rid in self._dead:
                continue
            try:
                link.connect()
            except (transport.TransportError, OSError) as e:
                self._mark_dead(rid, reason=f"connect: {e}")
        if self.recovered:
            self._republish_newest()
        for name, target in (("accept", self._accept_loop),
                             ("hb", self._hb_loop)):
            t = threading.Thread(target=target,
                                 name=f"tda-router-{name}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        tevents.emit("router_start", port=self.port,
                     mode=self.cfg.mode, policy=self.cfg.policy,
                     replicas=len(replicas),
                     recovered=self.recovered)
        return self

    def _bind(self, *, retry: bool) -> None:
        attempts = REBIND_ATTEMPTS if retry and self.port else 1
        last: OSError | None = None
        for _ in range(attempts):
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                sock.bind(("127.0.0.1", self.port))
            except OSError as e:
                sock.close()
                last = e
                time.sleep(REBIND_SLEEP)
                continue
            sock.listen(64)
            self._listener = sock
            self.port = sock.getsockname()[1]
            return
        raise OSError(
            f"router could not rebind port {self.port} "
            f"after {attempts} attempts: {last}")

    def _recover(self, records: list) -> list:
        """Roll the WAL forward: base snapshot -> port + membership,
        ``member_dead`` -> dead set, ``publish`` -> center history and
        current version (the hot-swap redo log)."""
        replicas = [tuple(a) for a in self.cfg.replicas]
        for kind, meta, arrays in records:
            if kind == "base":
                self.port = int(meta.get("port", self.port))
                if meta.get("replicas"):
                    replicas = [tuple(a) for a in meta["replicas"]]
            elif kind == "member_dead":
                self._dead.add(int(meta["replica"]))
            elif kind == "member_join":
                self._dead.discard(int(meta["replica"]))
            elif kind == "publish":
                v = int(meta["version"])
                self._history.record(v, arrays or {})
                self.version = max(self.version, v)
        tevents.emit("router_recover", port=self.port,
                     version=self.version, dead=sorted(self._dead))
        return replicas

    def _republish_newest(self) -> None:
        newest = self._history.newest()
        if newest is None:
            return
        version, center = newest
        for rid, link in self._links.items():
            if link.alive and (link.version or 0) < version:
                self._swap_link(link, center, version)

    def seed_history(self, version: int, center: dict) -> None:
        """Record the fleet's initial center so the FIRST publish can
        ride the compressed delta path (no WAL record: recovery's
        dense fallback covers a lost v0)."""
        self._history.record(version, center)
        self.version = max(self.version, int(version))

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass
        for link in self._links.values():
            link.close()
        if self._wal is not None:
            self._wal.close()

    def slam(self) -> None:
        """The router-crash drill: drop every socket with no goodbye
        (the WAL file is all that survives — recovery's input)."""
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for conn in list(self._conns):
            for fn in (lambda c=conn: c.shutdown(2),
                       lambda c=conn: c.close()):
                try:
                    fn()
                except OSError:
                    pass
        for link in self._links.values():
            link.close()
        if self._wal is not None:
            self._wal.close()

    # --------------------------------------------------- membership

    def _mark_dead(self, rid: int, *, reason: str = "") -> None:
        with self._lock:
            link = self._links.get(rid)
            if link is None or rid in self._dead:
                return
            link.alive = False
            self._dead.add(rid)
        tevents.emit("router_replica_dead", replica=rid,
                     reason=reason)
        self.log(f"router: replica {rid} dead ({reason})")
        if self._wal is not None:
            with self._wal_lock:
                try:
                    self._wal.append("member_dead", {"replica": rid})
                except (OSError, cluster_wal.WalError):
                    pass  # journalling a death must not kill routing
        link.close()

    def _alive(self) -> list:
        with self._lock:
            return [rid for rid, l in self._links.items() if l.alive]

    def _hb_loop(self) -> None:
        cfg = self.cfg
        while not self._stop.wait(cfg.hb_interval):
            for rid in self._alive():
                link = self._links[rid]
                try:
                    with link.ctrl_lock:
                        try:
                            kind, meta, _ = transport.request(
                                link._ctrl_sock, "hb",
                                deadline=cfg.hb_timeout)
                        except (transport.TransportError, OSError):
                            # one redial: a transient wire fault on
                            # the control connection is not a death
                            link.redial_ctrl()
                            kind, meta, _ = transport.request(
                                link._ctrl_sock, "hb",
                                deadline=cfg.hb_timeout)
                    if kind != "hb_ok":
                        raise transport.TransportError(
                            f"heartbeat answered {kind!r}")
                    with self._lock:
                        link.version = int(meta["version"])
                        link.last_beat = time.monotonic()
                except (transport.TransportError, OSError) as e:
                    self._mark_dead(rid, reason=f"heartbeat: {e}")
            # readmission sweep: a replica that a transient wire fault
            # condemned is still running — probe the dead set and
            # resurrect whoever answers (the serving-plane mirror of
            # the training cluster's worker-rejoin path; a genuinely
            # killed process refuses the dial and stays dead)
            with self._lock:
                dead = sorted(self._dead)
            for rid in dead:
                self._try_revive(rid)

    def _try_revive(self, rid: int) -> bool:
        old = self._links.get(rid)
        if old is None:
            return False
        fresh = _ReplicaLink(rid, old.addr, self.cfg,
                             count_merge_bytes=old.count_merge_bytes)
        try:
            fresh.connect()
        except (transport.TransportError, OSError):
            return False
        with self._lock:
            self._links[rid] = fresh
            self._dead.discard(rid)
        if self._wal is not None:
            with self._wal_lock:
                try:
                    self._wal.append("member_join", {"replica": rid})
                except (OSError, cluster_wal.WalError):
                    pass
        newest = self._history.newest()
        if newest is not None and (fresh.version or 0) < newest[0]:
            self._swap_link(fresh, newest[1], newest[0])
        tevents.emit("router_replica_revived", replica=rid)
        self.log(f"router: replica {rid} revived")
        return True

    # ----------------------------------------------------- requests

    def request(self, payload, *, key=None, timeout: float = 30.0):
        """Score one request. Returns ``(value, version, replica)`` —
        every reply stamped with the model version it was scored
        under (sharded: the min across shards). Raises
        :class:`ServeOverloadError` on a shed (client retries),
        :class:`NoReplicaError` when no replica can take it."""
        tevents.counter("serve.cluster_requests")
        t0 = time.perf_counter()
        deadline = t0 + timeout
        if self.cfg.mode == "sharded":
            out = self._request_sharded(payload, deadline)
        else:
            out = self._request_routed(payload, key, deadline)
        with self._lock:
            self._latencies.append(time.perf_counter() - t0)
            self._n["replies"] += 1
        tevents.counter("serve.cluster_replies")
        return out

    def _shed(self, err: BaseException):
        with self._lock:
            self._n["sheds"] += 1
        tevents.counter("serve.cluster_sheds")
        raise err

    def _request_routed(self, payload, key, deadline: float):
        attempts = 0
        max_attempts = len(self._links) + 2
        while True:
            with self._lock:
                alive = sorted(r for r, l in self._links.items()
                               if l.alive)
                loads = {r: self._links[r].pending for r in alive}
            if not alive:
                raise NoReplicaError(
                    "no alive replica — the whole fleet is dead")
            rid = self._policy.pick(alive, loads, key=key)
            link = self._links[rid]
            with self._lock:
                link.pending += 1
            try:
                reply = link.batcher.submit(payload)
                value, version = reply.result(
                    max(0.05, deadline - time.perf_counter()))
                return value, version, rid
            except ServeOverloadError as e:
                self._shed(e)
            except ServeClosedError as e:
                if link.alive:
                    self._shed(e)
            except (transport.TransportError, OSError):
                pass  # fall through to the re-route bookkeeping
            finally:
                with self._lock:
                    link.pending -= 1
            # the batch this request rode died with its replica (or
            # the link closed under us): mark, count, re-route
            self._mark_dead(rid, reason="score connection lost")
            with self._lock:
                self._n["reroutes"] += 1
            tevents.counter("serve.cluster_reroutes")
            attempts += 1
            if attempts >= max_attempts:
                raise NoReplicaError(
                    f"request re-routed {attempts}x without an "
                    f"alive replica accepting it")

    def _request_sharded(self, payload, deadline: float):
        alive = sorted(self._alive())
        n_shards = len(self.cfg.replicas)
        if len(alive) < n_shards:
            raise NoReplicaError(
                f"sharded fleet needs all {n_shards} shards alive, "
                f"have {sorted(alive)} — sharding has no redundancy")
        pending = []
        for rid in alive:
            link = self._links[rid]
            with self._lock:
                link.pending += 1
            pending.append((rid, link.batcher.submit(payload)))
        parts, versions = [], []
        error: BaseException | None = None
        for rid, reply in pending:
            link = self._links[rid]
            try:
                value, version = reply.result(
                    max(0.05, deadline - time.perf_counter()))
                parts.append((rid, value))
                versions.append(version)
            except ServeOverloadError as e:
                error = error or e
            except (ServeClosedError, transport.TransportError,
                    OSError) as e:
                self._mark_dead(rid, reason="score connection lost")
                error = error or NoReplicaError(
                    f"shard {rid} died mid-request: {e}")
            finally:
                with self._lock:
                    link.pending -= 1
        if error is not None:
            if isinstance(error, ServeOverloadError):
                self._shed(error)
            raise error
        value = self._merge(parts)
        return value, min(versions), -1

    def _merge(self, parts: list):
        """Cross-process candidate merge for ONE request — sparse
        pairs through ``merge_topk_pairs_host`` (identical order to
        the in-process ring merge) or dense block reassembly + the
        same two-key top-k. Run even for a single shard so routed and
        sharded replies share one code path (stable identity)."""
        k = self.cfg.k_top
        if self.cfg.merge == "sparse":
            all_v = np.stack([np.asarray(v, np.float32)[None, :]
                              for _, (v, _i) in parts])
            all_i = np.stack([np.asarray(i, np.int32)[None, :]
                              for _, (_v, i) in parts])
            vals, idx = pcomms.merge_topk_pairs_host(all_v, all_i,
                                                     k=k)
            return vals[0], idx[0]
        blocks = sorted(((off, np.asarray(s, np.float32))
                         for _, (s, off) in parts),
                        key=lambda t: t[0])
        full = np.concatenate([s for _, s in blocks])
        gidx = np.arange(full.shape[0], dtype=np.int32)
        order = np.lexsort((gidx, -full))[:k]
        return full[order], gidx[order]

    # ------------------------------------------------------ hot-swap

    def publish(self, center: dict, version: int) -> dict:
        """Land a new center in every live replica: journal it (the
        WAL write happens BEFORE any replica sees the version — the
        write-ahead contract), then per replica push a version-pinned
        compressed delta against its cached center, falling back to a
        dense snapshot when the replica's base is gone or stale."""
        version = int(version)
        center = {k: np.asarray(v, np.float32)
                  for k, v in center.items()}
        with self._pub_lock:
            self._history.record(version, center)
            if self._wal is not None:
                with self._wal_lock:
                    self._wal.append("publish", {"version": version},
                                     center)
            self.version = max(self.version, version)
            swapped, modes = [], {}
            for rid in sorted(self._alive()):
                mode = self._swap_link(self._links[rid], center,
                                       version)
                if mode:
                    swapped.append(rid)
                    modes[rid] = mode
        with self._lock:
            self._n["swaps"] += 1
        tevents.counter("serve.cluster_swaps")
        tevents.emit("router_publish", version=version,
                     swapped=swapped, modes=modes)
        return {"version": version, "swapped": swapped,
                "modes": modes}

    def _swap_link(self, link: _ReplicaLink, center: dict,
                   version: int) -> str | None:
        """Returns the landed mode (``delta``/``dense``) or None.
        Swaps are idempotent on the replica (a version it already
        holds acks ``swap_ok``), so a transient wire fault redials
        once and replays before the death verdict."""
        for attempt in (0, 1):
            try:
                return self._swap_link_once(link, center, version)
            except (transport.TransportError, OSError) as e:
                if attempt == 0:
                    try:
                        with link.ctrl_lock:
                            link.redial_ctrl()
                        continue
                    except (transport.TransportError, OSError):
                        pass
                self._mark_dead(link.rid, reason=f"swap: {e}")
                return None

    def _swap_link_once(self, link: _ReplicaLink, center: dict,
                        version: int) -> str | None:
        cfg = self.cfg
        with link.ctrl_lock:
            have = link.version
            delta = (self._history.delta_since(have, version)
                     if self._pull_codec is not None else None)
            if delta is not None:
                arrays, _ = pcomms.encode_tree(
                    self._pull_codec, delta, None,
                    pcomms.PULL_SEED_TAG, link.rid, int(have),
                    version)
                # tda: ignore[TDA112] -- the delta swap is
                # opportunistic: ANY non-swap_ok reply (swap_stale,
                # error) falls through to the dense swap below, which
                # checks its reply strictly
                kind, meta, _ = transport.request(
                    link._ctrl_sock, "swap",
                    {"mode": "delta", "cv": version,
                     "base": int(have)}, arrays,
                    deadline=cfg.rpc_deadline)
                if kind == "swap_ok":
                    link.version = int(meta["version"])
                    return "delta"
                # swap_stale: replica's base moved under us — fall
                # through to the dense snapshot
            # tda: ignore[TDA111] -- 'base' is read only on the DELTA
            # branch of the swap handler; the dense spelling ships
            # the full center and the handler never touches
            # meta["base"] for mode=dense
            kind, meta, _ = transport.request(
                link._ctrl_sock, "swap",
                {"mode": "dense", "cv": version}, center,
                deadline=cfg.rpc_deadline)
            if kind != "swap_ok":
                raise transport.TransportError(
                    f"swap answered {kind!r}")
            link.version = int(meta["version"])
            return "dense"

    # -------------------------------------------------- client wire

    def _accept_loop(self) -> None:
        self._listener.settimeout(POLL_SECONDS)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_client, args=(conn,),
                             name="tda-router-client",
                             daemon=True).start()

    def _serve_client(self, conn: socket.socket) -> None:
        self._conns.add(conn)
        try:
            while not self._stop.is_set():
                try:
                    kind, meta, arrays = transport.recv_frame(
                        conn, deadline=4 * self.cfg.rpc_deadline)
                except transport.TransportTimeout:
                    continue
                meta = meta or {}
                if kind == "stop":
                    transport.send_frame(conn, "bye", {},
                                         deadline=self.cfg.
                                         rpc_deadline)
                    break
                if kind != "route":
                    transport.send_frame(
                        conn, "error",
                        {"error": f"unknown frame kind {kind!r}"},
                        deadline=self.cfg.rpc_deadline)
                    continue
                reply = self._route_frame(meta, arrays or {})
                transport.send_frame(conn, *reply,
                                     deadline=self.cfg.rpc_deadline)
        except transport.TransportError:
            pass
        finally:
            self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _route_frame(self, meta: dict, arrays: dict) -> tuple:
        try:
            value, version, rid = self.request(
                arrays["x"], key=meta.get("key"),
                timeout=float(meta.get("timeout", 30.0)))
        except (ServeOverloadError, ServeClosedError):
            return ("reply", {"status": "shed"}, None)
        except Exception as e:  # noqa: BLE001 — the wire carries the
            #                      failure; the client decides
            return ("reply", {"status": "failed",
                              "error": str(e)}, None)
        if isinstance(value, tuple):
            out = {"vals": np.asarray(value[0], np.float32),
                   "idx": np.asarray(value[1], np.int32)}
        else:
            out = {"y": np.asarray(value)}
        return ("reply", {"status": "ok", "version": version,
                          "replica": rid}, out)

    # --------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            lat = sorted(self._latencies)
            n = dict(self._n)
            alive = sorted(r for r, l in self._links.items()
                           if l.alive)
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        qms = [round(x * 1e3, 3) for x in lat]

        def pct(p):
            if not qms:
                return 0.0
            return qms[min(len(qms) - 1, int(p * len(qms)))]

        return {"qps": round(n["replies"] / elapsed, 2),
                "p50_ms": pct(0.50), "p99_ms": pct(0.99),
                "replies": n["replies"], "sheds": n["sheds"],
                "reroutes": n["reroutes"], "swaps": n["swaps"],
                "alive": alive, "dead": sorted(self._dead),
                "version": self.version, "port": self.port}

    def emit_gauges(self) -> dict:
        """Publish the latency/throughput gauges (the bench + report
        surface: ``serve.cluster_qps`` / ``_p50_ms`` / ``_p99_ms``)."""
        s = self.stats()
        tevents.gauge("serve.cluster_qps", s["qps"])
        tevents.gauge("serve.cluster_p50_ms", s["p50_ms"])
        tevents.gauge("serve.cluster_p99_ms", s["p99_ms"])
        return s


# ---------------------------------------------------------------- client


class RouterClient:
    """A remote client of one router: ``route`` frames over a single
    framed-TCP connection (the CLI / cross-process surface; in-process
    callers use :meth:`Router.request` directly)."""

    def __init__(self, host: str, port: int, *,
                 deadline: float = 30.0):
        self._sock = transport.connect(host, port, deadline=deadline)
        self._deadline = deadline
        self._lock = threading.Lock()

    def request(self, payload, *, key=None, timeout: float = 30.0):
        meta = {"timeout": timeout}
        if key is not None:
            meta["key"] = key
        with self._lock:
            kind, rmeta, arrays = transport.request(
                self._sock, "route", meta,
                {"x": np.asarray(payload)},
                deadline=max(self._deadline, timeout + 5.0))
        rmeta = rmeta or {}
        if kind != "reply":
            raise transport.TransportError(
                f"router answered {kind!r}")
        status = rmeta.get("status")
        if status == "shed":
            raise ServeOverloadError("router shed the request")
        if status != "ok":
            raise RuntimeError(
                f"router request failed: {rmeta.get('error')}")
        if "y" in (arrays or {}):
            value = arrays["y"]
        else:
            value = (arrays["vals"], arrays["idx"])
        return value, int(rmeta["version"]), int(rmeta["replica"])

    def close(self) -> None:
        try:
            with self._lock:
                # tda: ignore[TDA112] -- best-effort farewell on
                # close: the client is gone either way; an error
                # reply must not turn close() into a raise
                transport.request(self._sock, "stop",
                                  deadline=self._deadline)
        except (transport.TransportError, OSError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass
