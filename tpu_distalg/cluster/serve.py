"""Serving replicas — the data plane of the distributed serving tier.

The serving plane fuses the two mature halves of the repo: ``serve/``
(PR 8: micro-batching, shed-don't-die, sharded top-k retrieval) ran in
ONE process, and ``cluster/`` (PRs 12-15: framed transport, WAL'd
control plane, compressed version-delta pulls) only trained. Here N
REPLICA processes each hold a servable model — or a model-axis SHARD
of one — behind the framed-numpy TCP transport, and a front-end router
(:mod:`tpu_distalg.cluster.router`) dispatches micro-batches at them.

Replica contract:

* **Batch-atomic scoring.** Every ``score`` frame is answered under
  the model lock and STAMPED with the model version it was scored
  under — a hot-swap can never land mid-batch, so a reply's stamp is
  exact, not approximate.
* **Live hot-swap.** The ``swap`` frame carries either a version-
  pinned compressed delta against the replica's cached center (the
  PR 15 pull codec: both ends derive it from the same ``--comm``
  spec) or a dense snapshot (the fallback when the replica's base
  doesn't match — it replies ``swap_stale`` and the router re-sends
  dense). Applying takes the same model lock scoring takes, so the
  swap is atomic at a batch boundary and ZERO requests are dropped.
* **Deterministic host scoring.** Replicas score with fixed-shape
  numpy kernels (:class:`HostModel`): every matmul block has the same
  operand shapes regardless of replica count or batch fill, so a
  sharded fleet's merged replies are BITWISE-identical to a single
  replica holding the whole catalogue — the property the chaos
  harness's undisturbed-vs-killed comparison rides.
* **Honest death.** The ``cluster:replica`` fault point fires at the
  score seam: ``kill`` SIGKILLs the process (thread mode slams every
  socket for the same router-side EOF observable), mid-burst, with
  requests in flight — the router detects via EOF/heartbeat and
  re-routes.

:class:`ServeFleet` is the local launcher (threads for tests/bench
fast paths, real subprocesses for the genuine kill -9), mirroring
``cluster/local.py``'s spawn discipline.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np

from tpu_distalg import faults
from tpu_distalg.cluster import transport
from tpu_distalg.faults import registry as fregistry
from tpu_distalg.parallel import comms as pcomms
from tpu_distalg.telemetry import events as tevents

#: accept-loop poll (the TDA090 settimeout-before-accept shape)
POLL_SECONDS = 0.05

#: fixed matmul tile width for host ALS scoring: shard boundaries
#: always align to this, so the per-block (rank,) x (rank, BLOCK)
#: products are the SAME BLAS calls under any shard count — the
#: bitwise sharded == single-replica contract is structural, not lucky
SCORE_BLOCK = 128


class ReplicaKilled(RuntimeError):
    """Thread-mode stand-in for the replica's SIGKILL: raised after the
    socket slam so the handler unwinds like a dead process's would."""


def center_of_state(root: str, state: list) -> tuple[str, dict]:
    """Map a checkpoint's ``(tag root, state leaves)`` to ``(kind,
    center)`` — the flat ``{name: ndarray}`` tree the hot-swap delta
    codec (``comms.encode_tree``/``decode_tree``) speaks, shared with
    the training cluster's center vocabulary."""
    if root in ("lr", "ssgd", "ma", "bmuf", "easgd", "local_sgd"):
        return "lr", {"w": np.asarray(state[0], np.float32)}
    if root.startswith("kmeans"):
        return "kmeans", {"centers": np.asarray(state[0], np.float32)}
    if root == "als":
        return "als", {"U": np.asarray(state[0], np.float32),
                       "V": np.asarray(state[1], np.float32)}
    raise ValueError(
        f"no serving-plane adapter for workload tag root {root!r} "
        f"(servable: lr-family, kmeans_*, als)")


def scoped_plan_spec(plan_spec: str | None,
                     points: tuple[str, ...] = ("cluster:replica",)
                     ) -> str | None:
    """The plan restricted to rules at ``points`` — what ONE targeted
    replica subprocess runs under. Handing the full plan to every
    replica would fire each per-process hit counter independently and
    kill the whole fleet at once; the launcher scopes the kill to its
    designated victim instead (thread mode shares one ambient registry,
    so the unscoped plan already fires exactly once there)."""
    if not plan_spec:
        return plan_spec
    plan = fregistry.FaultPlan.parse(plan_spec)
    rules = tuple(r for r in plan.rules if r.point in points)
    if not rules:
        return None
    return fregistry.FaultPlan(seed=plan.seed, rules=rules).spec()


# --------------------------------------------------------------- scoring


class HostModel:
    """Fixed-shape numpy scorer for one (possibly sharded) model.

    Scoring is PER-ROW with constant operand shapes: a request's reply
    bits depend only on its own payload and the model — never on batch
    fill, replica count, or which micro-batch it rode — which is what
    makes chaos re-routes and shard-count A/Bs bitwise-comparable.
    """

    def __init__(self, kind: str, center: dict, *, shard: int = 0,
                 n_shards: int = 1, k_top: int = 10,
                 merge: str = "sparse"):
        if kind not in ("lr", "kmeans", "als"):
            raise ValueError(f"unknown model kind {kind!r}")
        if not 0 <= shard < n_shards:
            raise ValueError(
                f"shard {shard} outside 0..{n_shards - 1}")
        if merge not in ("sparse", "dense"):
            raise ValueError(
                f"merge must be 'sparse' or 'dense', got {merge!r}")
        self.kind = kind
        self.center = {k: np.asarray(v, np.float32)
                       for k, v in center.items()}
        self.shard = int(shard)
        self.n_shards = int(n_shards)
        self.k_top = int(k_top)
        self.merge = merge
        if kind == "lr":
            self._w = self.center["w"].ravel()
        elif kind == "kmeans":
            self._centers = self.center["centers"]
        else:
            self._build_als()

    def _build_als(self) -> None:
        U, V = self.center["U"], self.center["V"]
        if U.shape[1] != V.shape[1]:
            raise ValueError(
                f"U {U.shape} vs V {V.shape}: factor ranks differ")
        self.n_items = int(V.shape[0])
        span = self.n_shards * SCORE_BLOCK
        n_pad = -(-self.n_items // span) * span
        self.local_n = n_pad // self.n_shards
        if self.k_top > min(self.n_items, self.local_n):
            raise ValueError(
                f"k_top={self.k_top} exceeds the catalogue "
                f"(n_items={self.n_items}, shard width "
                f"{self.local_n}) — merged top-k would carry "
                f"sentinel rows")
        self.off = self.shard * self.local_n
        Vl = np.zeros((self.local_n, V.shape[1]), np.float32)
        hi = min(self.off + self.local_n, self.n_items)
        if hi > self.off:
            Vl[:hi - self.off] = V[self.off:hi]
        # (rank, local_n) contiguous so each 128-wide column slice is
        # one fixed-shape gemm operand
        self._VT = np.ascontiguousarray(Vl.T)
        self._U = U
        self._gidx = (self.off
                      + np.arange(self.local_n)).astype(np.int32)
        self._valid = self._gidx < self.n_items

    def rebuild(self, center: dict) -> "HostModel":
        """The hot-swap constructor: same wiring, new weights."""
        return HostModel(self.kind, center, shard=self.shard,
                         n_shards=self.n_shards, k_top=self.k_top,
                         merge=self.merge)

    @property
    def meta(self) -> dict:
        out = {"kind": self.kind, "shard": self.shard,
               "n_shards": self.n_shards}
        if self.kind == "als":
            out.update(k_top=self.k_top, merge=self.merge,
                       n_items=self.n_items, local_n=self.local_n,
                       off=self.off)
        return out

    # ------------------------------------------------------ per kind

    def _score_lr(self, X: np.ndarray) -> dict:
        out = np.empty((X.shape[0],), np.float32)
        for r in range(X.shape[0]):
            z = np.float32(np.dot(X[r].astype(np.float32), self._w))
            out[r] = np.float32(1.0) / (np.float32(1.0)
                                        + np.exp(-z, dtype=np.float32))
        return {"y": out}

    def _score_kmeans(self, X: np.ndarray) -> dict:
        out = np.empty((X.shape[0],), np.int32)
        for r in range(X.shape[0]):
            d = self._centers - X[r].astype(np.float32)
            out[r] = np.argmin(
                np.sum(d * d, axis=1, dtype=np.float32))
        return {"y": out}

    def _local_scores(self, q: np.ndarray) -> np.ndarray:
        scores = np.empty((self.local_n,), np.float32)
        for j in range(0, self.local_n, SCORE_BLOCK):
            scores[j:j + SCORE_BLOCK] = np.dot(
                q, self._VT[:, j:j + SCORE_BLOCK])
        scores[~self._valid] = -np.inf
        return scores

    def _score_als(self, ids: np.ndarray) -> dict:
        B = ids.shape[0]
        if self.merge == "sparse":
            vals = np.empty((B, self.k_top), np.float32)
            idx = np.empty((B, self.k_top), np.int32)
            for r in range(B):
                s = self._local_scores(self._U[int(ids[r])])
                # value descending, ties toward the LOWER global index
                # — lax.top_k's order, and merge_topk_pairs_host's
                order = np.lexsort((self._gidx, -s))[:self.k_top]
                vals[r] = s[order]
                idx[r] = self._gidx[order]
            return {"vals": vals, "idx": idx}
        scores = np.empty((B, self.local_n), np.float32)
        for r in range(B):
            scores[r] = self._local_scores(self._U[int(ids[r])])
        return {"scores": scores}

    # ---------------------------------------------------------- frame

    def score_frame(self, arrays: dict) -> dict:
        """One ``score`` frame's reply arrays (shard candidates for a
        sharded ALS replica, final values otherwise)."""
        x = np.asarray(arrays["x"])
        if self.kind == "lr":
            return self._score_lr(x)
        if self.kind == "kmeans":
            return self._score_kmeans(x)
        return self._score_als(x.astype(np.int64))


# --------------------------------------------------------------- replica


class Replica:
    """One serving replica: a framed-TCP listener over a
    :class:`HostModel`, with version-stamped batch-atomic scoring and
    the live hot-swap seam."""

    def __init__(self, slot: int, model: HostModel, *,
                 version: int = 0, comm: str = "dense",
                 host: str = "127.0.0.1", port: int = 0,
                 rpc_deadline: float = 30.0,
                 process_kill: bool = False, logger=None):
        self.slot = int(slot)
        self.model = model
        self.version = int(version)
        self._comm = comm
        self.host = host
        self.port = int(port)
        self.rpc_deadline = float(rpc_deadline)
        self.process_kill = bool(process_kill)
        self.log = logger or (lambda *_: None)
        self._pull_codec = pcomms.make_host_pull_codec(comm)
        self._model_lock = threading.Lock()
        self._conns: set = set()
        self._stop = threading.Event()
        self._listener: socket.socket | None = None
        self._threads: list = []
        self.killed = False

    # ---------------------------------------------------- lifecycle

    def start(self) -> "Replica":
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        t = threading.Thread(target=self._accept_loop,
                             name=f"tda-replica{self.slot}-accept",
                             daemon=True)
        t.start()
        self._threads.append(t)
        tevents.emit("replica_start", slot=self.slot, port=self.port,
                     version=self.version, **self.model.meta)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass

    def slam(self) -> None:
        """Abruptly close the listener and every live connection —
        what a SIGKILL does to the process's sockets (the thread-mode
        kill observable, same shape as the coordinator's)."""
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for conn in list(self._conns):
            for fn in (lambda: conn.shutdown(2), conn.close):
                try:
                    fn()
                except OSError:
                    pass

    def _die(self) -> None:
        """The ``cluster:replica`` kill cell: a real SIGKILL in
        process mode; thread mode slams the sockets (same router-side
        EOF) and unwinds the handler."""
        self.killed = True
        self._stop.set()
        tevents.counter("cluster.replica_kills")
        if self.process_kill:
            os.kill(os.getpid(), signal.SIGKILL)
        self.slam()
        raise ReplicaKilled(f"replica {self.slot} killed at the "
                            f"score seam")

    # ----------------------------------------------------------- IO

    def _accept_loop(self) -> None:
        self._listener.settimeout(POLL_SECONDS)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # daemon handlers, untracked on purpose (the coordinator's
            # accept-loop shape): stop()/EOF ends them
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f"tda-replica{self.slot}-conn",
                daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        self._conns.add(conn)
        try:
            while not self._stop.is_set():
                try:
                    kind, meta, arrays = transport.recv_frame(
                        conn, deadline=4 * self.rpc_deadline)
                except transport.TransportTimeout:
                    continue  # idle connection
                reply = self._handle(kind, meta or {}, arrays or {})
                transport.send_frame(conn, *reply,
                                     deadline=self.rpc_deadline)
                if kind == "stop":
                    break
        except transport.TransportClosed:
            pass
        except transport.TransportError:
            pass
        except ReplicaKilled:
            pass  # thread-mode SIGKILL stand-in: just unwind
        finally:
            self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    # ----------------------------------------------------- handlers

    def _handle(self, kind: str, meta: dict, arrays: dict) -> tuple:
        if kind == "hello":
            with self._model_lock:
                m = {"replica": self.slot, "version": self.version}
                m.update(self.model.meta)
            return ("welcome", m, None)
        if kind == "score":
            return self._handle_score(arrays)
        if kind == "hb":
            with self._model_lock:
                return ("hb_ok", {"replica": self.slot,
                                  "version": self.version}, None)
        if kind == "swap":
            return self._handle_swap(meta, arrays)
        if kind == "stop":
            return ("bye", {"replica": self.slot}, None)
        return ("error", {"error": f"unknown frame kind {kind!r}"},
                None)

    def _handle_score(self, arrays: dict) -> tuple:
        # the replica's chaos seam: a kill here lands mid-burst, with
        # this batch's requests in flight and unanswered — the honest
        # failure the router's re-route machinery is measured against
        try:
            faults.inject("cluster:replica")
        except fregistry.InjectedKill:
            self._die()
        with self._model_lock:
            out = self.model.score_frame(arrays)
            version = self.version
        n = int(np.asarray(arrays["x"]).shape[0])
        tevents.counter("cluster.replica_requests", n)
        tevents.counter("cluster.replica_batches")
        return ("scored", {"replica": self.slot, "version": version,
                           "n": n}, out)

    def _handle_swap(self, meta: dict, arrays: dict) -> tuple:
        cv = int(meta["cv"])
        mode = meta.get("mode", "dense")
        with self._model_lock:
            if cv <= self.version:
                # idempotent re-publish (router recovery re-sends the
                # newest center): already absorbed, stay put
                return ("swap_ok", {"replica": self.slot,
                                    "version": self.version}, None)
            if mode == "delta":
                base = int(meta["base"])
                if self._pull_codec is None or base != self.version:
                    # delta computed against a center we don't hold —
                    # the router falls back to a dense snapshot
                    return ("swap_stale",
                            {"replica": self.slot,
                             "have": self.version}, None)
                delta = pcomms.decode_tree(self._pull_codec, arrays,
                                           self.model.center)
                center = {k: self.model.center[k] + delta[k]
                          for k in self.model.center}
                tevents.counter("cluster.replica_delta_swaps")
            else:
                center = {k: np.asarray(v, np.float32)
                          for k, v in arrays.items()}
                tevents.counter("cluster.replica_dense_swaps")
            # the atomic batch-boundary swap: scoring holds this lock
            # per batch, so no request ever sees a half-applied center
            self.model = self.model.rebuild(center)
            self.version = cv
            tevents.counter("cluster.replica_swaps")
            tevents.emit("replica_swap", slot=self.slot, version=cv,
                         mode=mode)
            return ("swap_ok", {"replica": self.slot,
                                "version": self.version}, None)


def run_replica(slot: int, artifact: str, *, shard: int = 0,
                n_shards: int = 1, k_top: int = 10,
                merge: str = "sparse", comm: str = "dense",
                host: str = "127.0.0.1", port: int = 0,
                logger=None) -> Replica:
    """The ``tda cluster --role replica`` entry: load the checkpoint
    artifact (through ``serve/artifacts.py``'s re-read degradation),
    build the shard's :class:`HostModel`, listen. Caller prints the
    ``cluster_replica: listening on host:port`` line and parks."""
    from tpu_distalg.serve import artifacts as serve_artifacts

    root, state, _step = serve_artifacts.load_artifact_state(artifact)
    kind, center = center_of_state(root, state)
    model = HostModel(kind, center, shard=shard, n_shards=n_shards,
                      k_top=k_top, merge=merge)
    return Replica(slot, model, comm=comm, host=host, port=port,
                   process_kill=True, logger=logger).start()


# ----------------------------------------------------------- the fleet


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """The local serving-plane launcher's knobs (CLI-mirrored)."""

    kind: str = "kmeans"          # lr | kmeans | als
    n_replicas: int = 3
    sharded: bool = False         # als: model-axis shards vs replicas
    policy: str = "least_loaded"  # least_loaded | consistent_hash
    comm: str = "dense"           # hot-swap wire schedule
    k_top: int = 10
    merge: str = "sparse"         # sparse pairs | dense blocks
    max_batch: int = 16
    max_delay_ms: float = 2.0
    queue_depth: int = 128
    hb_interval: float = 0.2
    hb_timeout: float = 2.0
    rpc_deadline: float = 30.0
    wal_dir: str | None = None    # router durable state (recovery)
    port: int = 0                 # router client port
    seed: int = 0
    version: int = 0              # version of the initial center
    artifact: str | None = None   # process spawn: checkpoint dir
    fault_slot: int | None = None  # process spawn: scoped-plan victim


class ServeFleet:
    """Replica fleet + router, launched locally — threads (fast, the
    test/bench path; one ambient fault registry) or real subprocesses
    (kill -9 is the genuine article). The router always runs
    in-process: its crash drill is :meth:`Router.slam` + a fresh
    ``Router`` recovering from the WAL on the same port."""

    def __init__(self, config: FleetConfig, center: dict | None = None,
                 *, spawn: str = "thread", plan_spec: str | None = None,
                 telemetry_dir: str | None = None, logger=None):
        if spawn not in ("thread", "process"):
            raise ValueError(f"spawn must be thread|process, "
                             f"got {spawn!r}")
        if spawn == "process" and config.artifact is None:
            raise ValueError(
                "process-mode replicas load a checkpoint artifact — "
                "set FleetConfig.artifact")
        self.cfg = config
        self.center = center
        self.spawn = spawn
        self.plan_spec = plan_spec
        self.telemetry_dir = telemetry_dir
        self.log = logger or (lambda *_: None)
        self.replicas: list[Replica] = []      # thread mode
        self.procs: list = []                  # process mode
        self.router = None

    # ---------------------------------------------------- lifecycle

    def start(self) -> "ServeFleet":
        from tpu_distalg.cluster.router import Router, RouterConfig

        n = self.cfg.n_replicas
        n_shards = n if self.cfg.sharded else 1
        addrs = []
        if self.spawn == "thread":
            for slot in range(n):
                model = HostModel(
                    self.cfg.kind, self.center,
                    shard=slot if self.cfg.sharded else 0,
                    n_shards=n_shards, k_top=self.cfg.k_top,
                    merge=self.cfg.merge)
                rep = Replica(slot, model, version=self.cfg.version,
                              comm=self.cfg.comm,
                              rpc_deadline=self.cfg.rpc_deadline,
                              logger=self.log).start()
                self.replicas.append(rep)
                addrs.append(("127.0.0.1", rep.port))
        else:
            for slot in range(n):
                addrs.append(self._spawn_process_replica(
                    slot, n_shards))
        self.router = Router(RouterConfig(
            replicas=tuple(addrs),
            mode="sharded" if self.cfg.sharded else "routed",
            policy=self.cfg.policy, comm=self.cfg.comm,
            port=self.cfg.port, wal_dir=self.cfg.wal_dir,
            max_batch=self.cfg.max_batch,
            max_delay_ms=self.cfg.max_delay_ms,
            queue_depth=self.cfg.queue_depth,
            hb_interval=self.cfg.hb_interval,
            hb_timeout=self.cfg.hb_timeout,
            rpc_deadline=self.cfg.rpc_deadline,
            seed=self.cfg.seed, k_top=self.cfg.k_top,
            merge=self.cfg.merge), logger=self.log).start()
        if self.center is not None:
            self.router.seed_history(self.cfg.version, self.center)
        return self

    def _spawn_process_replica(self, slot: int, n_shards: int):
        cfg = self.cfg
        cmd = [sys.executable, "-m", "tpu_distalg.cli", "cluster",
               "--role", "replica", "--slot", str(slot),
               "--artifact", cfg.artifact,
               "--replica-shards", str(n_shards),
               "--shard", str(slot if cfg.sharded else 0),
               "--k-top", str(cfg.k_top), "--merge", cfg.merge,
               "--comm", cfg.comm, "--port", "0"]
        if self.telemetry_dir:
            cmd += ["--telemetry-dir",
                    os.path.join(self.telemetry_dir,
                                 f"replica-{slot}")]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop(fregistry.ENV_PLAN, None)
        if self.plan_spec and slot == (self.cfg.fault_slot or 0):
            scoped = scoped_plan_spec(self.plan_spec)
            if scoped:
                env[fregistry.ENV_PLAN] = scoped
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        self.procs.append(proc)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if line.startswith("cluster_replica: listening on "):
                host, _, port = line.rsplit(None, 1)[-1].rpartition(
                    ":")
                return (host, int(port))
        raise RuntimeError(
            f"replica {slot} subprocess never announced its port "
            f"(rc={proc.poll()})")

    # --------------------------------------------------- operations

    def request(self, payload, *, key=None, timeout: float = 30.0):
        return self.router.request(payload, key=key, timeout=timeout)

    def publish(self, center: dict, version: int) -> dict:
        return self.router.publish(center, version)

    def stats(self) -> dict:
        return self.router.stats()

    def stop(self) -> None:
        if self.router is not None:
            self.router.stop()
        for rep in self.replicas:
            rep.stop()
        for proc in self.procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs:
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)


def run_fleet_closed_loop(fleet_or_router, payloads, *,
                          concurrency: int = 4, retries: int = 0,
                          retry_backoff_s: float = 0.002,
                          timeout: float = 60.0, keys=None):
    """The fleet's closed-loop load generator — ``serve/server.py``'s
    ``run_closed_loop`` lifted onto the router surface. A shed or a
    mid-flight replica death surfaces as the request's error; with
    ``retries`` the worker resubmits after backoff (the client half of
    shed-don't-die, and what makes a chaos run's reply set complete
    and bitwise-comparable). Returns ``(results, info)`` where each
    result is ``(value, version, replica)`` or ``None``; ``info``
    carries client-observed latency percentiles (first submit to
    final answer, retries and backoff INCLUDED — what a kill actually
    costs the caller, not what the router saw per attempt)."""
    results = [None] * len(payloads)
    errors = [None] * len(payloads)
    lat_ms = [None] * len(payloads)
    counts = {"retries": 0, "failed": 0, "first_try_ok": 0}
    lock = threading.Lock()

    def worker(idxs):
        for j in idxs:
            attempt = 0
            t_first = time.perf_counter()
            while True:
                try:
                    out = fleet_or_router.request(
                        payloads[j],
                        key=None if keys is None else keys[j],
                        timeout=timeout)
                    dt_ms = (time.perf_counter() - t_first) * 1e3
                    with lock:
                        results[j] = out
                        errors[j] = None
                        lat_ms[j] = dt_ms
                        if attempt == 0:
                            counts["first_try_ok"] += 1
                    break
                except Exception as e:  # noqa: BLE001 — sheds and
                    #                     re-route exhaustion are data
                    #                     here; the loop must finish
                    with lock:
                        errors[j] = e
                    if attempt >= retries:
                        with lock:
                            counts["failed"] += 1
                        break
                    attempt += 1
                    with lock:
                        counts["retries"] += 1
                    time.sleep(retry_backoff_s)

    concurrency = max(1, min(concurrency, len(payloads) or 1))
    slices = [list(range(w, len(payloads), concurrency))
              for w in range(concurrency)]
    threads = [threading.Thread(target=worker, args=(s,), daemon=True,
                                name=f"fleet-load-{w}")
               for w, s in enumerate(slices)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    n_ok = sum(1 for e in errors if e is None)
    done = sorted(x for x in lat_ms if x is not None)

    def _pct(q):
        if not done:
            return 0.0
        return round(done[min(len(done) - 1,
                              int(q * (len(done) - 1) + 0.5))], 3)

    info = {
        "elapsed_s": round(elapsed, 4),
        "qps": round(n_ok / elapsed, 2) if elapsed > 0 else 0.0,
        "ok": n_ok,
        "failed": counts["failed"],
        "retries": counts["retries"],
        # availability = fraction answered on the FIRST attempt: what
        # the kill actually cost clients, with retries factored out
        "availability": (round(counts["first_try_ok"]
                               / len(payloads), 4)
                         if payloads else 1.0),
        "p50_ms": _pct(0.50),
        "p99_ms": _pct(0.99),
        "concurrency": concurrency,
    }
    return results, info
