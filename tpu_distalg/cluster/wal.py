"""Durable write-ahead ledger — the coordinator's crash-survival log.

PR 12 made *workers* expendable; the coordinator remained the single
point of failure: one process held the membership ledger, the
cross-process SSP clock, and the merge pipeline in RAM, so its death
stranded every worker and discarded all progress since the last
periodic center save. This module closes that hole: every state
transition the replay contract depends on — admissions and incarnation
grants, announced skips, window commits (slot-ordered contribution
digests *and* the applied delta bytes — a redo log; under a ``--comm``
wire codec these are the COMPRESSED payload bytes exactly as pushed,
so replay re-runs the same exact decode and the re-push dedup digests
match a resent frame by construction), membership epochs, admission
holds — is appended as a CRC-framed record and fsynced *before* the
corresponding ack leaves the socket. On restart
the coordinator replays the ledger on top of the newest durable center
checkpoint and resumes as if it never died; a half-committed window
(pushes that arrived but never committed) is simply absent from the
ledger, so it rolls back to its start — and because push acks are
DEFERRED until commit, no worker ever observed it: rollback is
invisible by construction, and the workers re-push the identical
deltas on reconnect.

Record format: exactly the wire format. Each record is one
``transport.encode_frame`` frame (magic + u32/u64 length prefix +
CRC32 + JSON meta + raw numpy buffers), concatenated into an
append-only segment file — the same torn/corrupt detection the
transport gives a socket, applied to a file. Replay stops at the
FIRST bad record and truncates there with a quarantine event
(mirroring the checkpoint CRC-footer contract). For the common
crash-mid-append case that is lossless: the torn tail was never fully
fsynced, so its ack never left. For silent MID-file corruption (bit
rot, or a seeded ``cluster:wal`` ``corrupt`` cell) it is a deliberate
consistency choice — the records after the bad one may be intact and
may even have been acked, but applying them across a hole would
replay a version GAP (a skipped commit) into an inconsistent center,
so recovery keeps the last consistent PREFIX, exactly like a database
redo log; the quarantine event records how many bytes were dropped so
the loss is visible, never silent.

Segments & truncation: one segment file ``wal_<base>.log`` per durable
center checkpoint, where ``base`` is the checkpoint's version. Every
segment opens with a ``base`` record carrying a full snapshot of the
coordinator's CONTROL state (version, generation, incarnation counter,
slot table, event history) — the data plane lives in the checkpoint,
the control plane in the snapshot, and everything since in the
records. At each new durable center the WAL rotates to a fresh
segment and deletes segments older than the oldest KEPT checkpoint
(``keep``), so a quarantined-corrupt newest checkpoint can still fall
back to an older step and roll the intervening commits forward from
the older segments' redo records.

Durability discipline (machine-checked by TDA091): every append is
``write → flush → fsync`` before control returns — the caller's socket
send of the ack happens strictly after the record is durable — and
segment creation fsyncs the directory so the new file survives a power
cut, the same discipline as ``utils/checkpoint.save``.

Fault seam ``cluster:wal``: injected on the encoded record bytes at
the top of :meth:`WriteAheadLog.append` — ``corrupt`` really flips
bytes (the replay CRC catches it as a quarantined tail), ``oserror``
models a transient disk fault.

Record kinds (meta ``k``, one frame each):

  ``base``       segment header — full control-plane snapshot
  ``admit``      slot admission + incarnation grant (fencing)
  ``skip``       an announced busy-skip (dedup on replay)
  ``commit``     one SSP window: slot-ordered contribution digests +
                 the pushed delta bytes keyed ``{slot}/{leaf}`` — the
                 redo record. In rowstore PS mode each contribution
                 additionally carries its ``{slot}/{leaf}.rows``
                 int64 row-index array (the per-ROW redo record: the
                 replayed merge re-applies exactly those rows, and
                 the digest covers the index bytes too)
  ``rowcommit``  one row-store fleet commit (the cluster PageRank /
                 ALS engines in ``cluster/rowstore.py``): per-slot
                 sparse row pushes keyed ``{slot}/{leaf}.rows`` +
                 ``{slot}/<codec parts>``, plus the combine's scalar
                 meta (e.g. the dangling-mass sum) — replay re-runs
                 the identical decode and row apply, bitwise
  ``leave``      membership epoch transition (a declared death)
  ``hold``       admission hold      ``bye``  worker departure
  ``done``       run completion

stdlib + numpy only, like the transport.
"""

from __future__ import annotations

import os
import zlib

import numpy as np

from tpu_distalg import faults
from tpu_distalg.cluster import transport
from tpu_distalg.telemetry import events as tevents

#: segment filename pattern: wal_<base version, zero-padded>.log
_SEG_PREFIX = "wal_"
_SEG_SUFFIX = ".log"


class WalError(RuntimeError):
    """A WAL invariant broke in a way replay cannot repair (a segment
    whose HEADER record is unreadable — the snapshot is gone)."""


def delta_digest(arrays: dict) -> int:
    """CRC32 over a contribution's leaf names + raw bytes — the
    idempotence token: a worker re-delivering an already-committed
    push after a coordinator recovery must present the SAME bytes, and
    the commit record's digest is how the coordinator checks without
    keeping the delta itself in RAM forever."""
    crc = 0
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        crc = zlib.crc32(name.encode(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFFFFFF


def _segment_path(wal_dir: str, base: int) -> str:
    return os.path.join(wal_dir, f"{_SEG_PREFIX}{base:012d}{_SEG_SUFFIX}")


def segment_bases(wal_dir: str) -> list[int]:
    """The on-disk segment base versions, ascending."""
    if not os.path.isdir(wal_dir):
        return []
    out = []
    for name in sorted(os.listdir(wal_dir)):
        if name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX):
            try:
                out.append(int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]))
            except ValueError:
                continue
    return sorted(out)


def _fsync_dir(directory: str) -> None:
    """Directory fsync so a just-created segment survives a power cut
    — same best-effort contract as ``utils/checkpoint._fsync_dir``,
    deliberately DUPLICATED rather than imported: checkpoint.py
    imports jax at module level, and the WAL (like the transport)
    must stay importable in a bare host process before any jax
    import."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def read_segment(path: str, *, truncate_torn: bool = True):
    """Read one segment file -> ``(records, torn_bytes)`` where
    ``records`` is ``[(kind, meta, arrays), ...]`` in append order.

    A torn / CRC-bad / short tail stops the read at the last GOOD
    record; when ``truncate_torn`` the file is truncated there (and
    fsynced) with a ``wal_quarantine`` event — the durable mirror of
    the checkpoint quarantine path. Returns the number of bytes
    dropped (0 on a clean read)."""
    with open(path, "rb") as f:
        raw = f.read()
    records = []
    off = 0
    psize = transport._PREFIX.size
    while off < len(raw):
        if off + psize > len(raw):
            break  # torn prefix
        magic, hlen, blen, crc = transport._PREFIX.unpack(
            raw[off:off + psize])
        if magic != transport.MAGIC or \
                hlen > transport.MAX_HEADER_BYTES:
            break  # desynchronized / corrupt prefix
        end = off + psize + hlen + blen
        if end > len(raw):
            break  # torn record body
        header = raw[off + psize:off + psize + hlen]
        body = raw[off + psize + hlen:end]
        got = zlib.crc32(header)
        got = zlib.crc32(body, got) & 0xFFFFFFFF
        if got != crc:
            break  # bit-rot / injected corruption: CRC catches it
        try:
            records.append(transport.parse_payload(header, body))
        except transport.TransportError:
            break
        off = end
    torn = len(raw) - off
    if torn and truncate_torn:
        with open(path, "r+b") as f:
            f.truncate(off)
            f.flush()
            os.fsync(f.fileno())
        tevents.emit("wal_quarantine", path=path, torn_bytes=torn,
                     kept_records=len(records))
        tevents.counter("cluster.wal_quarantines")
    return records, torn


class WriteAheadLog:
    """Append-only CRC-framed segments under ``wal_dir``; one open
    segment at a time. Not thread-safe by itself — the coordinator
    appends under its own state lock, which is also what orders the
    records."""

    def __init__(self, wal_dir: str):
        self.wal_dir = wal_dir
        self._f = None
        self.base: int | None = None
        os.makedirs(wal_dir, exist_ok=True)

    # ------------------------------------------------------- writing

    def open_segment(self, base: int, snapshot: dict) -> None:
        """Start (or re-open, after a recovery) the segment for the
        durable center at version ``base``. A segment is only usable
        when its FIRST record is a readable ``base`` snapshot — an
        existing file whose header was torn/quarantined away (or that
        is empty) is REWRITTEN fresh with the caller's current
        snapshot, because appending acked records to a headerless
        segment would hand the next recovery a file it must skip
        whole; a healthy existing segment appends after its current
        end (recovery continues the segment it replayed)."""
        self.close()
        path = _segment_path(self.wal_dir, base)
        fresh = True
        if os.path.exists(path):
            head, _torn = read_segment(path, truncate_torn=True)
            if head and head[0][0] == "base":
                fresh = False
            else:
                # headerless husk: the snapshot below supersedes it
                # (it is the FULL control state, so nothing is lost)
                with open(path, "r+b") as f:
                    f.truncate(0)
                    f.flush()
                    os.fsync(f.fileno())
        self._f = open(path, "ab")
        self.base = base
        if fresh:
            self.append("base", snapshot)
            _fsync_dir(self.wal_dir)

    def append(self, kind: str, meta: dict,
               arrays: dict | None = None) -> None:
        """One durable record: encode, (fault seam), write, flush,
        fsync — the caller's ack send happens strictly after this
        returns (TDA091's contract). A FAILED append rewinds the
        segment to the record boundary before re-raising: the caller
        retries transient OSErrors (``supervised``), and retrying on
        top of a half-landed copy would leave a torn or duplicate
        record MID-log — replay would either truncate there
        (discarding every later acked record) or apply the record's
        events twice."""
        if self._f is None:
            raise WalError("append on a closed WAL — open_segment "
                           "first")
        buf = faults.inject(
            "cluster:wal",
            payload=transport.encode_frame(kind, meta, arrays))
        start = self._f.tell()
        try:
            self._f.write(buf)
            self._f.flush()
            os.fsync(self._f.fileno())
        except OSError:
            try:
                self._f.truncate(start)
            except (OSError, ValueError):
                # double fault: the rewind itself failed — the
                # segment may be torn mid-log; refuse further appends
                # (the coordinator's supervised retry surfaces the
                # original error) rather than append after garbage
                try:
                    self._f.close()
                except (OSError, ValueError):
                    pass
                self._f = None
            raise
        tevents.counter("cluster.wal_appends")

    def rotate(self, base: int, snapshot: dict, *,
               keep_base: int | None = None) -> None:
        """Cut over to the segment for the new durable center at
        ``base`` and delete segments older than ``keep_base`` (the
        oldest KEPT checkpoint's version — older segments could only
        matter for falling back past checkpoints that no longer
        exist)."""
        self.open_segment(base, snapshot)
        if keep_base is not None:
            for b in segment_bases(self.wal_dir):
                if b < keep_base and b != base:
                    try:
                        os.remove(_segment_path(self.wal_dir, b))
                    except FileNotFoundError:
                        pass

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except (OSError, ValueError):
                pass
            self._f.close()
            self._f = None

    # ------------------------------------------------------- reading

    @staticmethod
    def replay(wal_dir: str, center_version: int):
        """The recovery read path: every record needed to roll forward
        from the restored center at ``center_version`` — ``(records,
        replay_base)`` where ``records`` is ``[(kind, meta, arrays),
        ...]`` across segments in base order starting at the newest
        segment whose base ≤ ``center_version`` (older segments'
        commits for windows already inside the restored center are
        skipped by the applier's version check), and ``replay_base``
        is the base of the NEWEST readable segment (the one recovery
        re-opens for appending). Empty dir -> ``([], None)``."""
        bases = segment_bases(wal_dir)
        if not bases:
            return [], None
        readable: dict[int, list] = {}
        for b in bases:
            segment, _torn = read_segment(_segment_path(wal_dir, b))
            if segment and segment[0][0] == "base":
                readable[b] = segment
            # else: a headerless husk (its base snapshot never became
            # durable, or was quarantined away) — it must not SHADOW
            # older readable segments, and open_segment rewrites it
            # before any new record lands in it
        if not readable:
            return [], None
        eligible = [b for b in readable if b <= center_version]
        # a segment newer than the restored center means the newer
        # checkpoint it sat on was quarantined: roll forward from the
        # older segments' redo records through it
        start = max(eligible) if eligible else min(readable)
        records: list = []
        replay_base = None
        for b in sorted(readable):
            if b < start:
                continue
            records.extend(readable[b])
            replay_base = b
        return records, replay_base
