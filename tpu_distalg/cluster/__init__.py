"""Multi-process elastic runtime — coordinator/worker over TCP.

The native replacement for PySpark's driver↔executor PROCESS model
(SURVEY.md §4's last unported layer): a coordinator process owning
rendezvous, the cross-process SSP clock, heartbeat failure detection
and durable center checkpoints; N worker processes each running the
existing SGD-family trainers on their own local mesh; and a
parameter-server tier applying staleness-weighted (``decay**age``)
delta merges — all over a length-prefixed framed-numpy TCP transport
(no pickle, a deadline on every blocking receive; TDA090 lints the
discipline). ``--comm {dense,int8[:seed],topk[:frac]}`` selects the
WIRE schedule: compressed pushes (seeded stochastic int8 / top-k
pairs with worker-side error feedback) overlap the next window's
compute on a background sender, and pulls ship version-pinned
compressed deltas against the worker's cached center (the host
codecs of ``parallel/comms.py``). A worker can genuinely die
(``kill -9``), lag, join and leave while training continues at
reduced quorum; the seeded fault plan (``cluster:worker`` /
``cluster:rpc`` points) makes a chaos run — compressed or dense —
replay to the identical merge/membership event sequence.

``--ps-mode rowstore`` swaps the replicated PS tier for the SHARDED
row store (``cluster/rowstore.py``): each PS shard owns a disjoint
leading-dim row range under the model's partition rule table, pushes
carry per-leaf ``{name}.rows`` index arrays and merge row-wise with
per-row versions (``decay**age`` per ROW, not per delta), and the
cluster graph engines (``run_cluster_pagerank``) pull only the rows
an iteration touches — the model no longer has to fit one host.

See ``docs/ARCHITECTURE.md`` ("Multi-process elastic runtime",
"Sharded-state parameter server") and ``tda cluster --help``.
"""

from tpu_distalg.cluster import ps, rowstore, transport, wal
from tpu_distalg.cluster.rowstore import (
    ClusterPageRankConfig,
    RowStore,
    run_cluster_pagerank,
)
from tpu_distalg.cluster.coordinator import (
    ClusterAborted,
    ClusterConfig,
    Coordinator,
    TrainTask,
    center_accuracy,
    compile_coordinator_schedule,
)
from tpu_distalg.cluster.local import run_local_cluster
from tpu_distalg.cluster.worker import (
    compile_worker_schedule,
    run_worker,
    strip_kills,
)

__all__ = [
    "ClusterAborted",
    "ClusterConfig",
    "ClusterPageRankConfig",
    "Coordinator",
    "RowStore",
    "TrainTask",
    "center_accuracy",
    "compile_coordinator_schedule",
    "compile_worker_schedule",
    "ps",
    "rowstore",
    "run_cluster_pagerank",
    "run_local_cluster",
    "run_worker",
    "strip_kills",
    "transport",
    "wal",
]
