"""Parameter-server tier — the center, sharded by partition rule tables.

The coordinator process holds the replicated-center state the SGD
family trains (``w`` for the LR/SSGD vocabulary) split across
``n_shards`` PS shards, each with its own lock so concurrent merges on
disjoint shards never serialize. WHICH leaves split and which stay
whole is not re-decided here: the model's registered
:mod:`~tpu_distalg.parallel.partition` rule table is consulted — a
leaf whose spec shards its leading dim splits row-wise across the PS
shards (``np.array_split``: UNEVEN splits are first-class, which is
what a worker count that does not divide the model axis produces —
the device-side mirror of this is ``partition.reshard``'s
pad-reshard-slice path), a replicated-spec leaf lives whole on shard
0. So the PS placement is the same one-rule-table-per-model contract
the in-process trainers follow.

The merge is the stale-synchronous weighted delta application of
``parallel/ssp.py``, over the wire instead of a collective: each
contribution carries its base version, its weight is ``decay**age``
(``age = commit_window − base``, exactly ``ssp.staleness_weights``'
exponent), and the center moves by the weighted MEAN of the delivered
deltas — ``w += Σ wᵢ·Δᵢ / Σ wᵢ``, the same formula
``ssgd.make_ssp_train_fn``'s window body applies on device. A commit
nobody delivered to is a hard no-op (the in-process round-3 lesson:
no epsilon divides).

numpy-only: the PS applies host math; device placement is the
workers' business.
"""

from __future__ import annotations

import threading

import numpy as np

from tpu_distalg.parallel import partition
from tpu_distalg.parallel.ssp import DEFAULT_DECAY
from tpu_distalg.tune import defaults as tune_defaults

PS_MODES = ("replicated", "rowstore")

#: suffix of a delta's per-leaf row-index array (rowstore mode): a
#: push carrying ``{name}.rows`` moves ONLY those leading-dim rows of
#: leaf ``name``; without it the delta is whole-leaf (rows 0..n)
ROWS_SUFFIX = ".rows"


def split_center(center: dict, table_name: str,
                 n_shards: int) -> list[dict]:
    """Per-PS-shard sub-dicts of ``center`` under the model's rule
    table: sharded-spec leaves row-split (uneven OK), replicated-spec
    leaves whole on shard 0. The union of the shards is exactly the
    center (reassembled by :func:`join_center`). The slicing itself
    lives in :class:`partition.RowOwnershipMap` — ONE derivation of
    row ownership shared with the sharded row store
    (``cluster/rowstore.py``) and the cluster graph/ALS engines; this
    wrapper keeps the historical byte-level contract (it IS the old
    ``np.array_split`` arithmetic, now table-driven in one place)."""
    return partition.RowOwnershipMap.for_center(
        center, table_name, n_shards).split(center)


def join_center(shards: list[dict]) -> dict:
    """Inverse of :func:`split_center` (concatenate the split leaves in
    shard order; whole leaves pass through)."""
    out: dict = {}
    names: list[str] = []
    for sh in shards:
        for name in sh:
            if name not in names:
                names.append(name)
    for name in names:
        pieces = [sh[name] for sh in shards if name in sh]
        out[name] = (pieces[0].copy() if len(pieces) == 1
                     else np.concatenate(pieces, axis=0))
    return out


class PsShard:
    """One PS shard: its slice of every split leaf, one lock."""

    def __init__(self, leaves: dict):
        self.lock = threading.Lock()
        self.leaves = {k: np.asarray(v, np.float32)
                       if np.asarray(v).dtype.kind == "f"
                       else np.asarray(v).copy()
                       for k, v in leaves.items()}

    def apply_weighted(self, contribs: list[tuple[float, dict]]) -> None:
        """``leaf += Σ wᵢ·Δᵢ / Σ wᵢ`` for this shard's slice of every
        delta — the ssp window merge, host-side. Empty ⇒ hard no-op."""
        if not contribs:
            return
        wsum = float(sum(w for w, _ in contribs))
        if wsum <= 0.0:
            return
        with self.lock:
            for name in self.leaves:
                acc = None
                for w, delta in contribs:
                    if name not in delta:
                        continue
                    term = np.float32(w) * np.asarray(delta[name],
                                                      np.float32)
                    acc = term if acc is None else acc + term
                if acc is not None:
                    self.leaves[name] = (
                        self.leaves[name] + acc / np.float32(wsum))


class ParameterServer:
    """The tier: ``n_shards`` :class:`PsShard`\\ s over one model's
    center, plus the version counter (= windows merged so far — the
    number a contribution's age is measured against).

    ``history_depth > 0`` keeps a bounded ``{version: center}`` ring
    of post-merge snapshots — the compressed cluster wire's
    VERSION-DELTA pull source: a worker caching center@v is served
    ``quantize(center@new − center@v)`` instead of a dense snapshot,
    and because the ring rebuilds deterministically from WAL replay
    (each replayed commit re-records its snapshot), a recovered
    coordinator re-serves bit-identical pull bytes. Dense mode keeps
    the depth at 0: zero overhead, trajectories pinned to history."""

    def __init__(self, center: dict, *, table: str = "lr",
                 n_shards: int = tune_defaults.PS_SHARDS,
                 decay: float = DEFAULT_DECAY,
                 history_depth: int = 0, mode: str = "replicated",
                 row_staleness: int | None = None):
        if mode not in PS_MODES:
            raise ValueError(
                f"unknown ps mode {mode!r}; choose from {PS_MODES}")
        self.table = table
        self.decay = float(decay)
        self.n_shards = int(n_shards)
        self.mode = mode
        if mode == "rowstore":
            # deferred import: rowstore pulls the comms codec module
            # (jax) — the replicated tier stays numpy-light
            from tpu_distalg.cluster import rowstore as _rowstore

            self.store = _rowstore.RowStore(
                center, table=table, n_shards=self.n_shards,
                decay=self.decay, staleness=row_staleness)
            self.shards = []
        else:
            self.store = None
            self.shards = [PsShard(s) for s in
                           split_center(center, table, self.n_shards)]
        self._version_lock = threading.Lock()
        self.version = 0  # windows merged into the center
        self.history_depth = int(history_depth)
        self.history: dict[int, dict] = {}

    @staticmethod
    def weight(decay: float, age: int) -> float:
        """``decay**age`` — ssp.staleness_weights' exponent, scalar."""
        return float(np.float32(decay) ** np.float32(max(0, age)))

    def merge(self, commit_window: int,
              contribs: list[tuple[int, int, dict]]) -> list[dict]:
        """Apply one commit: ``contribs`` is ``[(slot, base, delta)]``
        in SLOT order (the caller — the coordinator's commit loop —
        owns the ordering, which is what makes the merge sequence a
        pure function of the plan). Returns the per-contribution
        records ``[{slot, base, age, weight}]``; bumps ``version``."""
        if self.mode == "rowstore":
            return self._merge_rows(commit_window, contribs)
        records = []
        weighted: list[tuple[float, list[dict]]] = []
        for slot, base, delta in contribs:
            # base = the center version (windows merged) the delta was
            # computed against; a fresh delivery at window w has
            # base == w (it adopted the post-commit-(w−1) center), so
            # age = w − base = 0 — in-process ssp's winid − basegen
            age = max(0, commit_window - int(base))
            w = self.weight(self.decay, age)
            records.append({"slot": int(slot), "base": int(base),
                            "age": int(age), "weight": round(w, 6)})
            # each delta splits under the SAME rule table as the
            # center, so shard i applies exactly its slice
            weighted.append(
                (w, split_center(delta, self.table, self.n_shards)))
        for i, shard in enumerate(self.shards):
            shard.apply_weighted(
                [(w, pieces[i]) for w, pieces in weighted])
        with self._version_lock:
            self.version = max(self.version, commit_window + 1)
        self.record_history(commit_window + 1)
        return records

    def _merge_rows(self, commit_window: int,
                    contribs: list[tuple[int, int, dict]]) -> list[dict]:
        """Rowstore-mode commit: each delta's ``{name}.rows`` array
        selects the leading-dim rows it moves (absent ⇒ whole leaf),
        the contribution's scalar ``base`` becomes every row's base
        version, and the weighted mean applies ROW-WISE in the
        :class:`~tpu_distalg.cluster.rowstore.RowStore` — a whole-leaf
        push at a uniform base merges bit-identically to the
        replicated path (the pin the mode ships under)."""
        records = []
        row_contribs = []
        for slot, base, delta in contribs:
            age = max(0, commit_window - int(base))
            records.append({"slot": int(slot), "base": int(base),
                            "age": int(age),
                            "weight": round(
                                self.weight(self.decay, age), 6)})
            leaf_deltas = {}
            for name, vals in delta.items():
                if name.endswith(ROWS_SUFFIX):
                    continue
                vals = np.asarray(vals, np.float32)
                rows = delta.get(f"{name}{ROWS_SUFFIX}")
                if rows is None:
                    rows = np.arange(
                        vals.shape[0] if vals.ndim else 1,
                        dtype=np.int64)
                    vals = vals.reshape((rows.shape[0],)
                                        + vals.shape[1:])
                leaf_deltas[name] = (np.asarray(rows, np.int64),
                                     vals, int(base))
            row_contribs.append((int(slot), leaf_deltas))
        self.store.merge_rows(commit_window, row_contribs)
        with self._version_lock:
            self.version = max(self.version, commit_window + 1)
        self.record_history(commit_window + 1)
        return records

    # --------------------------------------------- version history

    def record_history(self, version: int) -> None:
        """Snapshot the center as ``center@version`` into the bounded
        ring (no-op at depth 0); pruned oldest-first."""
        if self.history_depth <= 0:
            return
        self.history[int(version)] = self.snapshot()
        while len(self.history) > self.history_depth:
            del self.history[min(self.history)]

    def delta_since(self, have: int, version: int) -> dict | None:
        """``{name: center@version − center@have}`` leafwise, or
        ``None`` when either endpoint fell out of the ring (the
        caller falls back to a dense snapshot — the resume/rejoin
        path)."""
        a = self.history.get(int(have))
        b = self.history.get(int(version))
        if a is None or b is None:
            return None
        return {name: (np.asarray(b[name], np.float32)
                       - np.asarray(a[name], np.float32))
                for name in b}

    def snapshot(self) -> dict:
        """The assembled center (copies, consistent per shard)."""
        if self.mode == "rowstore":
            return self.store.snapshot()
        parts = []
        for shard in self.shards:
            with shard.lock:
                parts.append({k: v.copy()
                              for k, v in shard.leaves.items()})
        return join_center(parts)
