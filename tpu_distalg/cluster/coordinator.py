"""Coordinator process — rendezvous, clock, failure detection, commits.

The multi-process replacement for PySpark's driver↔executor process
model (the one reference layer PR 9 left in-process): one coordinator
owns generation-numbered MEMBERSHIP (``parallel/membership.py``'s
epoch semantics over the wire — every join/leave bumps the generation
and is recorded as a ``membership_epoch`` event), the cross-process
SSP CLOCK (the ``version`` counter: windows merged into the center —
``parallel/ssp.py``'s clock vector collapsed to the one number the
PS tier measures staleness against), HEARTBEAT failure detection
(``telemetry/heartbeat.py`` threads on the worker side, an age scan
here; a ``kill -9`` is seen even sooner as the connection's EOF), and
DURABLE center checkpoints (``utils/checkpoint.py`` — CRC footer,
atomic rename, quarantine fallback on resume).

Determinism contract (the acceptance the chaos/replay tests pin):
window ``w`` COMMITS only when every active admitted worker has
delivered a push or announced a skip for ``w`` — and because workers
pre-announce schedule-driven skips at window START, a straggler never
stalls a commit (its interference overlaps the peers' windows; its
delta arrives later, staler, weighted ``decay**age`` by the PS).
Contributions apply in SLOT order, never arrival order, and a push's
reply (the pull: the post-commit center) is deferred until its window
commits — so the merge sequence, the applied weights, and the
membership transitions are a pure function of the seeded fault plan,
and the same plan replays to the identical event sequence. What stays
timing-dependent is only WALL CLOCK (and the window at which an
unsolicited late joiner is admitted — the local launcher pins that
with an admission hold when replay equality matters).

A worker's death (EOF or heartbeat-timeout) removes it from the
expected set of the commit that was waiting on it, so training
CONTINUES at reduced quorum; a fresh worker joins by pulling the
center — no restart-budget burn, no resume-renegotiation round trip.
``policy='restart'`` is the measured BSP-baseline alternative: any
death aborts the run (checkpoint saved) for the launcher to respawn
everything — the gang-scheduled world the elastic runtime replaces.
"""

from __future__ import annotations

import dataclasses
import socket
import threading
import time

import numpy as np

from tpu_distalg.cluster import ps as psmod
from tpu_distalg.cluster import transport
from tpu_distalg.parallel import membership
from tpu_distalg.parallel.ssp import (
    DEFAULT_DECAY,
    DEFAULT_STALENESS,
)
from tpu_distalg.telemetry import events as tevents

#: how often the accept loop wakes to scan for stale heartbeats
POLL_SECONDS = 0.05
#: default worker-silence deadline before a slot is declared dead
DEFAULT_HEARTBEAT_TIMEOUT = 5.0

FREE, ACTIVE, DEAD = "free", "active", "dead"


@dataclasses.dataclass
class TrainTask:
    """The training job the coordinator OWNS and hands every worker at
    join (a worker needs only the coordinator's address): the synthetic
    two-class task of bench.comm_comparison_task's shape, sliced into
    per-slot contiguous row blocks."""

    algo: str = "ssgd"            # 'ssgd' | 'local_sgd'
    n_rows: int = 4096
    test_rows: int = 1024
    n_features: int = 30
    data_seed: int = 0
    seed: int = 42                # sampling seed base (per-slot stride)
    eta: float = 0.1
    mini_batch_fraction: float = 0.1
    lam: float = 0.0
    reg_type: str = "l2"

    def as_meta(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ClusterConfig:
    n_slots: int = 3
    n_windows: int = 24
    staleness: int = DEFAULT_STALENESS      # ticks per window AND bound
    decay: float = DEFAULT_DECAY
    ps_shards: int = 2
    table: str = "lr"                       # PS placement rule table
    host: str = "127.0.0.1"
    port: int = 0                           # 0 = ephemeral
    heartbeat_timeout: float = DEFAULT_HEARTBEAT_TIMEOUT
    heartbeat_interval: float = 0.5
    rpc_deadline: float = 30.0
    checkpoint_dir: str | None = None
    checkpoint_every: int = 8               # windows between center saves
    policy: str = "elastic"                 # 'elastic' | 'restart'
    plan_spec: str | None = None            # fault plan handed to workers
    train: TrainTask = dataclasses.field(default_factory=TrainTask)

    def __post_init__(self):
        if self.n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {self.n_slots}")
        if self.staleness < 1:
            raise ValueError(
                f"staleness must be >= 1, got {self.staleness}")
        if self.policy not in ("elastic", "restart"):
            raise ValueError(
                f"unknown policy {self.policy!r}: 'elastic' (continue "
                f"at reduced quorum) or 'restart' (the BSP gang-"
                f"scheduled baseline: any death aborts for a full "
                f"respawn from the checkpoint)")


@dataclasses.dataclass
class SlotState:
    status: str = FREE
    admit: int = 0                   # first window this worker owns
    incarnation: int = 0             # fencing token: which JOIN owns
    #                                  this slot (a zombie's frames
    #                                  must never act on a replacement)
    last_beat: float = 0.0
    pushes: dict = dataclasses.field(default_factory=dict)
    skips: set = dataclasses.field(default_factory=set)
    delivered: int = -1              # newest window pushed or skipped
    stats: dict = dataclasses.field(default_factory=dict)


def init_center(task: TrainTask) -> dict:
    """The step-0 center — zero weights over the biased feature width
    (the SGD family's convention for this task)."""
    return {"w": np.zeros((task.n_features + 1,), np.float32)}


def center_accuracy(center: dict, task: TrainTask) -> float:
    """Test accuracy of the center on the task's held-out tail —
    numpy-only, so the coordinator can report convergence without a
    device."""
    from tpu_distalg.utils import datasets

    X, y = datasets.synthetic_two_class(
        task.n_rows + task.test_rows, task.n_features,
        seed=task.data_seed)
    X = datasets.add_bias_column(X)
    X_te, y_te = X[task.n_rows:], y[task.n_rows:]
    z = X_te @ np.asarray(center["w"], np.float32)
    return float(np.mean((z > 0).astype(np.float32) == y_te))


class ClusterAborted(RuntimeError):
    """The run ended without completing (restart policy fired, or the
    caller stopped it)."""


class Coordinator:
    """``start()`` binds and serves on daemon threads; ``wait()``
    blocks to the result. One lock + condition guard all state; the
    commit loop runs inside whichever handler completes a window."""

    def __init__(self, config: ClusterConfig):
        self.cfg = config
        self.task = config.train
        self.ps = psmod.ParameterServer(
            init_center(self.task), table=config.table,
            n_shards=config.ps_shards, decay=config.decay)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.slots = {i: SlotState() for i in range(config.n_slots)}
        self.version = 0              # windows merged (the SSP clock)
        self.gen = 0                  # membership generation
        self.done = False
        self.aborted: str | None = None
        self.events: list[tuple] = []
        self.hold_at: dict[int, int] = {}   # window -> required actives
        self.worker_stats: dict[int, dict] = {}
        self._next_incarnation = 1
        self._threads: list[threading.Thread] = []
        self._listener: socket.socket | None = None
        self._stop = threading.Event()
        self._tag = (f"cluster:{self.task.algo}:ssp:"
                     f"{config.staleness}:{config.decay:g}")
        self.port: int | None = None
        self._maybe_resume()

    # ------------------------------------------------------ lifecycle

    def _maybe_resume(self) -> None:
        from tpu_distalg.utils import checkpoint as ckpt

        if not self.cfg.checkpoint_dir:
            return
        restored = ckpt.restore_newest_with_fallback(
            self.cfg.checkpoint_dir)
        if restored is None:
            return
        payload, step = restored
        saved_tag = ckpt.decode_tag(payload, self._tag)
        if saved_tag != self._tag or "center" not in payload:
            raise ValueError(
                f"checkpoint in {self.cfg.checkpoint_dir} holds "
                f"workload {saved_tag!r}, this cluster is "
                f"{self._tag!r} — use a fresh directory")
        center = {k: np.asarray(v)
                  for k, v in payload["center"].items()}
        self.ps = psmod.ParameterServer(
            center, table=self.cfg.table,
            n_shards=self.cfg.ps_shards, decay=self.cfg.decay)
        self.version = int(step)
        self.ps.version = self.version
        tevents.emit("cluster_resume", version=self.version)

    def start(self) -> "Coordinator":
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((self.cfg.host, self.cfg.port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        t = threading.Thread(target=self._accept_loop,
                             name="tda-cluster-accept", daemon=True)
        t.start()
        self._threads.append(t)
        tevents.emit("cluster_start", port=self.port,
                     n_slots=self.cfg.n_slots,
                     n_windows=self.cfg.n_windows,
                     resume_version=self.version)
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def wait(self, timeout: float | None = None) -> dict:
        """Block until done/aborted; returns the result dict. Raises
        :class:`ClusterAborted` under the restart policy's abort (the
        launcher catches it and respawns), and ``TimeoutError`` when
        ``timeout`` expires first (the run keeps going)."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cond:
            while not self.done and self.aborted is None:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"cluster run still at window {self.version}/"
                        f"{self.cfg.n_windows} after {timeout}s")
                self._cond.wait(timeout=0.2 if remaining is None
                                else min(0.2, remaining))
        if self.aborted is not None:
            raise ClusterAborted(self.aborted)
        return self.result()

    def result(self) -> dict:
        with self._lock:
            center = self.ps.snapshot()
            return {
                "center": center,
                "version": self.version,
                "gen": self.gen,
                "events": list(self.events),
                "merge_sequence": self.merge_sequence(),
                "membership_sequence": self.membership_sequence(),
                "accuracy": center_accuracy(center, self.task),
                "worker_stats": dict(self.worker_stats),
            }

    def hold_admission(self, window: int, n_active: int) -> None:
        """Pin the admission of a (re)joining worker to a WINDOW: the
        commit of ``window`` waits until ``n_active`` workers are
        active. This is how the local launcher makes a rejoin land at
        a plan-determined position in the event sequence (an
        unsolicited late join is otherwise admitted at whatever window
        the cluster happens to be at)."""
        with self._cond:
            self.hold_at[int(window)] = int(n_active)
            self._cond.notify_all()

    # ------------------------------------------------- event recording

    def merge_sequence(self) -> list:
        """The commit trace: ``(window, ((slot, age), ...), (skipped
        slots...))`` per merge, in commit order — what the replay
        acceptance compares bit-for-bit. Caller may hold the lock."""
        return [e[1:] for e in self.events if e[0] == "merge"]

    def membership_sequence(self) -> list:
        """``(kind, slot, window)`` SORTED — concurrent connects make
        same-window join ORDER (and so the generation numbers)
        scheduler-dependent, so the comparable sequence projects the
        plan-determined fields and is order-free within a window."""
        return sorted((e[0], e[1], e[2]) for e in self.events
                      if e[0] in ("join", "leave"))

    def _emit_membership(self, reason: str, prev_active: int) -> None:
        active = tuple(self.slots[i].status == ACTIVE
                       for i in sorted(self.slots))
        membership.emit_epoch_event(
            membership.Epoch(gen=self.gen, start=self.version,
                             end=self.cfg.n_windows, active=active),
            reason=reason, prev_active=prev_active)
        tevents.counter("cluster.membership_epochs")

    # ------------------------------------------------------ accept/IO

    def _accept_loop(self) -> None:
        self._listener.settimeout(POLL_SECONDS)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                self._scan_heartbeats()
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # daemon handlers, deliberately untracked: a long-lived
            # coordinator accepts one connection per join/heartbeat/
            # rejoin forever, and an ever-growing thread list would be
            # a slow leak (stop() ends them via the stop event/EOF)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="tda-cluster-conn", daemon=True).start()

    def _scan_heartbeats(self) -> None:
        """Declare slots whose last frame is older than the timeout
        dead — the partition/hang detector (EOF catches clean deaths
        faster, in the connection handler)."""
        now = time.monotonic()
        with self._lock:
            stale = [i for i, st in self.slots.items()
                     if st.status == ACTIVE and st.last_beat > 0
                     and now - st.last_beat
                     > self.cfg.heartbeat_timeout]
            for slot in stale:
                self._death(slot, "heartbeat timeout")

    def _serve_conn(self, conn: socket.socket) -> None:
        """One connection's request loop. A worker's MAIN connection
        binds to its slot AND its join incarnation; EOF on it is that
        incarnation's death — never its replacement's (a zombie conn
        outliving a heartbeat-timeout death must not kill the fresh
        worker now holding the slot). Heartbeat connections never
        join, so they never bind and their EOF is inert."""
        bound_slot: int | None = None
        bound_inc: int | None = None
        try:
            while not self._stop.is_set():
                try:
                    kind, meta, arrays = transport.recv_frame(
                        conn, deadline=max(
                            self.cfg.rpc_deadline,
                            4 * self.cfg.heartbeat_timeout))
                except transport.TransportTimeout:
                    continue  # idle connection; liveness rides beats
                reply = self._handle(kind, meta, arrays, conn)
                if kind == "join" and "slot" in reply[1]:
                    bound_slot = int(reply[1]["slot"])
                    bound_inc = int(reply[1]["incarnation"])
                transport.send_frame(
                    conn, *reply, deadline=self.cfg.rpc_deadline)
                if kind == "bye":
                    break
        except transport.TransportClosed:
            pass
        except transport.TransportError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if bound_slot is not None:
                with self._lock:
                    st = self.slots.get(bound_slot)
                    if st is not None and st.status == ACTIVE \
                            and st.incarnation == bound_inc:
                        self._death(bound_slot, "connection lost")

    # ------------------------------------------------------- handlers

    def _fenced(self, meta) -> SlotState | None:
        """Lock held. The slot state a frame may act on: ACTIVE and,
        when the frame carries an incarnation token (every frame a
        welcomed worker sends), the SAME incarnation — a partitioned
        zombie's late frames must neither feed the replacement's push
        state nor keep its heartbeat fresh."""
        slot = meta.get("slot")
        if slot is None:
            return None
        st = self.slots.get(int(slot))
        if st is None or st.status != ACTIVE:
            return None
        inc = meta.get("inc")
        if inc is not None and int(inc) != st.incarnation:
            return None
        return st

    def _handle(self, kind, meta, arrays, conn):
        """Dispatch one frame -> ``(kind, meta, arrays)`` reply."""
        with self._lock:
            st = self._fenced(meta)
            if st is not None:
                st.last_beat = time.monotonic()
        if kind == "join":
            return self._handle_join(meta)
        if kind == "push":
            return self._handle_push(meta, arrays)
        if kind == "skip":
            return self._handle_skip(meta)
        if kind in ("poll", "beat", "hb"):
            with self._lock:
                return ("ok", self._status_meta(), {})
        if kind == "pull":
            with self._lock:
                return ("center", self._status_meta(),
                        self.ps.snapshot())
        if kind == "bye":
            return self._handle_bye(meta)
        return ("error", {"error": f"unknown frame kind {kind!r}"}, {})

    def _status_meta(self) -> dict:
        return {"version": self.version, "gen": self.gen,
                "done": self.done,
                "restart": self.aborted is not None}

    def _handle_join(self, meta) -> tuple:
        want = meta.get("slot")
        with self._lock:
            slot = None
            if want is not None and int(want) in self.slots and \
                    self.slots[int(want)].status != ACTIVE:
                slot = int(want)
            else:
                for i in sorted(self.slots):
                    if self.slots[i].status != ACTIVE:
                        slot = i
                        break
            if slot is None:
                return ("error", {
                    "error": f"all {self.cfg.n_slots} slots active — "
                             f"grow --workers to admit more"}, {})
            prev_active = sum(s.status == ACTIVE
                              for s in self.slots.values())
            # a launcher-pinned admission window makes the rejoin's
            # position in the event sequence plan-determined; an
            # unsolicited join starts at the first uncommitted window
            admit = max(self.version,
                        int(meta.get("admit_at") or self.version))
            admit = min(admit, max(0, self.cfg.n_windows - 1))
            inc = self._next_incarnation
            self._next_incarnation += 1
            st = self.slots[slot] = SlotState(
                status=ACTIVE, admit=admit, incarnation=inc,
                last_beat=time.monotonic(),
                delivered=admit - 1)
            self.gen += 1
            self.events.append(("join", slot, admit, self.gen))
            tevents.emit("cluster_join", slot=slot, gen=self.gen,
                         window=admit)
            tevents.counter("cluster.joins")
            self._emit_membership(
                "rejoin" if meta.get("rejoin") else "join",
                prev_active)
            self._try_commit()
            welcome = {
                "slot": slot, "gen": self.gen,
                "version": self.version,
                "admit": st.admit,
                "incarnation": st.incarnation,
                "n_slots": self.cfg.n_slots,
                "n_windows": self.cfg.n_windows,
                "s": self.cfg.staleness,
                "decay": self.cfg.decay,
                "heartbeat_interval": self.cfg.heartbeat_interval,
                "heartbeat_timeout": self.cfg.heartbeat_timeout,
                "rpc_deadline": self.cfg.rpc_deadline,
                "plan": self.cfg.plan_spec,
                "train": self.task.as_meta(),
                "done": self.done,
            }
            return ("welcome", welcome, self.ps.snapshot())

    def _handle_skip(self, meta) -> tuple:
        window = int(meta["window"])
        with self._lock:
            st = self._fenced(meta)
            if st is None:
                return ("error", {"error": "stale slot"}, {})
            st.skips.add(window)
            st.delivered = max(st.delivered, window)
            # (no cluster.skips bump here: the WORKER owns that
            # counter — in thread mode both sides share one sink and
            # the merged report would double-count; the server-side
            # story is cluster.skipped_deliveries at commit time)
            self._try_commit()
            return ("ok", self._status_meta(), {})

    def _handle_push(self, meta, arrays) -> tuple:
        window = int(meta["window"])
        base = int(meta["base"])
        with self._cond:
            st = self._fenced(meta)
            if st is None:
                return ("error", {"error": "stale slot"}, {})
            st.pushes[window] = (base, dict(arrays))
            st.delivered = max(st.delivered, window)
            # (no cluster.pushes bump: the worker owns it — see skip)
            self._try_commit()
            # the DEFERRED ack: reply once this window has merged —
            # the pull piggybacks the post-commit center, and the
            # worker's next base version is plan-determined instead of
            # arrival-order-determined (the determinism contract)
            while (self.version <= window and not self.done
                   and self.aborted is None
                   and self._fenced(meta) is st
                   and not self._stop.is_set()):
                self._cond.wait(timeout=0.2)
            if self._fenced(meta) is not st:
                return ("error", {"error": "declared dead while "
                                           "awaiting commit"}, {})
            return ("center", self._status_meta(), self.ps.snapshot())

    def _handle_bye(self, meta) -> tuple:
        slot = int(meta["slot"])
        with self._lock:
            st = self._fenced(meta)
            if st is not None:
                self.worker_stats[slot] = dict(meta.get("stats") or {})
                self._record_worker_counters(slot)
                if self.done or st.delivered >= self.cfg.n_windows - 1:
                    # graceful departure: end-of-run, or a worker that
                    # already delivered (pushed or skipped) everything
                    # it owes and finished its last window before the
                    # peers' final pushes commit — a DEATH here would
                    # make the membership sequence race wall clock,
                    # and under the restart policy would abort a
                    # healthy completing run
                    st.status = FREE
                    self._try_commit()
                    self._cond.notify_all()
                else:
                    self._death(slot, "graceful leave")
            return ("ok", self._status_meta(), {})

    def _record_worker_counters(self, slot: int) -> None:
        stats = self.worker_stats.get(slot) or {}
        ms = stats.get("push_pull_ms_total")
        n = stats.get("pushes")
        if ms is not None:
            tevents.counter("cluster.push_pull_ms",
                            int(round(float(ms))))
        if n:
            tevents.counter("cluster.worker_pushes", int(n))

    # ------------------------------------------------ death & commits

    def _death(self, slot: int, reason: str) -> None:
        """Lock held. Membership leave + generation bump; the commit
        that was blocked on this worker proceeds without it."""
        st = self.slots[slot]
        if st.status != ACTIVE:
            return
        prev_active = sum(s.status == ACTIVE
                          for s in self.slots.values())
        st.status = DEAD
        self.gen += 1
        self.events.append(
            ("leave", slot, max(st.delivered, st.admit - 1) + 1,
             self.gen, reason))
        tevents.emit("cluster_leave", slot=slot, gen=self.gen,
                     reason=reason, delivered=st.delivered)
        tevents.counter("cluster.leaves")
        self._emit_membership(f"leave:{reason}", prev_active)
        if self.cfg.policy == "restart" and not self.done:
            self._abort(f"worker {slot} died ({reason}); restart "
                        f"policy aborts for a full respawn")
            return
        self._try_commit()
        self._cond.notify_all()

    def _abort(self, reason: str) -> None:
        """Lock held. The restart-policy exit. Deliberately NO
        checkpoint here: the gang-scheduled baseline restarts from the
        last PERIODIC save and re-pays every window since — exactly
        the progress loss the elastic policy exists to avoid (an
        abort-time save would quietly gift the baseline lossless
        restarts and flatter the measured speedup's denominator)."""
        self.aborted = reason
        tevents.emit("cluster_abort", reason=reason,
                     version=self.version)
        self._cond.notify_all()

    def _expected(self, window: int) -> list[int]:
        return [i for i in sorted(self.slots)
                if self.slots[i].status == ACTIVE
                and self.slots[i].admit <= window]

    def _try_commit(self) -> None:
        """Lock held. Drain every committable window: all expected
        workers have pushed-or-skipped it (and any admission hold is
        satisfied); apply pushes in slot order; bump the clock."""
        while self.version < self.cfg.n_windows and not self.done \
                and self.aborted is None:
            w = self.version
            need = self.hold_at.get(w)
            expected = self._expected(w)
            if need is not None and len(expected) < need:
                return                       # admission hold
            if not expected:
                return                       # quorumless: wait for a join
            if any(w not in self.slots[i].pushes
                   and w not in self.slots[i].skips
                   for i in expected):
                return
            contribs = []
            skipped = []
            for i in sorted(self.slots):     # dead workers' buffered
                st = self.slots[i]           # pushes still count: they
                if w in st.pushes:           # delivered before dying
                    base, delta = st.pushes.pop(w)
                    contribs.append((i, base, delta))
                elif w in st.skips:
                    st.skips.discard(w)
                    skipped.append(i)
            records = self.ps.merge(w, contribs)
            self.version = w + 1
            self.events.append((
                "merge", w,
                tuple((r["slot"], r["age"]) for r in records),
                tuple(skipped)))
            tevents.emit("cluster_merge", window=w,
                         applied=records, skipped=skipped,
                         n_active=len(expected))
            tevents.counter("cluster.merges")
            tevents.counter("cluster.deliveries", len(records))
            tevents.counter("cluster.skipped_deliveries",
                            len(skipped))
            if records:
                tevents.gauge(
                    "cluster.max_staleness",
                    max(r["age"] for r in records))
            self._checkpoint()
            if self.version >= self.cfg.n_windows:
                self.done = True
                self._checkpoint(force=True)
                tevents.emit("cluster_done", version=self.version,
                             gen=self.gen)
            self._cond.notify_all()

    def _checkpoint(self, force: bool = False) -> None:
        """Lock held. Durable center save through the shared
        checkpoint machinery (CRC footer, atomic rename, prune)."""
        if not self.cfg.checkpoint_dir:
            return
        if not force and (self.version == 0
                          or self.version % self.cfg.checkpoint_every):
            return
        from tpu_distalg.utils import checkpoint as ckpt

        ckpt.save(self.cfg.checkpoint_dir,
                  {"tag": ckpt.encode_tag(self._tag),
                   "center": self.ps.snapshot()},
                  step=self.version)
        ckpt.prune(self.cfg.checkpoint_dir, keep=3)
        tevents.emit("checkpoint_saved", step=self.version,
                     tag=self._tag)
        tevents.counter("checkpoints_saved")
