"""Coordinator process — rendezvous, clock, failure detection, commits.

The multi-process replacement for PySpark's driver↔executor process
model (the one reference layer PR 9 left in-process): one coordinator
owns generation-numbered MEMBERSHIP (``parallel/membership.py``'s
epoch semantics over the wire — every join/leave bumps the generation
and is recorded as a ``membership_epoch`` event), the cross-process
SSP CLOCK (the ``version`` counter: windows merged into the center —
``parallel/ssp.py``'s clock vector collapsed to the one number the
PS tier measures staleness against), HEARTBEAT failure detection
(``telemetry/heartbeat.py`` threads on the worker side, an age scan
here; a ``kill -9`` is seen even sooner as the connection's EOF), and
DURABLE center checkpoints (``utils/checkpoint.py`` — CRC footer,
atomic rename, quarantine fallback on resume).

Determinism contract (the acceptance the chaos/replay tests pin):
window ``w`` COMMITS only when every active admitted worker has
delivered a push or announced a skip for ``w`` — and because workers
pre-announce schedule-driven skips at window START, a straggler never
stalls a commit (its interference overlaps the peers' windows; its
delta arrives later, staler, weighted ``decay**age`` by the PS).
Contributions apply in SLOT order, never arrival order, and a push's
reply (the pull: the post-commit center) is deferred until its window
commits — so the merge sequence, the applied weights, and the
membership transitions are a pure function of the seeded fault plan,
and the same plan replays to the identical event sequence. What stays
timing-dependent is only WALL CLOCK (and the window at which an
unsolicited late joiner is admitted — the local launcher pins that
with an admission hold when replay equality matters).

A worker's death (EOF or heartbeat-timeout) removes it from the
expected set of the commit that was waiting on it, so training
CONTINUES at reduced quorum; a fresh worker joins by pulling the
center — no restart-budget burn, no resume-renegotiation round trip.
``policy='restart'`` is the measured BSP-baseline alternative: any
death aborts the run (checkpoint saved) for the launcher to respawn
everything — the gang-scheduled world the elastic runtime replaces.

CRASH TOLERANCE (the other half of elasticity — the control plane is
as killable as the data plane): with a ``checkpoint_dir`` every state
transition the replay contract depends on is appended to a durable
write-ahead ledger (``cluster/wal.py``) and fsynced BEFORE the
corresponding ack leaves the socket — admissions and incarnation
grants, announced skips, window commits (slot-ordered contribution
digests + the applied delta bytes), membership leaves, admission
holds. On restart :meth:`Coordinator._maybe_resume` replays the
ledger on top of the newest durable center: membership generation,
the SSP clock, incarnation fencing, and the in-flight window's
partial commit state all reconstruct; a half-committed window (pushes
in RAM, commit record never written) rolls back to its start — and
because push acks are deferred until commit, no worker ever observed
it, so rollback is invisible by construction: the surviving workers
re-present their incarnation tokens (re-admitted WITHOUT burning a
membership epoch) and re-push the identical deltas, which the WAL's
commit digests dedupe if the commit did land. The seeded
``cluster:coordinator`` fault point (kinds ``kill``/``hang``, probed
plan-pure by :func:`compile_coordinator_schedule`) makes the
coordinator's own death a replayable chaos input — same plan, same
recovery, bitwise-identical final center and identical merge/
membership event digest vs the undisturbed run.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import socket
import threading
import time

import numpy as np

from tpu_distalg.cluster import ps as psmod
from tpu_distalg.cluster import transport
from tpu_distalg.cluster import wal as walmod
from tpu_distalg.faults import registry as fregistry
from tpu_distalg.parallel import comms as pcomms
from tpu_distalg.parallel import membership
from tpu_distalg.parallel.ssp import (
    DEFAULT_DECAY,
    DEFAULT_STALENESS,
)
from tpu_distalg.telemetry import events as tevents
from tpu_distalg.tune import defaults as tune_defaults

#: how often the accept loop wakes to scan for stale heartbeats
POLL_SECONDS = 0.05
#: default worker-silence deadline before a slot is declared dead
DEFAULT_HEARTBEAT_TIMEOUT = 5.0
#: coordinator-schedule cell code for a kill (hang cells hold seconds)
COORD_KILL = -1.0

PULL_SEED_TAG = pcomms.PULL_SEED_TAG

#: every Nth commit version ships a DENSE version-pinned pull instead
#: of a delta: pull-direction quantization noise has no EF channel
#: (each decoded delta adds independent rounding noise to the
#: worker's cached view — a random walk of stddev ~ sqrt(windows) ·
#: scale), so the periodic refresh bounds the drift at
#: sqrt(REFRESH) · scale instead of letting a long run's workers
#: train against an ever-worse center. Amortized wire cost: 4d/16 =
#: 0.25 bytes/elem/window on top of int8's ~1 — the reduction claim
#: survives. A pure function of cv, so replays are unaffected. The
#: default cadence lives in the tuner's geometry table
#: (``tune/defaults.py``); ``ClusterConfig.pull_refresh_windows``
#: overrides it per run (the autotuner's resolver re-derives the
#: cadence from the measured wire).
PULL_REFRESH_WINDOWS = tune_defaults.PULL_REFRESH_WINDOWS

FREE, ACTIVE, DEAD = "free", "active", "dead"


class CoordinatorKilled(Exception):
    """Thread-mode stand-in for the coordinator's SIGKILL (the real
    coordinator process never raises this — it is gone)."""


def compile_coordinator_schedule(n_windows: int, *,
                                 plan=None) -> np.ndarray:
    """The (n_windows,) float64 coordinator fault schedule from the
    plan's ``cluster:coordinator`` rules: cell == -1 = kill (the
    coordinator SIGKILLs itself at that window's commit point — pushes
    buffered in RAM, commit record not yet durable: the rollback path),
    cell > 0 = hang that many seconds there. One probe per window
    against a FRESH quiet registry (a pure function of the plan, like
    the worker/SSP compilers); fires mirror into the live ledger
    exactly once."""
    live = fregistry.active()
    if plan is None:
        plan = live.plan if live is not None else None
    out = np.zeros((n_windows,), np.float64)
    if plan is None or not any(
            r.point == "cluster:coordinator" for r in plan.rules):
        return out
    reg = fregistry.FaultRegistry(plan, quiet=True)
    for w in range(n_windows):
        hit = reg.probe("cluster:coordinator")
        if hit is None:
            continue
        kind, arg = hit
        if kind == "kill":
            out[w] = COORD_KILL
        else:
            out[w] = float(arg if arg is not None
                           else fregistry.DEFAULT_HANG_SECONDS)
    if live is not None and live.plan == plan:
        live.record(reg.fired)
    return out


def _tupled(x):
    """JSON round-trip repair: the WAL snapshot stores the event list
    through JSON (tuples become lists); the comparable sequences are
    tuples all the way down."""
    if isinstance(x, list):
        return tuple(_tupled(v) for v in x)
    return x


@dataclasses.dataclass
class TrainTask:
    """The training job the coordinator OWNS and hands every worker at
    join (a worker needs only the coordinator's address): the synthetic
    two-class task of bench.comm_comparison_task's shape, sliced into
    per-slot contiguous row blocks."""

    algo: str = "ssgd"            # 'ssgd' | 'local_sgd'
    n_rows: int = 4096
    test_rows: int = 1024
    n_features: int = 30
    data_seed: int = 0
    seed: int = 42                # sampling seed base (per-slot stride)
    eta: float = 0.1
    mini_batch_fraction: float = 0.1
    lam: float = 0.0
    reg_type: str = "l2"

    def as_meta(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ClusterConfig:
    n_slots: int = 3
    n_windows: int = 24
    staleness: int = DEFAULT_STALENESS      # ticks per window AND bound
    decay: float = DEFAULT_DECAY
    ps_shards: int = 2
    table: str = "lr"                       # PS placement rule table
    host: str = "127.0.0.1"
    port: int = 0                           # 0 = ephemeral
    heartbeat_timeout: float = DEFAULT_HEARTBEAT_TIMEOUT
    heartbeat_interval: float = 0.5
    rpc_deadline: float = 30.0
    #: seconds a bound connection's EOF leaves its slot SUSPECT before
    #: the death fires — the window a reconnecting worker's re-dial
    #: has to race the coordinator's EOF sweep of its dead connection
    #: (a transient transport fault must not burn a membership epoch)
    reconnect_grace: float = 1.0
    checkpoint_dir: str | None = None
    checkpoint_every: int = 8               # windows between center saves
    policy: str = "elastic"                 # 'elastic' | 'restart'
    plan_spec: str | None = None            # fault plan handed to workers
    #: cluster wire schedule — ``dense`` (f32 snapshots/deltas, the
    #: pre-compression trajectories bit-for-bit), ``int8[:seed]``
    #: (seeded stochastic rounding, ~1 byte/elem both directions) or
    #: ``topk[:frac]`` ((value, index) pairs with worker-side error
    #: feedback on pushes; pulls ride the int8 codec — see
    #: ``worker.py``). ``@seq`` disables the async push overlap.
    comm: str = "dense"
    #: PS state layout — ``replicated`` (every shard a row slice of a
    #: center that must fit one host; the verbatim pre-rowstore path,
    #: pinned bitwise) or ``rowstore`` (disjoint row ownership with
    #: per-row versions: pushes carry ``{leaf}.rows`` index arrays and
    #: merge row-wise — see ``cluster/rowstore.py``)
    ps_mode: str = "replicated"
    #: compressed-pull refresh cadence — every Nth commit ships a
    #: dense version-pinned pull (see :data:`PULL_REFRESH_WINDOWS`).
    #: The autotuner's resolver re-derives this from the measured
    #: wire; a pure function of cv either way, so replays and the
    #: bitwise determinism contract are unaffected by the value.
    pull_refresh_windows: int = tune_defaults.PULL_REFRESH_WINDOWS
    #: the rig profile id this config's geometry was resolved from
    #: (``None`` = untuned table defaults) — carried into the welcome
    #: meta so worker logs can name the profile that shaped the run
    tune_profile: str | None = None
    train: TrainTask = dataclasses.field(default_factory=TrainTask)

    def __post_init__(self):
        if self.ps_mode not in psmod.PS_MODES:
            raise ValueError(
                f"unknown ps_mode {self.ps_mode!r}; choose from "
                f"{psmod.PS_MODES}")
        if self.n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {self.n_slots}")
        if self.staleness < 1:
            raise ValueError(
                f"staleness must be >= 1, got {self.staleness}")
        if self.pull_refresh_windows < 1:
            raise ValueError(
                f"pull_refresh_windows must be >= 1, got "
                f"{self.pull_refresh_windows}")
        # parse-validate eagerly: an unknown/deviceless schedule must
        # fail at config time, not in a worker subprocess mid-join
        pcomms.make_host_codec(self.comm)
        if self.policy not in ("elastic", "restart"):
            raise ValueError(
                f"unknown policy {self.policy!r}: 'elastic' (continue "
                f"at reduced quorum) or 'restart' (the BSP gang-"
                f"scheduled baseline: any death aborts for a full "
                f"respawn from the checkpoint)")


@dataclasses.dataclass
class SlotState:
    status: str = FREE
    admit: int = 0                   # first window this worker owns
    incarnation: int = 0             # fencing token: which JOIN owns
    #                                  this slot (a zombie's frames
    #                                  must never act on a replacement)
    last_beat: float = 0.0
    pushes: dict = dataclasses.field(default_factory=dict)
    skips: set = dataclasses.field(default_factory=set)
    delivered: int = -1              # newest window pushed or skipped
    stats: dict = dataclasses.field(default_factory=dict)
    conn_serial: int = 0             # which CONNECTION owns the
    #                                  incarnation: a resume-join bumps
    #                                  it, so the dead predecessor
    #                                  connection's EOF is inert
    suspect_at: float | None = None  # EOF seen; death after the
    #                                  reconnect grace unless a fenced
    #                                  frame lands first


def init_center(task: TrainTask) -> dict:
    """The step-0 center — zero weights over the biased feature width
    (the SGD family's convention for this task)."""
    return {"w": np.zeros((task.n_features + 1,), np.float32)}


def center_accuracy(center: dict, task: TrainTask) -> float:
    """Test accuracy of the center on the task's held-out tail —
    numpy-only, so the coordinator can report convergence without a
    device."""
    from tpu_distalg.utils import datasets

    X, y = datasets.synthetic_two_class(
        task.n_rows + task.test_rows, task.n_features,
        seed=task.data_seed)
    X = datasets.add_bias_column(X)
    X_te, y_te = X[task.n_rows:], y[task.n_rows:]
    z = X_te @ np.asarray(center["w"], np.float32)
    return float(np.mean((z > 0).astype(np.float32) == y_te))


class ClusterAborted(RuntimeError):
    """The run ended without completing (restart policy fired, or the
    caller stopped it)."""


class Coordinator:
    """``start()`` binds and serves on daemon threads; ``wait()``
    blocks to the result. One lock + condition guard all state; the
    commit loop runs inside whichever handler completes a window."""

    def __init__(self, config: ClusterConfig, *, die=None):
        self.cfg = config
        self.task = config.train
        # the cluster wire codec (None = dense, the verbatim legacy
        # path) + the model's known center layout for exact decode;
        # compressed modes keep a bounded center-version history in
        # the PS for version-delta pulls (deep enough that any base
        # the SSP gate admits — plus the async push's one-window lag
        # — still resolves to a delta instead of a dense fallback)
        self._codec = pcomms.make_host_codec(config.comm)
        self._pull_codec = pcomms.make_host_pull_codec(config.comm)
        self._center_template = init_center(config.train)
        self._history_depth = (0 if self._codec is None
                               else 2 * config.staleness + 8)
        self.ps = psmod.ParameterServer(
            init_center(self.task), table=config.table,
            n_shards=config.ps_shards, decay=config.decay,
            history_depth=self._history_depth,
            mode=config.ps_mode)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.slots = {i: SlotState() for i in range(config.n_slots)}
        self.version = 0              # windows merged (the SSP clock)
        self.gen = 0                  # membership generation
        self.done = False
        self.aborted: str | None = None
        self.killed = False           # thread-mode SIGKILL stand-in
        self.recovered = False        # this incarnation replayed a WAL
        self.wal_records_replayed = 0
        self.first_recommit_at: float | None = None  # monotonic time
        #                               of the first commit AFTER a
        #                               recovery — the endpoint of the
        #                               measured detect→recover→
        #                               first-recommitted-window span
        self.events: list[tuple] = []
        self.hold_at: dict[int, int] = {}   # window -> required actives
        self.worker_stats: dict[int, dict] = {}
        self.commit_digests: dict[tuple[int, int], int] = {}
        self._next_incarnation = 1
        self._threads: list[threading.Thread] = []
        self._listener: socket.socket | None = None
        self._conns: set[socket.socket] = set()
        self._stop = threading.Event()
        self._die_fn = die            # thread-mode override (sockets
        #                               slam instead of a real SIGKILL)
        comm_sched = pcomms.CommSpec.parse(config.comm).schedule
        self._tag = (f"cluster:{self.task.algo}:ssp:"
                     f"{config.staleness}:{config.decay:g}"
                     + ("" if comm_sched == "dense"
                        else f":{comm_sched}"))
        self.port: int | None = None
        self.wal: walmod.WriteAheadLog | None = None
        plan = (fregistry.FaultPlan.parse(config.plan_spec)
                if config.plan_spec else None)
        self._coord_sched = compile_coordinator_schedule(
            config.n_windows, plan=plan)
        self._coord_fired: set[int] = set()
        # the PS-shard fault schedule (cluster:ps — the merge seam,
        # AFTER the commit record is durable): compiled by the shared
        # rowstore compiler, one probe per window, same plan-purity
        from tpu_distalg.cluster import rowstore as rowstoremod

        self._ps_sched = rowstoremod.compile_point_schedule(
            "cluster:ps", config.n_windows, plan=plan)[:, 0]
        self._ps_fired: set[int] = set()
        self._maybe_resume()
        # seed the version history at whatever center recovery landed
        # on (replayed commits already recorded theirs inside merge)
        self.ps.record_history(self.version)

    # ------------------------------------------------------ lifecycle

    def _maybe_resume(self) -> None:
        """Durable-state recovery: restore the newest center
        checkpoint, then replay the WAL on top of it — membership
        generation, incarnation fencing, the SSP clock, announced
        skips and every committed-but-not-yet-checkpointed window's
        merge all reconstruct; an in-flight window with no commit
        record rolls back to its start (invisible: its acks never
        left). Torn WAL tails are truncated with a quarantine event
        inside :func:`wal.read_segment`, mirroring checkpoint
        restore."""
        from tpu_distalg.utils import checkpoint as ckpt

        if not self.cfg.checkpoint_dir:
            return
        wal_dir = os.path.join(self.cfg.checkpoint_dir, "wal")
        restored = ckpt.restore_newest_with_fallback(
            self.cfg.checkpoint_dir)
        if restored is not None:
            payload, step = restored
            saved_tag = ckpt.decode_tag(payload, self._tag)
            if saved_tag != self._tag or "center" not in payload:
                raise ValueError(
                    f"checkpoint in {self.cfg.checkpoint_dir} holds "
                    f"workload {saved_tag!r}, this cluster is "
                    f"{self._tag!r} — use a fresh directory")
            center = {k: np.asarray(v)
                      for k, v in payload["center"].items()}
            self.ps = psmod.ParameterServer(
                center, table=self.cfg.table,
                n_shards=self.cfg.ps_shards, decay=self.cfg.decay,
                history_depth=self._history_depth,
                mode=self.cfg.ps_mode)
            self.version = int(step)
            self.ps.version = self.version
            # the restored base enters the version history BEFORE the
            # WAL replay merges on top: a re-pushed window whose
            # original ack diffed against this base must re-serve the
            # identical delta bytes, not a dense fallback
            self.ps.record_history(self.version)
        if self.cfg.policy == "restart":
            # the gang-scheduled BASELINE deliberately has no WAL:
            # it restarts from the last PERIODIC save and re-pays
            # every window since — replaying a ledger here would (a)
            # quietly gift the baseline lossless restarts and flatter
            # the measured elastic speedup's denominator, and (b)
            # resurrect the aborted incarnations' slot state, whose
            # inevitable heartbeat deaths would re-trigger the abort
            # in a loop
            if restored is not None:
                tevents.emit("cluster_resume", version=self.version)
            return
        records, replay_base = walmod.WriteAheadLog.replay(
            wal_dir, self.version)
        self.wal = walmod.WriteAheadLog(wal_dir)
        if records:
            t0 = time.monotonic()
            n = self._apply_wal_records(records)
            self.recovered = True
            self.wal_records_replayed = n
            # the replayed segment stays the open segment — recovery
            # appends continue it (its snapshot + records already
            # cover everything up to here)
            self.wal.open_segment(
                replay_base if replay_base is not None
                else self.version, self._snapshot_control())
            tevents.emit(
                "cluster_recovered", version=self.version,
                gen=self.gen, records=n, base=replay_base,
                seconds=round(time.monotonic() - t0, 4))
            tevents.counter("cluster.recoveries")
            tevents.counter("cluster.wal_records_replayed", n)
        else:
            self.wal.open_segment(self.version,
                                  self._snapshot_control())
            if restored is not None:
                tevents.emit("cluster_resume", version=self.version)

    # ----------------------------------------------------- WAL plumbing

    def _snapshot_control(self) -> dict:
        """The control-plane snapshot a WAL segment opens with: the
        data plane lives in the center checkpoint, everything else
        (clock, generation, fencing counter, slot table, event
        history, holds, commit digests) lives here — so recovery =
        checkpoint + snapshot + records, in that order."""
        return {
            "version": self.version,
            "gen": self.gen,
            "next_incarnation": self._next_incarnation,
            "done": self.done,
            "events": self.events,
            "hold_at": {str(k): v for k, v in self.hold_at.items()},
            "worker_stats": {str(k): v for k, v
                             in self.worker_stats.items()},
            "commit_digests": [[w, s, d] for (w, s), d
                               in self.commit_digests.items()],
            "slots": {
                # tda: ignore[TDA100] -- last_beat/suspect_at/
                # conn_serial/stats are PER-INCARNATION state and must
                # NOT be resurrected: a recovered slot gets a FRESH
                # liveness clock (see _apply_wal_records), connection
                # ownership dies with the old process's sockets, and
                # worker stats re-ride the bye frames; pushes roll
                # forward from replayed WAL push records instead
                str(i): {"status": st.status, "admit": st.admit,
                         "incarnation": st.incarnation,
                         "delivered": st.delivered,
                         "skips": sorted(st.skips)}
                for i, st in self.slots.items()},
        }

    def _adopt_snapshot(self, snap: dict) -> None:
        """Apply a ``base`` record. ``version`` only moves FORWARD: a
        snapshot older than the restored center (the crash landed
        between a checkpoint and its WAL rotation) must not rewind the
        clock — its commit records re-apply idempotently instead."""
        self.version = max(self.version, int(snap.get("version", 0)))
        self.ps.version = max(self.ps.version, self.version)
        self.gen = int(snap.get("gen", self.gen))
        self._next_incarnation = max(
            self._next_incarnation,
            int(snap.get("next_incarnation", 1)))
        if snap.get("done"):
            self.done = True
        self.events = [_tupled(e) for e in snap.get("events", [])]
        self.hold_at = {int(k): int(v) for k, v
                        in (snap.get("hold_at") or {}).items()}
        self.worker_stats = {int(k): dict(v) for k, v
                             in (snap.get("worker_stats")
                                 or {}).items()}
        self.commit_digests = {
            (int(w), int(s)): int(d)
            for w, s, d in snap.get("commit_digests", [])}
        for k, s in (snap.get("slots") or {}).items():
            self.slots[int(k)] = SlotState(
                status=s["status"], admit=int(s["admit"]),
                incarnation=int(s["incarnation"]),
                delivered=int(s["delivered"]),
                skips=set(int(x) for x in s.get("skips", ())))

    def _apply_wal_records(self, records) -> int:
        """Roll the control state (and any post-checkpoint commits)
        forward through the replayed records; returns the record
        count. Recovered ACTIVE slots get a fresh liveness clock —
        their workers have ``heartbeat_timeout`` seconds to re-present
        their incarnation tokens before the usual elastic death."""
        for kind, meta, arrays in records:
            if kind == "base":
                self._adopt_snapshot(meta)
            elif kind == "admit":
                slot = int(meta["slot"])
                self.slots[slot] = SlotState(
                    status=ACTIVE, admit=int(meta["admit"]),
                    incarnation=int(meta["incarnation"]),
                    delivered=int(meta["admit"]) - 1)
                self.gen = int(meta["gen"])
                self._next_incarnation = max(
                    self._next_incarnation,
                    int(meta["incarnation"]) + 1)
                self.events.append(
                    ("join", slot, int(meta["admit"]), self.gen))
            elif kind == "leave":
                slot = int(meta["slot"])
                st = self.slots.get(slot)
                if st is not None:
                    st.status = DEAD
                self.gen = int(meta["gen"])
                self.events.append(
                    ("leave", slot, int(meta["window"]), self.gen,
                     str(meta.get("reason", ""))))
            elif kind == "skip":
                st = self.slots.get(int(meta["slot"]))
                if st is not None and \
                        st.incarnation == int(meta.get(
                            "inc", st.incarnation)):
                    w = int(meta["window"])
                    st.skips.add(w)
                    st.delivered = max(st.delivered, w)
            elif kind == "hold":
                self.hold_at[int(meta["window"])] = \
                    int(meta["n_active"])
            elif kind == "commit":
                self._replay_commit(meta, arrays)
            elif kind == "bye":
                slot = int(meta["slot"])
                self.worker_stats[slot] = dict(meta.get("stats")
                                               or {})
                st = self.slots.get(slot)
                if st is not None and st.status == ACTIVE:
                    st.status = FREE
            elif kind == "done":
                self.done = True
        now = time.monotonic()
        for st in self.slots.values():
            if st.status == ACTIVE:
                st.last_beat = now
                st.suspect_at = None
        return len(records)

    def _replay_commit(self, meta: dict, arrays: dict) -> None:
        """Re-apply one committed window's redo record: the merge
        event always re-enters the history; the DELTAS re-apply only
        when the window is not already inside the restored center
        (the idempotence that lets an older segment roll forward past
        a quarantined checkpoint)."""
        w = int(meta["window"])
        contribs = []
        for c in meta.get("contribs", ()):
            slot = int(c["slot"])
            self.commit_digests[(w, slot)] = int(c["digest"])
            st = self.slots.get(slot)
            if st is not None:
                st.pushes.pop(w, None)
                st.delivered = max(st.delivered, w)
            prefix = f"{slot}/"
            delta = {k[len(prefix):]: v for k, v in arrays.items()
                     if k.startswith(prefix)}
            contribs.append((slot, int(c["base"]),
                             self._decode_delta(delta)))
        skipped = [int(s) for s in meta.get("skipped", ())]
        for s in skipped:
            st = self.slots.get(s)
            if st is not None:
                st.skips.discard(w)
                st.delivered = max(st.delivered, w)
        if w >= self.version:
            self.ps.merge(w, contribs)
            self.version = w + 1
            self.ps.version = self.version
        self.events.append((
            "merge", w,
            tuple((int(c["slot"]), int(c["age"]))
                  for c in meta.get("contribs", ())),
            tuple(skipped)))

    def _wal_append(self, kind: str, meta: dict,
                    arrays: dict | None = None) -> None:
        """One durable ledger record (no-op without a checkpoint
        dir). Transient disk faults retry through ``supervised`` —
        the same discipline as ``checkpoint.save`` — because an
        un-durable record must never let its ack escape."""
        if self.wal is None or self.killed:
            return
        from tpu_distalg.telemetry.supervisor import supervised

        supervised(lambda: self.wal.append(kind, meta, arrays),
                   phase="cluster:wal", retries=2, backoff=0.05,
                   backoff_cap=0.05, jitter=0.0, retry_on=(OSError,),
                   failure_counter="cluster.wal_write_failures",
                   log=lambda m: None)

    def start(self) -> "Coordinator":
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        for attempt in range(100):
            try:
                self._listener.bind((self.cfg.host, self.cfg.port))
                break
            except OSError:
                # a recovered coordinator re-binds its predecessor's
                # port and can race the dying listener's close (thread
                # mode) or the kernel's release of it — brief patience
                # instead of failing the recovery
                if attempt == 99:
                    raise
                time.sleep(0.05)
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        t = threading.Thread(target=self._accept_loop,
                             name="tda-cluster-accept", daemon=True)
        t.start()
        self._threads.append(t)
        tevents.emit("cluster_start", port=self.port,
                     n_slots=self.cfg.n_slots,
                     n_windows=self.cfg.n_windows,
                     resume_version=self.version)
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        if self.wal is not None:
            self.wal.close()

    def _die(self) -> None:
        """The ``cluster:coordinator`` kill cell. The real coordinator
        SIGKILLs its own process (sockets slam, WAL handle dies with
        it — the genuine article); thread mode runs the injected
        ``die`` hook (slams the listener and every connection for the
        same EOF observable) and unwinds the handler."""
        self.killed = True
        self._stop.set()
        if self.wal is not None:
            self.wal.close()
        if self._die_fn is not None:
            self._die_fn(self)
            self._cond.notify_all()
            raise CoordinatorKilled()
        os.kill(os.getpid(), signal.SIGKILL)

    def slam(self) -> None:
        """Abruptly close the listener and every live connection —
        what a SIGKILL does to the process's sockets; the thread-mode
        ``die`` hook and the tests use it directly."""
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for conn in list(self._conns):
            for fn in (lambda: conn.shutdown(2), conn.close):
                try:
                    fn()
                except OSError:
                    pass

    def wait(self, timeout: float | None = None) -> dict:
        """Block until done/aborted; returns the result dict. Raises
        :class:`ClusterAborted` under the restart policy's abort (the
        launcher catches it and respawns), and ``TimeoutError`` when
        ``timeout`` expires first (the run keeps going)."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cond:
            while not self.done and self.aborted is None:
                remaining = None if deadline is None else \
                    deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"cluster run still at window {self.version}/"
                        f"{self.cfg.n_windows} after {timeout}s")
                self._cond.wait(timeout=0.2 if remaining is None
                                else min(0.2, remaining))
        if self.aborted is not None:
            raise ClusterAborted(self.aborted)
        return self.result()

    def result(self) -> dict:
        with self._lock:
            center = self.ps.snapshot()
            return {
                "center": center,
                "version": self.version,
                "gen": self.gen,
                "events": list(self.events),
                "merge_sequence": self.merge_sequence(),
                "membership_sequence": self.membership_sequence(),
                "accuracy": center_accuracy(center, self.task),
                "worker_stats": dict(self.worker_stats),
                "recovered": self.recovered,
                "wal_records_replayed": self.wal_records_replayed,
            }

    def hold_admission(self, window: int, n_active: int) -> None:
        """Pin the admission of a (re)joining worker to a WINDOW: the
        commit of ``window`` waits until ``n_active`` workers are
        active. This is how the local launcher makes a rejoin land at
        a plan-determined position in the event sequence (an
        unsolicited late join is otherwise admitted at whatever window
        the cluster happens to be at). Durable: a recovered
        coordinator must keep honoring the hold."""
        with self._cond:
            self.hold_at[int(window)] = int(n_active)
            self._wal_append("hold", {"window": int(window),
                                      "n_active": int(n_active)})
            self._cond.notify_all()

    # ------------------------------------------------- event recording

    def merge_sequence(self) -> list:
        """The commit trace: ``(window, ((slot, age), ...), (skipped
        slots...))`` per merge, in commit order — what the replay
        acceptance compares bit-for-bit. Caller may hold the lock."""
        return [e[1:] for e in self.events if e[0] == "merge"]

    def membership_sequence(self) -> list:
        """``(kind, slot, window)`` SORTED — concurrent connects make
        same-window join ORDER (and so the generation numbers)
        scheduler-dependent, so the comparable sequence projects the
        plan-determined fields and is order-free within a window."""
        return sorted((e[0], e[1], e[2]) for e in self.events
                      if e[0] in ("join", "leave"))

    def _emit_membership(self, reason: str, prev_active: int) -> None:
        active = tuple(self.slots[i].status == ACTIVE
                       for i in sorted(self.slots))
        membership.emit_epoch_event(
            membership.Epoch(gen=self.gen, start=self.version,
                             end=self.cfg.n_windows, active=active),
            reason=reason, prev_active=prev_active)
        tevents.counter("cluster.membership_epochs")

    # ------------------------------------------------------ accept/IO

    def _accept_loop(self) -> None:
        self._listener.settimeout(POLL_SECONDS)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                try:
                    self._scan_heartbeats()
                except CoordinatorKilled:
                    break  # a death's commit drain hit a kill cell
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # daemon handlers, deliberately untracked: a long-lived
            # coordinator accepts one connection per join/heartbeat/
            # rejoin forever, and an ever-growing thread list would be
            # a slow leak (stop() ends them via the stop event/EOF)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="tda-cluster-conn", daemon=True).start()

    def _scan_heartbeats(self) -> None:
        """Declare slots dead on silence past the heartbeat timeout
        (the partition/hang detector), or on an unresolved connection
        EOF past the reconnect grace — EOF alone is only SUSPICION,
        because a worker riding out a transient transport fault
        re-dials the same incarnation and must not burn a membership
        epoch racing our sweep of its dead connection."""
        now = time.monotonic()
        with self._lock:
            for slot, st in list(self.slots.items()):
                if st.status != ACTIVE:
                    continue
                if st.last_beat > 0 and now - st.last_beat \
                        > self.cfg.heartbeat_timeout:
                    self._death(slot, "heartbeat timeout")
                elif st.suspect_at is not None and \
                        now - st.suspect_at \
                        > self.cfg.reconnect_grace:
                    self._death(slot, "connection lost")

    def _serve_conn(self, conn: socket.socket) -> None:
        """One connection's request loop. A worker's MAIN connection
        binds to its slot, its join incarnation AND a connection
        serial; EOF on it marks that incarnation SUSPECT (death after
        the reconnect grace) — never its replacement's, and never an
        incarnation that already resumed on a newer connection (the
        serial check: a re-dial superseded this one). Heartbeat
        connections never join, so they never bind and their EOF is
        inert."""
        bound_slot: int | None = None
        bound_inc: int | None = None
        bound_serial: int | None = None
        self._conns.add(conn)
        try:
            while not self._stop.is_set():
                try:
                    kind, meta, arrays = transport.recv_frame(
                        conn, deadline=max(
                            self.cfg.rpc_deadline,
                            4 * self.cfg.heartbeat_timeout))
                except transport.TransportTimeout:
                    continue  # idle connection; liveness rides beats
                reply = self._handle(kind, meta, arrays, conn)
                if kind == "join" and "slot" in reply[1]:
                    bound_slot = int(reply[1]["slot"])
                    bound_inc = int(reply[1]["incarnation"])
                    bound_serial = int(reply[1].get("serial", 0))
                transport.send_frame(
                    conn, *reply, deadline=self.cfg.rpc_deadline)
                if kind == "bye":
                    break
        except transport.TransportClosed:
            pass
        except transport.TransportError:
            pass
        except CoordinatorKilled:
            pass  # thread-mode SIGKILL stand-in: just unwind
        finally:
            self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
            if bound_slot is not None and not self.killed:
                with self._lock:
                    st = self.slots.get(bound_slot)
                    if st is not None and st.status == ACTIVE \
                            and st.incarnation == bound_inc \
                            and st.conn_serial == bound_serial:
                        st.suspect_at = time.monotonic()

    # ------------------------------------------------------- handlers

    def _fenced(self, meta) -> SlotState | None:
        """Lock held. The slot state a frame may act on: ACTIVE and
        carrying the SAME incarnation token (every frame a welcomed
        worker sends has one) — a partitioned zombie's late frames
        must neither feed the replacement's push state nor keep its
        heartbeat fresh, and a REPLACEMENT's pre-welcome join retries
        (slot but no token yet) must not read as liveness for the
        dying incarnation they are waiting to replace (that would
        clear the EOF suspicion forever and wedge the admission)."""
        slot = meta.get("slot")
        if slot is None:
            return None
        st = self.slots.get(int(slot))
        if st is None or st.status != ACTIVE:
            return None
        inc = meta.get("inc")
        if inc is None or int(inc) != st.incarnation:
            return None
        return st

    def _handle(self, kind, meta, arrays, conn):
        """Dispatch one frame -> ``(kind, meta, arrays)`` reply."""
        if self.killed:
            # a dead coordinator goes SILENT, never answers: in the
            # beat between killed=True and the socket slam, an error
            # reply here would escape to a healthy worker and read as
            # a GENUINE rejection (fatal), when the right observable
            # is EOF -> reconnect -> resume on the recovered
            # incarnation
            raise CoordinatorKilled()
        with self._lock:
            st = self._fenced(meta)
            if st is not None:
                st.last_beat = time.monotonic()
                st.suspect_at = None  # a live fenced frame IS liveness
        if kind == "join":
            return self._handle_join(meta)
        if kind == "push":
            return self._handle_push(meta, arrays)
        if kind == "skip":
            return self._handle_skip(meta)
        if kind in ("poll", "beat", "hb"):
            with self._lock:
                return ("ok", self._status_meta(), {})
        if kind == "hold":
            # the launcher's admission pin, over the wire (a
            # subprocess coordinator has no in-process handle)
            self.hold_admission(int(meta["window"]),
                                int(meta["n_active"]))
            with self._lock:
                return ("ok", self._status_meta(), {})
        if kind == "pull":
            with self._lock:
                return ("center", self._status_meta(),
                        self.ps.snapshot())
        if kind == "bye":
            return self._handle_bye(meta)
        return ("error", {"error": f"unknown frame kind {kind!r}"}, {})

    def _status_meta(self) -> dict:
        return {"version": self.version, "gen": self.gen,
                "done": self.done,
                "restart": self.aborted is not None,
                # CLOCK_MONOTONIC is machine-wide on Linux, so a
                # launcher process can subtract its own detect time
                # from this to get the true recovery span (the
                # subprocess-coordinator recovery measurement)
                "recommit_at": self.first_recommit_at}

    def _welcome_meta(self, slot: int, st: SlotState) -> dict:
        return {
            "slot": slot, "gen": self.gen,
            "version": self.version,
            "admit": st.admit,
            "incarnation": st.incarnation,
            "serial": st.conn_serial,
            "n_slots": self.cfg.n_slots,
            "n_windows": self.cfg.n_windows,
            "s": self.cfg.staleness,
            "decay": self.cfg.decay,
            "heartbeat_interval": self.cfg.heartbeat_interval,
            "heartbeat_timeout": self.cfg.heartbeat_timeout,
            "rpc_deadline": self.cfg.rpc_deadline,
            "comm": self.cfg.comm,
            "ps_mode": self.cfg.ps_mode,
            "pull_refresh": self.cfg.pull_refresh_windows,
            "tune_profile": self.cfg.tune_profile,
            "plan": self.cfg.plan_spec,
            "train": self.task.as_meta(),
            "done": self.done,
            "restart": self.aborted is not None,
        }

    def _handle_join(self, meta) -> tuple:
        want = meta.get("slot")
        with self._lock:
            if meta.get("resume") and want is not None:
                # a surviving worker re-attaching after a coordinator
                # recovery or a transient connection loss: it presents
                # the SAME incarnation token, so it is re-admitted
                # WITHOUT burning a membership epoch (no gen bump, no
                # join event — the membership never changed); the new
                # connection supersedes the dead one (serial bump), so
                # the old connection's pending EOF sweep is inert
                st = self.slots.get(int(want))
                if st is not None and st.status == ACTIVE and \
                        st.incarnation == int(meta.get("inc", -1)):
                    st.last_beat = time.monotonic()
                    st.suspect_at = None
                    st.conn_serial += 1
                    tevents.emit("cluster_worker_resume",
                                 slot=int(want),
                                 incarnation=st.incarnation)
                    tevents.counter("cluster.worker_resumes")
                    self._cond.notify_all()
                    welcome = self._welcome_meta(int(want), st)
                    welcome["resume"] = True
                    # NO center payload: a resumed worker keeps its
                    # local state (it re-pushes / re-pulls as its own
                    # loop dictates) — shipping the model here would
                    # tax every reconnect on the recovery hot path
                    # only to be discarded
                    return ("welcome", welcome, {})
                if meta.get("resume_only"):
                    # a best-effort frame's reconnect (the bye): the
                    # incarnation is gone and a FRESH admission would
                    # be a ghost slot nobody drives — commits would
                    # stall on it until the heartbeat timeout and the
                    # spurious join/leave would change the membership
                    # digest of a run that recovered correctly
                    return ("error", {"error": "incarnation gone — "
                                               "resume-only join "
                                               "refused"}, {})
                # fencing moved on (declared dead during the outage):
                # fall through to a fresh admission — the worker
                # resets to the new admission window
            slot = None
            if want is not None and int(want) in self.slots and \
                    self.slots[int(want)].status != ACTIVE:
                slot = int(want)
            else:
                for i in sorted(self.slots):
                    if self.slots[i].status != ACTIVE:
                        slot = i
                        break
            if slot is None:
                return ("error", {
                    "error": f"all {self.cfg.n_slots} slots active — "
                             f"grow --workers to admit more"}, {})
            prev_active = sum(s.status == ACTIVE
                              for s in self.slots.values())
            # a launcher-pinned admission window makes the rejoin's
            # position in the event sequence plan-determined; an
            # unsolicited join starts at the first uncommitted window
            admit = max(self.version,
                        int(meta.get("admit_at") or self.version))
            admit = min(admit, max(0, self.cfg.n_windows - 1))
            inc = self._next_incarnation
            self._next_incarnation += 1
            st = self.slots[slot] = SlotState(
                status=ACTIVE, admit=admit, incarnation=inc,
                last_beat=time.monotonic(),
                delivered=admit - 1)
            self.gen += 1
            self.events.append(("join", slot, admit, self.gen))
            # the admission + incarnation grant go durable BEFORE the
            # welcome leaves: a recovered coordinator must keep
            # fencing the tokens it already handed out
            self._wal_append("admit", {"slot": slot, "admit": admit,
                                       "incarnation": inc,
                                       "gen": self.gen})
            tevents.emit("cluster_join", slot=slot, gen=self.gen,
                         window=admit)
            tevents.counter("cluster.joins")
            self._emit_membership(
                "rejoin" if meta.get("rejoin") else "join",
                prev_active)
            self._try_commit()
            return ("welcome", self._welcome_meta(slot, st),
                    self.ps.snapshot())

    def _handle_skip(self, meta) -> tuple:
        window = int(meta["window"])
        with self._lock:
            st = self._fenced(meta)
            if st is None:
                return ("error", {"error": "stale slot"}, {})
            already = window in st.skips or window <= st.delivered
            st.skips.add(window)
            st.delivered = max(st.delivered, window)
            # the announced skip goes durable BEFORE its ack: the ack
            # releases the worker into its straggle, and a recovered
            # coordinator must still expect the aged delivery instead
            # of stalling the window's commit on a skip nobody will
            # re-announce (a RE-announced skip — the ack was lost to
            # the crash — is deduped here: replay already holds it)
            if not already:
                self._wal_append("skip", {"slot": int(meta["slot"]),
                                          "inc": st.incarnation,
                                          "window": window})
            # (no cluster.skips bump here: the WORKER owns that
            # counter — in thread mode both sides share one sink and
            # the merged report would double-count; the server-side
            # story is cluster.skipped_deliveries at commit time)
            self._try_commit()
            return ("ok", self._status_meta(), {})

    def _decode_delta(self, arrays: dict) -> dict:
        """A pushed contribution's dense reconstruction: identity in
        dense mode; under a wire codec the exact host decode (int8 ->
        int32 widening before the one scale multiply, topk scatter-
        add) against the model's known center layout. The WAL and the
        idempotence digests see the COMPRESSED bytes — this decode is
        a pure function of them, so replay stays bitwise. A rowstore-
        mode push's ``{leaf}.rows`` index arrays ride AROUND the codec
        (they are exact int64 structure, not compressible values, and
        their ``{leaf}.``-prefixed names would otherwise be mistaken
        for codec parts) and re-attach to the decoded delta for the
        PS's row-wise merge."""
        if self._codec is None:
            return arrays
        rows = {k: v for k, v in arrays.items()
                if k.endswith(psmod.ROWS_SUFFIX)}
        vals = {k: v for k, v in arrays.items()
                if not k.endswith(psmod.ROWS_SUFFIX)}
        out = pcomms.decode_tree(self._codec, vals,
                                 self._center_template)
        out.update(rows)
        return out

    def _pull_reply(self, slot: int, window: int, have) -> tuple:
        """Lock held. The deferred push-ack's pull payload for a push
        of ``window`` from ``slot``. Dense mode ships the live center
        snapshot (the pre-compression contract, bit-for-bit). Under a
        wire codec the reply is VERSION-PINNED to the push's own
        commit (``cv = window + 1``) and ships the compressed delta
        ``center@cv − center@have`` (seeded by (slot, have, cv), so a
        recovered coordinator re-serves identical bytes); a ``have``
        outside the PS history falls back to a dense version-pinned
        snapshot — the resume/rejoin path — and every
        :data:`PULL_REFRESH_WINDOWS`-th commit ships dense ON
        SCHEDULE, bounding the pull-noise random walk in the worker's
        cached view."""
        if self._codec is None:
            return ("center", self._status_meta(), self.ps.snapshot())
        cv = window + 1
        refresh = self.cfg.pull_refresh_windows
        if have is not None and int(have) < cv \
                and cv % refresh:
            delta = self.ps.delta_since(int(have), cv)
            if delta is not None:
                arrays, _ = pcomms.encode_tree(
                    self._pull_codec, delta, None,
                    PULL_SEED_TAG, slot, int(have), cv)
                meta = self._status_meta()
                meta.update(mode="delta", cv=cv, have=int(have))
                tevents.counter("cluster.delta_pulls")
                return ("center", meta, arrays)
        meta = self._status_meta()
        meta["mode"] = "dense"
        # pin the fallback to the OLDEST history version >= cv, never
        # the live clock: a peer's concurrent commit (a WAL-replayed
        # skip can release a window this slot never re-delivers) may
        # already have advanced self.version, and an arrival-timed cv
        # would make the worker's next push base — and so the
        # decay^age merge weights — scheduler-dependent, breaking the
        # plan-determined replay contract exactly on the recovery
        # path it exists for
        newer = sorted(v for v in self.ps.history if v >= cv)
        if newer:
            meta["cv"] = newer[0]
            snap = self.ps.history[newer[0]]
        else:   # no history at all (dense-depth 0 cannot reach here)
            meta["cv"] = self.version
            snap = self.ps.snapshot()
        if not cv % refresh:
            tevents.counter("cluster.pull_refreshes")
        else:
            tevents.counter("cluster.pull_dense_fallbacks")
        return ("center", meta, snap)

    def _handle_push(self, meta, arrays) -> tuple:
        window = int(meta["window"])
        base = int(meta["base"])
        with self._cond:
            st = self._fenced(meta)
            if st is None:
                return ("error", {"error": "stale slot"}, {})
            if window < self.version:
                # re-delivery of an ALREADY-COMMITTED window: the
                # commit record went durable but the coordinator died
                # before the deferred ack left, so the worker pushed
                # again after reconnecting. Idempotent by the WAL's
                # commit digest: the same bytes were already merged —
                # ack with the window's own pull reply, apply nothing.
                want = self.commit_digests.get(
                    (window, int(meta["slot"])))
                if want is not None and \
                        want != walmod.delta_digest(arrays):
                    return ("error", {
                        "error": f"non-idempotent re-delivery for "
                                 f"window {window}: delta digest "
                                 f"mismatch vs the committed record "
                                 f"— refusing to double-apply"}, {})
                tevents.counter("cluster.dedup_pushes")
                return self._pull_reply(int(meta["slot"]), window,
                                        meta.get("have"))
            st.pushes[window] = (base, dict(arrays))
            st.delivered = max(st.delivered, window)
            # (no cluster.pushes bump: the worker owns it — see skip)
            self._try_commit()
            # the DEFERRED ack: reply once this window has merged —
            # the pull piggybacks the post-commit center, and the
            # worker's next base version is plan-determined instead of
            # arrival-order-determined (the determinism contract)
            while (self.version <= window and not self.done
                   and self.aborted is None
                   and self._fenced(meta) is st
                   and not self._stop.is_set()):
                self._cond.wait(timeout=0.2)
            if self._fenced(meta) is not st:
                return ("error", {"error": "declared dead while "
                                           "awaiting commit"}, {})
            return self._pull_reply(int(meta["slot"]), window,
                                    meta.get("have"))

    def _handle_bye(self, meta) -> tuple:
        slot = int(meta["slot"])
        with self._lock:
            st = self._fenced(meta)
            if st is not None:
                self.worker_stats[slot] = dict(meta.get("stats") or {})
                self._record_worker_counters(slot)
                self._wal_append("bye", {
                    "slot": slot,
                    "stats": self.worker_stats[slot]})
                if self.done or st.delivered >= self.cfg.n_windows - 1:
                    # graceful departure: end-of-run, or a worker that
                    # already delivered (pushed or skipped) everything
                    # it owes and finished its last window before the
                    # peers' final pushes commit — a DEATH here would
                    # make the membership sequence race wall clock,
                    # and under the restart policy would abort a
                    # healthy completing run
                    st.status = FREE
                    self._try_commit()
                    self._cond.notify_all()
                else:
                    self._death(slot, "graceful leave")
            return ("ok", self._status_meta(), {})

    def _record_worker_counters(self, slot: int) -> None:
        stats = self.worker_stats.get(slot) or {}
        ms = stats.get("push_pull_ms_total")
        n = stats.get("pushes")
        if ms is not None:
            tevents.counter("cluster.push_pull_ms",
                            int(round(float(ms))))
        if n:
            tevents.counter("cluster.worker_pushes", int(n))

    # ------------------------------------------------ death & commits

    def _death(self, slot: int, reason: str) -> None:
        """Lock held. Membership leave + generation bump; the commit
        that was blocked on this worker proceeds without it."""
        st = self.slots[slot]
        if st.status != ACTIVE or self.killed:
            return
        prev_active = sum(s.status == ACTIVE
                          for s in self.slots.values())
        st.status = DEAD
        self.gen += 1
        window = max(st.delivered, st.admit - 1) + 1
        self.events.append(("leave", slot, window, self.gen, reason))
        self._wal_append("leave", {"slot": slot, "window": window,
                                   "gen": self.gen,
                                   "reason": reason})
        tevents.emit("cluster_leave", slot=slot, gen=self.gen,
                     reason=reason, delivered=st.delivered)
        tevents.counter("cluster.leaves")
        self._emit_membership(f"leave:{reason}", prev_active)
        if self.cfg.policy == "restart" and not self.done:
            self._abort(f"worker {slot} died ({reason}); restart "
                        f"policy aborts for a full respawn")
            return
        self._try_commit()
        self._cond.notify_all()

    def _abort(self, reason: str) -> None:
        """Lock held. The restart-policy exit. Deliberately NO
        checkpoint here: the gang-scheduled baseline restarts from the
        last PERIODIC save and re-pays every window since — exactly
        the progress loss the elastic policy exists to avoid (an
        abort-time save would quietly gift the baseline lossless
        restarts and flatter the measured speedup's denominator)."""
        self.aborted = reason
        tevents.emit("cluster_abort", reason=reason,
                     version=self.version)
        self._cond.notify_all()

    def _expected(self, window: int) -> list[int]:
        return [i for i in sorted(self.slots)
                if self.slots[i].status == ACTIVE
                and self.slots[i].admit <= window]

    def _try_commit(self) -> None:
        """Lock held. Drain every committable window: all expected
        workers have pushed-or-skipped it (and any admission hold is
        satisfied); apply pushes in slot order; bump the clock."""
        while self.version < self.cfg.n_windows and not self.done \
                and self.aborted is None and not self.killed:
            w = self.version
            need = self.hold_at.get(w)
            expected = self._expected(w)
            if need is not None and len(expected) < need:
                return                       # admission hold
            if not expected:
                return                       # quorumless: wait for a join
            if any(w not in self.slots[i].pushes
                   and w not in self.slots[i].skips
                   for i in expected):
                return
            # the seeded coordinator fault lands HERE — every push for
            # w is buffered in RAM, the commit record is not yet
            # durable: a kill exercises the rollback path (the window
            # re-runs from its pushes on reconnect), a hang freezes
            # the commit the workers are all waiting on
            if w < self._coord_sched.shape[0] and \
                    self._coord_sched[w] and \
                    w not in self._coord_fired:
                self._coord_fired.add(w)
                cell = float(self._coord_sched[w])
                if cell == COORD_KILL:
                    tevents.emit("cluster_coordinator_kill",
                                 window=w)
                    self._die()       # never returns (or raises)
                time.sleep(cell)      # the frozen-coordinator cell
                # the freeze held the state lock, so every beat
                # handler was parked and last_beat is uniformly
                # stale: restart the liveness clock (same semantics
                # as recovery) — otherwise an unfairly-scheduled
                # heartbeat scan could declare healthy workers dead
                # the moment the lock frees, making the digest
                # timing-dependent
                now_ = time.monotonic()
                for st_ in self.slots.values():
                    if st_.status == ACTIVE:
                        st_.last_beat = now_
                        st_.suspect_at = None
            contribs = []
            skipped = []
            for i in sorted(self.slots):     # dead workers' buffered
                st = self.slots[i]           # pushes still count: they
                if w in st.pushes:           # delivered before dying
                    base, delta = st.pushes.pop(w)
                    contribs.append((i, base, delta))
                elif w in st.skips:
                    st.skips.discard(w)
                    skipped.append(i)
            # WRITE-AHEAD: the commit record (slot-ordered contribution
            # digests + the delta bytes — a redo log) goes durable
            # BEFORE the merge mutates the center and BEFORE any
            # deferred push-ack observes the new version; a crash on
            # either side of this line is recoverable (before: the
            # window rolls back invisibly; after: replay re-applies
            # the record and re-pushes dedupe against its digests)
            wal_meta = {
                "window": w,
                "contribs": [
                    {"slot": i, "base": b,
                     "age": max(0, w - int(b)),
                     "digest": walmod.delta_digest(d)}
                    for i, b, d in contribs],
                "skipped": skipped,
                "version": w + 1,
            }
            self._wal_append(
                "commit", wal_meta,
                {f"{i}/{k}": v for i, _b, d in contribs
                 for k, v in d.items()})
            for c in wal_meta["contribs"]:
                self.commit_digests[(w, c["slot"])] = c["digest"]
            # the seeded PS-SHARD fault lands HERE — the commit record
            # IS durable but the merge has not applied: a kill
            # exercises the WAL's REDO half (recovery replays the
            # record and re-applies the logged deltas; the coordinator
            # cell above covers the rollback half), a hang freezes the
            # shard merge everyone is waiting on
            if w < self._ps_sched.shape[0] and \
                    self._ps_sched[w] and \
                    w not in self._ps_fired:
                self._ps_fired.add(w)
                cell = float(self._ps_sched[w])
                if cell == COORD_KILL:
                    tevents.emit("cluster_ps_kill", window=w)
                    self._die()       # never returns (or raises)
                time.sleep(cell)      # the frozen-shard cell: same
                #                       liveness-clock reset as the
                #                       coordinator freeze above
                now_ = time.monotonic()
                for st_ in self.slots.values():
                    if st_.status == ACTIVE:
                        st_.last_beat = now_
                        st_.suspect_at = None
            # the WAL carried the COMPRESSED payload bytes (the redo
            # log replays bitwise); the exact host decode happens
            # here, strictly after durability, in slot order
            records = self.ps.merge(
                w, [(i, b, self._decode_delta(d))
                    for i, b, d in contribs])
            self.version = w + 1
            if self.recovered and self.first_recommit_at is None:
                self.first_recommit_at = time.monotonic()
            self.events.append((
                "merge", w,
                tuple((r["slot"], r["age"]) for r in records),
                tuple(skipped)))
            tevents.emit("cluster_merge", window=w,
                         applied=records, skipped=skipped,
                         n_active=len(expected))
            tevents.counter("cluster.merges")
            tevents.counter("cluster.deliveries", len(records))
            tevents.counter("cluster.skipped_deliveries",
                            len(skipped))
            if records:
                tevents.gauge(
                    "cluster.max_staleness",
                    max(r["age"] for r in records))
            self._checkpoint()
            if self.version >= self.cfg.n_windows:
                self.done = True
                self._wal_append("done", {"version": self.version})
                self._checkpoint(force=True)
                tevents.emit("cluster_done", version=self.version,
                             gen=self.gen)
            self._cond.notify_all()

    def _checkpoint(self, force: bool = False) -> None:
        """Lock held. Durable center save through the shared
        checkpoint machinery (CRC footer, atomic rename, prune), then
        the WAL rotates onto the new durable center: a fresh segment
        opens with the control-state snapshot and segments older than
        the oldest KEPT checkpoint are deleted — the configured-
        cadence truncation that keeps the ledger O(windows since last
        save), while a quarantined-corrupt newest checkpoint can still
        fall back and roll forward from the older segments."""
        if not self.cfg.checkpoint_dir:
            return
        if not force and (self.version == 0
                          or self.version % self.cfg.checkpoint_every):
            return
        from tpu_distalg.utils import checkpoint as ckpt

        ckpt.save(self.cfg.checkpoint_dir,
                  {"tag": ckpt.encode_tag(self._tag),
                   "center": self.ps.snapshot()},
                  step=self.version)
        ckpt.prune(self.cfg.checkpoint_dir, keep=3)
        if self.wal is not None:
            kept = ckpt.list_steps(self.cfg.checkpoint_dir)
            self.wal.rotate(self.version, self._snapshot_control(),
                            keep_base=min(kept) if kept else None)
        tevents.emit("checkpoint_saved", step=self.version,
                     tag=self._tag)
        tevents.counter("checkpoints_saved")
