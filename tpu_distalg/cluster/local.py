"""Local cluster launcher — N workers + coordinator on this machine.

``tda cluster --role local --workers N``'s engine, and the harness the
tests and bench drive: starts an in-process :class:`Coordinator`,
spawns workers either as REAL OS processes (``spawn='process'`` — the
``tda cluster --role worker`` CLI in a subprocess, where ``kill -9``
is a genuine SIGKILL) or as threads (``spawn='thread'`` — same
protocol over the same localhost sockets, a kill cell slams the
sockets instead; fast enough for tier-1 tests and for bench arms
where process-spawn noise would drown the measurement).

Elastic supervision: when the plan's schedule kills a worker, the
launcher respawns its slot once — under the plan WITH KILL RULES
STRIPPED (``worker.strip_kills``: the fault was transient; a
deterministic cell would re-kill every incarnation forever) — and
pins the rejoin to a plan-determined window with
``Coordinator.hold_admission`` so the replayed event sequence is
identical. ``policy='restart'`` instead respawns the WHOLE cluster
from the durable checkpoint on any death: the gang-scheduled
BSP-restart baseline the bench's elastic-speedup ratio measures
against.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
import threading
import time

import numpy as np

from tpu_distalg.cluster import worker as workermod
from tpu_distalg.cluster.coordinator import (
    ClusterAborted,
    ClusterConfig,
    Coordinator,
)
from tpu_distalg.faults import registry as fregistry

#: windows a killed slot stays away before its replacement is admitted
DEFAULT_REJOIN_AFTER = 3


class _ThreadWorker:
    """One thread-mode worker: the real protocol over real sockets;
    its kill-cell ``die`` slams both sockets (EOF at the coordinator —
    the same observable as a SIGKILL'd process)."""

    def __init__(self, host, port, slot, *, rejoin=False,
                 admit_at=None):
        self.slot = slot
        self.result: dict | None = None
        self.error: Exception | None = None
        self._socks: list = []
        self._t = threading.Thread(
            target=self._run, args=(host, port, slot, rejoin,
                                    admit_at),
            name=f"tda-cluster-worker{slot}", daemon=True)
        self._t.start()

    def _connect(self, *a, **kw):
        from tpu_distalg.cluster import transport

        s = transport.connect(*a, **kw)
        self._socks.append(s)
        return s

    def _die(self):
        # not a process: death = the sockets vanish, abruptly
        for s in list(self._socks):
            try:
                s.shutdown(2)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        raise workermod.WorkerKilled()

    def _run(self, host, port, slot, rejoin, admit_at):
        try:
            self.result = workermod.run_worker(
                host, port, slot=slot, rejoin=rejoin,
                admit_at=admit_at, die=self._die,
                connect=self._connect)
        except workermod.WorkerKilled:
            self.result = {"killed": True}
        except Exception as e:  # noqa: BLE001 — surfaced via .error
            self.error = e

    def join(self, timeout=None):
        self._t.join(timeout)
        return self.result

    @property
    def alive(self):
        return self._t.is_alive()


def _spawn_process_worker(host, port, slot, *, plan_spec,
                          telemetry_dir, rejoin=False,
                          admit_at=None):
    """A REAL worker process via the CLI — ``kill -9`` here is the
    genuine article. The worker's schedule comes from the
    coordinator's welcome frame; the plan is NOT exported into the
    child's environment (a worker-side registry would double-probe)."""
    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    env.pop("TDA_FAULT_PLAN", None)
    cmd = [sys.executable, "-m", "tpu_distalg.cli", "cluster",
           "--role", "worker", "--connect", f"{host}:{port}",
           "--slot", str(slot)]
    if rejoin:
        cmd.append("--rejoin")
    if admit_at is not None:
        cmd += ["--admit-at", str(admit_at)]
    if telemetry_dir:
        cmd += ["--telemetry-dir",
                os.path.join(telemetry_dir, f"worker-{slot}")]
    return subprocess.Popen(
        cmd, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)


def run_local_cluster(config: ClusterConfig, *, spawn: str = "thread",
                      respawn: bool = True,
                      rejoin_after: int = DEFAULT_REJOIN_AFTER,
                      telemetry_dir: str | None = None,
                      timeout: float = 600.0,
                      logger=None) -> dict:
    """Run one full cluster training locally; returns the
    coordinator's result dict plus launcher bookkeeping
    (``restarts``, ``respawns``, ``wall_seconds``).

    * ``policy='elastic'`` (config): a killed worker's slot is
      respawned once (``respawn=True``) under the kill-stripped plan,
      admitted at the plan-determined window ``kill_window +
      rejoin_after`` via an admission hold — so a chaos run's event
      sequence replays identically.
    * ``policy='restart'``: any death aborts; the WHOLE cluster
      respawns from the checkpoint until the run completes — the
      measured BSP-restart baseline.
    """
    log = logger or (lambda m: None)
    t0 = time.monotonic()
    plan_spec = config.plan_spec
    restarts = 0
    while True:
        coord = Coordinator(config).start()
        host, port = config.host, coord.port
        schedule = workermod.compile_worker_schedule(
            config.n_windows, config.n_slots,
            plan=(fregistry.FaultPlan.parse(plan_spec)
                  if plan_spec else None))
        # first kill cell per slot (a slot dies at most once per
        # incarnation; later cells are moot — the process is gone)
        kill_cells: dict[int, int] = {}
        for w, slot in zip(*np.nonzero(schedule == workermod.KILL)):
            kill_cells.setdefault(int(slot), int(w))
        if config.policy == "elastic" and respawn:
            # pin every replacement's admission window up front: the
            # event sequence becomes a pure function of the plan
            for slot, w_kill in sorted(kill_cells.items()):
                coord.hold_admission(
                    min(w_kill + rejoin_after, config.n_windows - 1),
                    config.n_slots)
        workers = {}
        for slot in range(config.n_slots):
            workers[slot] = _start(spawn, host, port, slot,
                                   telemetry_dir=telemetry_dir)
        pending_respawn = (
            {slot: min(w + rejoin_after, config.n_windows - 1)
             for slot, w in kill_cells.items()}
            if config.policy == "elastic" and respawn else {})
        respawned: list[int] = []
        try:
            result = _supervise(coord, workers, pending_respawn,
                                spawn, host, port, telemetry_dir,
                                timeout, log, respawned)
            result["restarts"] = restarts
            # OBSERVED respawns (a death the supervisor actually saw
            # and replaced), not the plan's kill-cell count — the
            # bench's did-the-kill-really-fire guard reads this
            result["respawns"] = len(respawned)
            result["wall_seconds"] = round(time.monotonic() - t0, 3)
            return result
        except ClusterAborted as e:
            restarts += 1
            log(f"[cluster] aborted ({e}); restart policy respawns "
                f"the whole cluster (restart {restarts})")
            coord.stop()
            _reap(workers, spawn)
            # the transient fault already fired: the respawned job
            # runs kill-free (worker.strip_kills), like a real
            # executor loss
            plan_spec = workermod.strip_kills(plan_spec)
            config = dataclasses.replace(config, plan_spec=plan_spec)
            if time.monotonic() - t0 > timeout:
                raise TimeoutError(
                    f"restart-policy run exceeded {timeout}s") from e
        finally:
            coord.stop()


def _start(spawn, host, port, slot, *, telemetry_dir,
           rejoin=False, admit_at=None):
    if spawn == "process":
        return _spawn_process_worker(
            host, port, slot, plan_spec=None,
            telemetry_dir=telemetry_dir, rejoin=rejoin,
            admit_at=admit_at)
    return _ThreadWorker(host, port, slot, rejoin=rejoin,
                         admit_at=admit_at)


def _alive(h, spawn):
    return (h.poll() is None) if spawn == "process" else h.alive


def _reap(workers, spawn):
    for h in workers.values():
        if spawn == "process":
            try:
                # workers exit on their own once the coordinator says
                # done — give them time to flush telemetry (a kill
                # here would lose their counters event) before the
                # hard reap
                h.wait(timeout=20)
            except subprocess.TimeoutExpired:
                h.kill()
                h.wait(timeout=30)
        else:
            h.join(timeout=30)


def _supervise(coord, workers, pending_respawn, spawn, host, port,
               telemetry_dir, timeout, log, respawned):
    """Drive one incarnation to completion: wait on the coordinator,
    respawning killed slots (elastic) as their deaths surface.
    ``pending_respawn`` maps slot -> pinned admission window;
    ``respawned`` collects the slots actually replaced."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            # short wait slices: a scheduled kill's respawn latency is
            # bounded by this poll, and it sits on the elastic arm's
            # measured wall clock
            coord.wait(timeout=0.05)
            _reap(workers, spawn)
            # re-snapshot AFTER the workers' byes have landed, so the
            # result carries their reported stats
            return coord.result()
        except TimeoutError:
            if time.monotonic() > deadline:
                coord.stop()
                _reap(workers, spawn)
                raise TimeoutError(
                    f"cluster run still incomplete after {timeout}s "
                    f"(version {coord.version}/{coord.cfg.n_windows})"
                    ) from None
        for slot in list(pending_respawn):
            h = workers.get(slot)
            if h is not None and _alive(h, spawn):
                continue
            # the kill landed; respawn the slot ONCE, its admission
            # pinned to the plan-determined window (a rejoiner never
            # re-executes windows before its admission, so the old
            # kill cell cannot re-fire)
            admit_at = pending_respawn.pop(slot)
            respawned.append(slot)
            log(f"[cluster] worker {slot} died on schedule; "
                f"respawning (rejoin at window {admit_at})")
            workers[slot] = _start(
                spawn, host, port, slot,
                telemetry_dir=telemetry_dir, rejoin=True,
                admit_at=admit_at)
