"""Local cluster launcher — N workers + coordinator on this machine.

``tda cluster --role local --workers N``'s engine, and the harness the
tests and bench drive: starts an in-process :class:`Coordinator`,
spawns workers either as REAL OS processes (``spawn='process'`` — the
``tda cluster --role worker`` CLI in a subprocess, where ``kill -9``
is a genuine SIGKILL) or as threads (``spawn='thread'`` — same
protocol over the same localhost sockets, a kill cell slams the
sockets instead; fast enough for tier-1 tests and for bench arms
where process-spawn noise would drown the measurement).

Elastic supervision: when the plan's schedule kills a worker, the
launcher respawns its slot once — under the plan WITH KILL RULES
STRIPPED (``worker.strip_kills``: the fault was transient; a
deterministic cell would re-kill every incarnation forever) — and
pins the rejoin to a plan-determined window with
``Coordinator.hold_admission`` so the replayed event sequence is
identical. ``policy='restart'`` instead respawns the WHOLE cluster
from the durable checkpoint on any death: the gang-scheduled
BSP-restart baseline the bench's elastic-speedup ratio measures
against.

COORDINATOR supervision (crash tolerance): a ``cluster:coordinator``
kill cell in the plan kills the coordinator itself mid-window — in
thread/inproc mode the injected ``die`` slams its listener and every
connection (the SIGKILL observable), with ``coordinator_spawn=
'process'`` the coordinator is a real subprocess that genuinely
``kill -9``\\ s itself. Either way the launcher detects the death,
respawns the coordinator ON THE SAME PORT under the coordinator-kill-
stripped plan, and the new incarnation recovers from the durable WAL
(``cluster/wal.py``) while the surviving workers reconnect and resume
their incarnations — no membership epoch burns, no progress is lost,
and the measured ``detect -> recover -> first recommitted window``
latency lands in the result as ``recovery_ms`` (the
``cluster_coordinator_recovery_ms`` bench metric).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

from tpu_distalg.cluster import rowstore as rowstoremod
from tpu_distalg.cluster import transport
from tpu_distalg.cluster import worker as workermod
from tpu_distalg.cluster.coordinator import (
    COORD_KILL,
    ClusterAborted,
    ClusterConfig,
    Coordinator,
    compile_coordinator_schedule,
)
from tpu_distalg.faults import registry as fregistry
from tpu_distalg.telemetry import events as tevents

#: windows a killed slot stays away before its replacement is admitted
DEFAULT_REJOIN_AFTER = 3


def _record_recovery(recovery_ms: list, t_detect: float,
                     recommit_at: float) -> float:
    """Close one detect→recover→first-recommitted-window measurement:
    append the span and emit the counter + running-median gauge. ONE
    spelling, shared by the inproc and subprocess-coordinator
    supervisors, so the recovery telemetry's shape cannot drift
    between the two."""
    ms = (recommit_at - t_detect) * 1e3
    recovery_ms.append(round(ms, 3))
    tevents.counter("cluster.recovery_ms", int(round(ms)))
    tevents.gauge(
        "cluster.recovery_ms_p50",
        round(float(np.percentile(recovery_ms, 50)), 3))
    tevents.emit("cluster_recovery_measured", ms=round(ms, 3),
                 recoveries=len(recovery_ms))
    return ms


def event_digest(result: dict) -> str:
    """The 16-hex-char fingerprint of a run's merge + membership
    sequences — what the CLI's ``cluster_result:`` tail line prints
    and the replay/chaos acceptances compare (ONE spelling, so the
    two can never drift)."""
    import hashlib

    seq = json.dumps([result["merge_sequence"],
                      result["membership_sequence"]], default=int)
    return hashlib.sha256(seq.encode()).hexdigest()[:16]


class _ThreadWorker:
    """One thread-mode worker: the real protocol over real sockets;
    its kill-cell ``die`` slams both sockets (EOF at the coordinator —
    the same observable as a SIGKILL'd process)."""

    def __init__(self, host, port, slot, *, rejoin=False,
                 admit_at=None):
        self.slot = slot
        self.result: dict | None = None
        self.error: Exception | None = None
        self._socks: list = []
        self._t = threading.Thread(
            target=self._run, args=(host, port, slot, rejoin,
                                    admit_at),
            name=f"tda-cluster-worker{slot}", daemon=True)
        self._t.start()

    def _connect(self, *a, **kw):
        from tpu_distalg.cluster import transport

        s = transport.connect(*a, **kw)
        self._socks.append(s)
        return s

    def _die(self):
        # not a process: death = the sockets vanish, abruptly
        for s in list(self._socks):
            try:
                s.shutdown(2)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        raise workermod.WorkerKilled()

    def _run(self, host, port, slot, rejoin, admit_at):
        try:
            self.result = workermod.run_worker(
                host, port, slot=slot, rejoin=rejoin,
                admit_at=admit_at, die=self._die,
                connect=self._connect)
        except workermod.WorkerKilled:
            self.result = {"killed": True}
        except Exception as e:  # noqa: BLE001 — surfaced via .error
            self.error = e

    def join(self, timeout=None):
        self._t.join(timeout)
        return self.result

    @property
    def alive(self):
        return self._t.is_alive()


def _spawn_process_worker(host, port, slot, *, plan_spec,
                          telemetry_dir, rejoin=False,
                          admit_at=None):
    """A REAL worker process via the CLI — ``kill -9`` here is the
    genuine article. The worker's schedule comes from the
    coordinator's welcome frame; the plan is NOT exported into the
    child's environment (a worker-side registry would double-probe)."""
    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    env.pop("TDA_FAULT_PLAN", None)
    cmd = [sys.executable, "-m", "tpu_distalg.cli", "cluster",
           "--role", "worker", "--connect", f"{host}:{port}",
           "--slot", str(slot)]
    if rejoin:
        cmd.append("--rejoin")
    if admit_at is not None:
        cmd += ["--admit-at", str(admit_at)]
    if telemetry_dir:
        cmd += ["--telemetry-dir",
                os.path.join(telemetry_dir, f"worker-{slot}")]
    return subprocess.Popen(
        cmd, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)


class _CoordSupervisor:
    """The in-process coordinator under launcher supervision: builds
    it with the thread-mode ``die`` hook (a kill cell slams the
    listener and every connection — the SIGKILL observable), detects
    the death, respawns ON THE SAME PORT under the coordinator-kill-
    stripped plan (the new incarnation recovers from the WAL), and
    measures ``detect -> recover -> first recommitted window``."""

    def __init__(self, config: ClusterConfig, log):
        self.config = config
        self.log = log
        self.coord = Coordinator(
            config, die=lambda c: c.slam()).start()
        self.port = self.coord.port
        self.recoveries = 0
        self.recovery_ms: list[float] = []
        self.wal_records_replayed = 0
        self._pending: float | None = None   # detect time of an
        #                                      unclosed measurement

    def check(self) -> None:
        """One supervision tick: respawn a killed coordinator, close
        out a pending recovery measurement once the first window past
        the death point recommits (the coordinator records that
        commit's monotonic timestamp itself, so a supervision tick
        landing late — or only at completion — still measures the
        true detect→recover→first-recommitted-window span)."""
        if self.coord.killed and self._pending is None:
            t_detect = time.monotonic()
            v_death = self.coord.version
            self.log(f"[cluster] coordinator died on schedule at "
                     f"version {v_death}; respawning on port "
                     f"{self.port} (WAL recovery)")
            # the transient fault already fired: the recovered
            # incarnation runs coordinator-kill-free
            self.config = dataclasses.replace(
                self.config, port=self.port,
                plan_spec=workermod.strip_kills(
                    self.config.plan_spec,
                    points=("cluster:coordinator", "cluster:ps")))
            self.coord = Coordinator(
                self.config, die=lambda c: c.slam()).start()
            self.recoveries += 1
            self.wal_records_replayed += \
                self.coord.wal_records_replayed
            self._pending = t_detect
        if self._pending is not None and \
                self.coord.first_recommit_at is not None:
            _record_recovery(self.recovery_ms, self._pending,
                             self.coord.first_recommit_at)
            self._pending = None

    def stop(self) -> None:
        self.coord.stop()

    def bookkeeping(self) -> dict:
        self.check()   # close out a measurement the last poll missed
        return {
            "coordinator_recoveries": self.recoveries,
            "recovery_ms": list(self.recovery_ms),
            "wal_records_replayed": self.wal_records_replayed,
        }


def run_local_cluster(config: ClusterConfig, *, spawn: str = "thread",
                      coordinator_spawn: str = "inproc",
                      respawn: bool = True,
                      rejoin_after: int = DEFAULT_REJOIN_AFTER,
                      telemetry_dir: str | None = None,
                      timeout: float = 600.0,
                      logger=None) -> dict:
    """Run one full cluster training locally; returns the
    coordinator's result dict plus launcher bookkeeping
    (``restarts``, ``respawns``, ``wall_seconds``, and — when the
    plan kills the coordinator — ``coordinator_recoveries`` /
    ``recovery_ms`` / ``wal_records_replayed``).

    * ``policy='elastic'`` (config): a killed worker's slot is
      respawned once (``respawn=True``) under the kill-stripped plan,
      admitted at the plan-determined window ``kill_window +
      rejoin_after`` via an admission hold — so a chaos run's event
      sequence replays identically.
    * ``policy='restart'``: any death aborts; the WHOLE cluster
      respawns from the checkpoint until the run completes — the
      measured BSP-restart baseline.
    * a ``cluster:coordinator`` kill cell kills the COORDINATOR
      mid-window; the launcher respawns it on the same port and the
      WAL recovery + worker reconnects make the completed run
      bitwise-identical to the undisturbed one. Requires a
      ``checkpoint_dir`` (the WAL lives under it).
      ``coordinator_spawn='process'`` runs the coordinator as a real
      subprocess (``tda cluster --role coordinator``) so the kill is
      a genuine ``kill -9``.
    """
    log = logger or (lambda m: None)
    _plan = (fregistry.FaultPlan.parse(config.plan_spec)
             if config.plan_spec else None)
    coord_sched = compile_coordinator_schedule(
        config.n_windows, plan=_plan)
    ps_sched = rowstoremod.compile_point_schedule(
        "cluster:ps", config.n_windows, plan=_plan)[:, 0]
    if ((coord_sched == COORD_KILL).any()
            or (ps_sched == COORD_KILL).any()) \
            and not config.checkpoint_dir:
        raise ValueError(
            "a cluster:coordinator / cluster:ps kill plan needs a "
            "checkpoint_dir: the durable WAL (and the center "
            "checkpoints it sits on) live under it — without one "
            "there is nothing to recover from")
    if coordinator_spawn == "process":
        return _run_process_coordinator(
            config, spawn=spawn, respawn=respawn,
            rejoin_after=rejoin_after, telemetry_dir=telemetry_dir,
            timeout=timeout, log=log)
    if coordinator_spawn != "inproc":
        raise ValueError(
            f"unknown coordinator_spawn {coordinator_spawn!r}: "
            f"'inproc' (thread-mode die hook) or 'process' (real "
            f"subprocess, genuine kill -9)")
    t0 = time.monotonic()
    plan_spec = config.plan_spec
    restarts = 0
    while True:
        sup = _CoordSupervisor(config, log)
        host, port = config.host, sup.port
        schedule = workermod.compile_worker_schedule(
            config.n_windows, config.n_slots,
            plan=(fregistry.FaultPlan.parse(plan_spec)
                  if plan_spec else None))
        # first kill cell per slot (a slot dies at most once per
        # incarnation; later cells are moot — the process is gone)
        kill_cells: dict[int, int] = {}
        for w, slot in zip(*np.nonzero(schedule == workermod.KILL)):
            kill_cells.setdefault(int(slot), int(w))
        if config.policy == "elastic" and respawn:
            # pin every replacement's admission window up front: the
            # event sequence becomes a pure function of the plan
            # (durable — a recovered coordinator keeps the hold)
            for slot, w_kill in sorted(kill_cells.items()):
                sup.coord.hold_admission(
                    min(w_kill + rejoin_after, config.n_windows - 1),
                    config.n_slots)
        workers = {}
        for slot in range(config.n_slots):
            workers[slot] = _start(spawn, host, port, slot,
                                   telemetry_dir=telemetry_dir)
        pending_respawn = (
            {slot: min(w + rejoin_after, config.n_windows - 1)
             for slot, w in kill_cells.items()}
            if config.policy == "elastic" and respawn else {})
        respawned: list[int] = []
        try:
            result = _supervise(sup, workers, pending_respawn,
                                spawn, host, port, telemetry_dir,
                                timeout, log, respawned)
            result["restarts"] = restarts
            # OBSERVED respawns (a death the supervisor actually saw
            # and replaced), not the plan's kill-cell count — the
            # bench's did-the-kill-really-fire guard reads this
            result["respawns"] = len(respawned)
            result["wall_seconds"] = round(time.monotonic() - t0, 3)
            result.update(sup.bookkeeping())
            return result
        except ClusterAborted as e:
            restarts += 1
            log(f"[cluster] aborted ({e}); restart policy respawns "
                f"the whole cluster (restart {restarts})")
            # reap BEFORE stopping: the aborted coordinator keeps
            # answering status frames with restart=True, so surviving
            # workers exit their loops gracefully instead of entering
            # their reconnect retry budgets against a closed port
            _reap(workers, spawn)
            sup.stop()
            # the transient fault already fired: the respawned job
            # runs kill-free (worker.strip_kills), like a real
            # executor loss
            plan_spec = workermod.strip_kills(plan_spec)
            config = dataclasses.replace(config, plan_spec=plan_spec)
            if time.monotonic() - t0 > timeout:
                raise TimeoutError(
                    f"restart-policy run exceeded {timeout}s") from e
        finally:
            sup.stop()


def _start(spawn, host, port, slot, *, telemetry_dir,
           rejoin=False, admit_at=None):
    if spawn == "process":
        return _spawn_process_worker(
            host, port, slot, plan_spec=None,
            telemetry_dir=telemetry_dir, rejoin=rejoin,
            admit_at=admit_at)
    return _ThreadWorker(host, port, slot, rejoin=rejoin,
                         admit_at=admit_at)


def _alive(h, spawn):
    return (h.poll() is None) if spawn == "process" else h.alive


def _respawn_dead_workers(workers, pending_respawn, spawn, host,
                          port, telemetry_dir, respawned, log):
    """One supervision sweep of the worker slots: a scheduled kill's
    dead handle is replaced ONCE, its admission pinned to the
    plan-determined window (a rejoiner never re-executes windows
    before its admission, so the old kill cell cannot re-fire).
    Shared by the inproc and subprocess-coordinator supervisors so
    the two loops cannot drift."""
    for slot in list(pending_respawn):
        h = workers.get(slot)
        if h is not None and _alive(h, spawn):
            continue
        admit_at = pending_respawn.pop(slot)
        respawned.append(slot)
        log(f"[cluster] worker {slot} died on schedule; "
            f"respawning (rejoin at window {admit_at})")
        workers[slot] = _start(
            spawn, host, port, slot, telemetry_dir=telemetry_dir,
            rejoin=True, admit_at=admit_at)


def _reap(workers, spawn):
    for h in workers.values():
        if spawn == "process":
            try:
                # workers exit on their own once the coordinator says
                # done — give them time to flush telemetry (a kill
                # here would lose their counters event) before the
                # hard reap
                h.wait(timeout=20)
            except subprocess.TimeoutExpired:
                h.kill()
                h.wait(timeout=30)
        else:
            h.join(timeout=30)


def _supervise(sup, workers, pending_respawn, spawn, host, port,
               telemetry_dir, timeout, log, respawned):
    """Drive one incarnation to completion: wait on the (supervised)
    coordinator, respawning killed slots (elastic) — and a killed
    COORDINATOR — as their deaths surface. ``pending_respawn`` maps
    slot -> pinned admission window; ``respawned`` collects the slots
    actually replaced."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            # short wait slices: a scheduled kill's respawn latency is
            # bounded by this poll, and it sits on the elastic arm's
            # measured wall clock
            sup.coord.wait(timeout=0.05)
            _reap(workers, spawn)
            # re-snapshot AFTER the workers' byes have landed, so the
            # result carries their reported stats
            return sup.coord.result()
        except TimeoutError:
            if time.monotonic() > deadline:
                sup.stop()
                _reap(workers, spawn)
                raise TimeoutError(
                    f"cluster run still incomplete after {timeout}s "
                    f"(version {sup.coord.version}/"
                    f"{sup.coord.cfg.n_windows})") from None
        sup.check()   # coordinator death -> respawn + WAL recovery
        _respawn_dead_workers(workers, pending_respawn, spawn, host,
                              port, telemetry_dir, respawned, log)


# --------------------------------------------- subprocess coordinator


class _ProcCoordinator:
    """A REAL coordinator process (``tda cluster --role coordinator``)
    — the seeded ``cluster:coordinator`` kill is a genuine
    ``kill -9`` here. Stdout is drained on a thread; the launcher
    parses the ``listening on`` line for the port and the final
    ``cluster_result:`` line for the result."""

    def __init__(self, config: ClusterConfig, telemetry_dir, *,
                 port: int = 0):
        env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
            "JAX_PLATFORMS", "cpu"))
        env.pop("TDA_FAULT_PLAN", None)
        cmd = [sys.executable, "-m", "tpu_distalg.cli", "cluster",
               "--role", "coordinator",
               "--host", config.host, "--port", str(port),
               "--workers", str(config.n_slots),
               "--n-windows", str(config.n_windows),
               "--sync",
               f"ssp:{config.staleness}:{config.decay:g}",
               "--ps-shards", str(config.ps_shards),
               "--heartbeat-timeout", str(config.heartbeat_timeout),
               "--heartbeat-interval",
               str(config.heartbeat_interval),
               "--rpc-deadline", str(config.rpc_deadline),
               "--reconnect-grace", str(config.reconnect_grace),
               "--comm", config.comm,
               "--ps-mode", config.ps_mode,
               # the EXACT TrainTask, every field — workers take the
               # task from the coordinator's welcome, so a lossy
               # handoff here would silently train a different task
               # than the caller configured
               "--train-json", json.dumps(config.train.as_meta()),
               "--policy", config.policy]
        if config.checkpoint_dir:
            cmd += ["--checkpoint-dir", config.checkpoint_dir,
                    "--checkpoint-every",
                    str(config.checkpoint_every)]
        if config.plan_spec:
            cmd += ["--fault-plan", config.plan_spec]
        if telemetry_dir:
            cmd += ["--telemetry-dir",
                    os.path.join(telemetry_dir, "coordinator")]
        self.proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, bufsize=1)
        self.lines: list[str] = []
        self._t = threading.Thread(target=self._drain,
                                   name="tda-coord-stdout",
                                   daemon=True)
        self._t.start()
        self.port = self._await_port()

    def _drain(self):
        for line in self.proc.stdout:
            self.lines.append(line.rstrip("\n"))

    def _await_port(self, timeout: float = 90.0) -> int:
        deadline = time.monotonic() + timeout
        prefix = "cluster_coordinator: listening on "
        while time.monotonic() < deadline:
            for line in list(self.lines):
                if line.startswith(prefix):
                    return int(line[len(prefix):].rsplit(":", 1)[1])
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"coordinator process exited rc="
                    f"{self.proc.returncode} before binding:\n"
                    + "\n".join(self.lines[-20:]))
            time.sleep(0.02)
        raise TimeoutError("coordinator process never reported its "
                           "port")

    def result_line(self) -> dict:
        prefix = "cluster_result: "
        for line in reversed(self.lines):
            if line.startswith(prefix):
                return json.loads(line[len(prefix):])
        raise RuntimeError(
            "coordinator process exited without a cluster_result "
            "line:\n" + "\n".join(self.lines[-20:]))


def _tcp_status(host, port, *, deadline: float = 2.0):
    """One status poll over the wire (the launcher's liveness /
    recovery probe for a subprocess coordinator); ``None`` when the
    coordinator is unreachable."""
    try:
        sock = transport.connect(host, port, deadline=deadline,
                                 attempts=1)
    except transport.TransportError:
        return None
    try:
        # tda: ignore[TDA112] -- launcher-side liveness probe: a dead
        # coordinator surfaces as TransportError from request itself,
        # and the caller treats any reply shape as "alive" (the meta
        # fields all default); there is no fencing to misread here
        _, m, _ = transport.request(sock, "poll", {},
                                    deadline=deadline)
        return m
    except transport.TransportError:
        return None
    finally:
        try:
            sock.close()
        except OSError:
            pass


def _tcp_hold(host, port, window, n_active, *,
              deadline: float = 5.0) -> None:
    """Pin an admission hold over the wire (the subprocess-coordinator
    spelling of ``Coordinator.hold_admission``)."""
    sock = transport.connect(host, port, deadline=deadline)
    try:
        # tda: ignore[TDA112] -- best-effort admission hint: the
        # launcher proceeds identically whether the hold lands or
        # errors (the rejoiner's admit_at pins the schedule either
        # way), so the reply is deliberately unexamined
        transport.request(sock, "hold",
                          {"window": window, "n_active": n_active},
                          deadline=deadline)
    finally:
        try:
            sock.close()
        except OSError:
            pass


def _run_process_coordinator(config: ClusterConfig, *, spawn,
                             respawn, rejoin_after, telemetry_dir,
                             timeout, log) -> dict:
    """The subprocess-coordinator cluster: the coordinator is a real
    OS process, so a ``cluster:coordinator`` kill cell is a genuine
    mid-window ``kill -9`` of the control plane (workers honor the
    caller's ``spawn`` — processes for the full acceptance, threads
    for a faster genuine-coordinator-kill run). The launcher
    respawns it on the same port under the coordinator-kill-stripped
    plan; recovery (WAL replay + worker reconnects) is measured over
    TCP status polls. Elastic policy only — the restart baseline has
    an in-process launcher already."""
    if config.policy != "elastic":
        raise ValueError(
            "coordinator_spawn='process' supports policy='elastic' "
            "only (the restart baseline is an in-process launcher "
            "measurement)")
    t0 = time.monotonic()
    pc = _ProcCoordinator(config, telemetry_dir)
    host, port = config.host, pc.port
    schedule = workermod.compile_worker_schedule(
        config.n_windows, config.n_slots,
        plan=(fregistry.FaultPlan.parse(config.plan_spec)
              if config.plan_spec else None))
    kill_cells: dict[int, int] = {}
    for w, slot in zip(*np.nonzero(schedule == workermod.KILL)):
        kill_cells.setdefault(int(slot), int(w))
    _plan = (fregistry.FaultPlan.parse(config.plan_spec)
             if config.plan_spec else None)
    coord_kill_expected = bool(
        (compile_coordinator_schedule(
            config.n_windows, plan=_plan) == COORD_KILL).any()
        or (rowstoremod.compile_point_schedule(
            "cluster:ps", config.n_windows,
            plan=_plan)[:, 0] == COORD_KILL).any())
    pending_respawn = {}
    if respawn:
        for slot, w_kill in sorted(kill_cells.items()):
            _tcp_hold(host, port,
                      min(w_kill + rejoin_after,
                          config.n_windows - 1), config.n_slots)
        pending_respawn = {
            slot: min(w + rejoin_after, config.n_windows - 1)
            for slot, w in kill_cells.items()}
    workers = {slot: _start(spawn, host, port, slot,
                            telemetry_dir=telemetry_dir)
               for slot in range(config.n_slots)}
    respawned: list[int] = []
    recoveries = 0
    recovery_ms: list[float] = []
    pending_rec: float | None = None   # detect time
    last_version = 0
    deadline = t0 + timeout
    try:
        while True:
            rc = pc.proc.poll()
            if rc is not None:
                if rc == 0:
                    break                       # clean completion
                if not coord_kill_expected or recoveries >= 1:
                    raise RuntimeError(
                        f"coordinator process died rc={rc} with no "
                        f"scheduled kill left — a real failure:\n"
                        + "\n".join(pc.lines[-20:]))
                t_detect = time.monotonic()
                log(f"[cluster] coordinator killed (rc={rc}); "
                    f"respawning on port {port} (WAL recovery)")
                config = dataclasses.replace(
                    config, plan_spec=workermod.strip_kills(
                        config.plan_spec,
                        points=("cluster:coordinator", "cluster:ps")))
                pc = _ProcCoordinator(config, telemetry_dir,
                                      port=port)
                recoveries += 1
                pending_rec = t_detect
            status = _tcp_status(host, port)
            if status is not None:
                last_version = max(last_version,
                                   int(status.get("version", 0)))
                recommit_at = status.get("recommit_at")
                if pending_rec is not None and \
                        recommit_at is not None:
                    # the recovered coordinator stamps its own first
                    # post-recovery commit (CLOCK_MONOTONIC is
                    # machine-wide), so the span is the true detect->
                    # recover->first-recommitted-window — not "first
                    # status poll after replay"
                    _record_recovery(recovery_ms, pending_rec,
                                     float(recommit_at))
                    pending_rec = None
            _respawn_dead_workers(workers, pending_respawn,
                                  spawn, host, port,
                                  telemetry_dir, respawned, log)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"cluster run still incomplete after {timeout}s "
                    f"(version {last_version}/{config.n_windows})")
            time.sleep(0.05)
    finally:
        if pc.proc.poll() is None and time.monotonic() > deadline:
            pc.proc.kill()
        _reap(workers, spawn)
    pc.proc.wait(timeout=30)
    if pending_rec is not None:
        # the run completed before a status poll caught the recommit:
        # completion bounds it — record the (over-estimating) span
        # rather than dropping the observation
        _record_recovery(recovery_ms, pending_rec,
                         time.monotonic())
    result = pc.result_line()
    result["restarts"] = 0
    result["respawns"] = len(respawned)
    result["wall_seconds"] = round(time.monotonic() - t0, 3)
    result["coordinator_recoveries"] = recoveries
    result["recovery_ms"] = recovery_ms
    return result
