"""Length-prefixed TCP transport — framed numpy buffers, no pickle.

The cluster runtime's one wire format (TDA060's liveness spirit,
machine-checked here by TDA090): every message is a single FRAME with
an explicit length prefix, every blocking receive carries a DEADLINE,
and the payload is JSON metadata plus raw C-contiguous numpy buffers —
never pickled code, so a compromised or version-skewed peer can
corrupt a training run's numbers but can never execute anything.

Frame layout (all integers little-endian)::

    magic  b"TDAC"                      4 bytes
    u32    header length                (JSON, <= MAX_HEADER_BYTES)
    u64    body length                  (<= max_frame bytes)
    u32    CRC32 of header || body      (a torn/corrupt frame is
                                         DETECTED, mirroring the
                                         checkpoint footer contract)
    header JSON: {"k": kind, "meta": {...},
                  "arrays": [{"n": name, "d": dtype, "s": shape}, ...]}
    body   the arrays' raw bytes, concatenated in header order

Failure taxonomy — every receive path lands in exactly one:

  * :class:`TransportClosed` — EOF (peer died / socket slammed): a
    ``kill -9``'d worker is observed HERE, immediately;
  * :class:`TransportTimeout` — the deadline expired mid-receive (a
    network partition / ``cluster:rpc hang`` injection);
  * :class:`FrameTooLarge` — a length prefix past ``max_frame`` (a
    corrupt prefix must not become a multi-GB allocation);
  * :class:`TransportError` — bad magic, CRC mismatch, or a dtype the
    safe set does not admit (object dtypes would be pickle by the
    back door).

Fault seam ``cluster:rpc`` (``faults/registry.py``): injected at the
top of :func:`send_frame` and :func:`recv_frame` — ``oserror`` models
a torn connection, ``hang`` a partition that the recv deadline and the
coordinator's heartbeat timeout must observe, not wedge on.

Stdlib + numpy only: workers and coordinator use it before (and
without) any jax import.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib

import numpy as np

from tpu_distalg import faults
from tpu_distalg.telemetry import events as tevents

MAGIC = b"TDAC"
_PREFIX = struct.Struct("<4sIQI")  # magic, header len, body len, crc

#: refuse headers past this (a header is a few hundred bytes of JSON)
MAX_HEADER_BYTES = 1 << 20
#: default ceiling for one frame's body (center pytrees are MBs, not GBs)
DEFAULT_MAX_FRAME_BYTES = 1 << 28
#: default bound for any single blocking receive
DEFAULT_DEADLINE_SECONDS = 30.0
#: dtype kinds a frame may carry — everything numeric/bool/bytes-free;
#: 'O' (object) would be pickle by the back door and is refused on
#: BOTH ends
SAFE_DTYPE_KINDS = frozenset("biufc")

_RECV_CHUNK = 1 << 20


class TransportError(RuntimeError):
    """Malformed frame: bad magic, CRC mismatch, unsafe dtype."""


class TransportClosed(TransportError):
    """EOF — the peer died or closed mid-frame (a truncated frame is
    this, not a parse error: the bytes simply stopped)."""


class TransportTimeout(TransportError):
    """The receive deadline expired — a partition or a wedged peer."""


class FrameTooLarge(TransportError):
    """A length prefix past the configured ceiling."""


def _inject_rpc() -> None:
    """The ``cluster:rpc`` fault seam, folded into the transport's
    failure taxonomy: an injected ``oserror`` IS a torn connection,
    so it must surface as :class:`TransportClosed` — the error every
    handler/reconnect path already rides — not as a raw ``OSError``
    that would skewer a coordinator handler thread."""
    try:
        faults.inject("cluster:rpc")
    except OSError as e:
        raise TransportClosed(
            f"injected torn connection: {e}") from e


def _check_dtype(dt: np.dtype) -> np.dtype:
    dt = np.dtype(dt)
    if dt.kind not in SAFE_DTYPE_KINDS:
        raise TransportError(
            f"refusing dtype {dt!r} on the wire (kind {dt.kind!r}): "
            f"only plain numeric/bool buffers are framed — object "
            f"dtypes would be pickle by the back door")
    return dt


def encode_frame_parts(kind: str, meta: dict | None = None,
                       arrays: dict | None = None) -> list:
    """The frame for ``(kind, meta, arrays)`` as its natural buffer
    list — ``[prefix + header, body chunk, body chunk, ...]`` — whose
    concatenation IS the wire frame. :func:`send_frame` hands this
    straight to ``socket.sendmsg`` (scatter-gather: the kernel walks
    the array buffers in place, no host-side concatenation of a
    multi-MB body), and :func:`encode_frame` joins it for callers
    that need one contiguous record (the WAL). ONE framing
    implementation, so the scatter-gather path can never drift a byte
    from the contiguous one."""
    specs, chunks = [], []
    for name, arr in (arrays or {}).items():
        a = np.ascontiguousarray(arr)
        _check_dtype(a.dtype)
        specs.append({"n": str(name), "d": a.dtype.str,
                      "s": list(a.shape)})
        # a zero-copy byte view, not a.tobytes(): the scatter-gather
        # send (and the CRC walk) read the array's own buffer — the
        # memoryview keeps the (possibly temporary) contiguous array
        # alive, and b"".join accepts it wherever one contiguous
        # record is needed (encode_frame / the WAL)
        chunks.append(memoryview(a).cast("B"))
    header = json.dumps(
        {"k": kind, "meta": meta or {}, "arrays": specs},
        separators=(",", ":")).encode()
    if len(header) > MAX_HEADER_BYTES:
        raise FrameTooLarge(
            f"frame header of {len(header)} bytes exceeds "
            f"{MAX_HEADER_BYTES} — metadata belongs in arrays")
    crc = zlib.crc32(header)
    for chunk in chunks:
        crc = zlib.crc32(chunk, crc)
    crc &= 0xFFFFFFFF
    body_len = sum(len(c) for c in chunks)
    return [_PREFIX.pack(MAGIC, len(header), body_len, crc) + header,
            *chunks]


def encode_frame(kind: str, meta: dict | None = None,
                 arrays: dict | None = None) -> bytes:
    """One contiguous wire frame for ``(kind, meta, arrays)``.
    ``meta`` must be JSON-serializable; ``arrays`` maps name ->
    ndarray (made C-contiguous here). Byte-identical to the
    concatenation of :func:`encode_frame_parts`."""
    return b"".join(encode_frame_parts(kind, meta, arrays))


# -- measured wire accounting ----------------------------------------
# Every frame that leaves through send_frame is counted here by KIND
# (its real encoded length — what actually crosses the TCP wire), so
# the bench's cluster_wire_reduction_vs_dense is MEASURED frame bytes,
# never a schedule-side estimate. Thread-mode clusters run both ends
# in one process; the kind split ('push' = worker->coordinator delta,
# 'center' = coordinator->worker pull) keeps the directions separate.

_WIRE_LOCK = threading.Lock()
_WIRE: dict[str, list[int]] = {}

#: frame kinds whose measured bytes also ride telemetry counters
#: (``cluster.wire_push_bytes`` / ``cluster.wire_center_bytes``) —
#: the hot-path payload directions; beats/polls stay out of the
#: counter namespace
_COUNTED_KINDS = ("push", "center")


def wire_stats_reset() -> None:
    with _WIRE_LOCK:
        _WIRE.clear()


def wire_stats() -> dict[str, dict[str, int]]:
    """``{kind: {"frames": n, "bytes": total}}`` since the last
    reset — the measured per-direction wire accounting."""
    with _WIRE_LOCK:
        return {k: {"frames": v[0], "bytes": v[1]}
                for k, v in _WIRE.items()}


def _account(kind: str, nbytes: int) -> None:
    with _WIRE_LOCK:
        slot = _WIRE.setdefault(kind, [0, 0])
        slot[0] += 1
        slot[1] += nbytes
    if kind in _COUNTED_KINDS:
        tevents.counter(f"cluster.wire_{kind}_bytes", nbytes)


def _send_parts(sock: socket.socket, parts: list,
                deadline: float | None) -> None:
    """Scatter-gather send of one frame's buffer list. ``sendmsg``
    walks the buffers in the kernel (bounded at 512 iovecs per call —
    comfortably under every IOV_MAX); a partial send resumes from the
    split point with memoryview slices. ``deadline`` bounds the WHOLE
    send, not each call: every retry's socket timeout is the time
    REMAINING, so a peer that trickle-drains a few KB per interval
    cannot keep the loop alive past the deadline (the ``sendall``
    contract this path replaces). Platforms without ``sendmsg`` fall
    back to ``sendall`` of the joined bytes — byte-identical on the
    wire by construction (the parts ARE the frame)."""
    if not hasattr(sock, "sendmsg"):
        sock.settimeout(deadline)
        # tda: ignore[TDA090] -- the parts ARE encode_frame_parts
        # output (send_frame built them two lines up): their join is
        # byte-identical to encode_frame, not an ad-hoc payload
        sock.sendall(b"".join(parts))
        return
    deadline_at = None if deadline is None \
        else time.monotonic() + deadline
    views = [memoryview(p) for p in parts if len(p)]
    while views:
        if deadline_at is None:
            sock.settimeout(None)
        else:
            remaining = deadline_at - time.monotonic()
            if remaining <= 0:
                raise socket.timeout(
                    "send deadline expired mid-frame")
            sock.settimeout(remaining)
        sent = sock.sendmsg(views[:512])
        while sent:
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


def send_frame(sock: socket.socket, kind: str,
               meta: dict | None = None, arrays: dict | None = None,
               *, deadline: float | None = DEFAULT_DEADLINE_SECONDS
               ) -> None:
    """Frame and send one message; ``deadline`` bounds the whole send
    (a full peer socket buffer must not wedge the sender forever)."""
    _inject_rpc()
    parts = encode_frame_parts(kind, meta, arrays)
    total = sum(len(p) for p in parts)
    _account(kind, total)
    try:
        _send_parts(sock, parts, deadline)
    except socket.timeout as e:
        raise TransportTimeout(
            f"send of {total}-byte {kind!r} frame timed out after "
            f"{deadline}s — peer wedged or partitioned") from e
    except (BrokenPipeError, ConnectionError, OSError) as e:
        raise TransportClosed(
            f"send of {kind!r} frame failed: {e}") from e


def _recv_exact(sock: socket.socket, n: int, deadline_at: float,
                what: str) -> bytes:
    """Exactly ``n`` bytes, every recv bounded by the remaining
    deadline; EOF mid-read is :class:`TransportClosed` naming how many
    bytes arrived (the truncated-frame diagnosis)."""
    parts, got = [], 0
    while got < n:
        remaining = deadline_at - time.monotonic()
        if remaining <= 0:
            raise TransportTimeout(
                f"receive deadline expired after {got}/{n} bytes "
                f"of {what}")
        sock.settimeout(remaining)
        try:
            chunk = sock.recv(min(n - got, _RECV_CHUNK))
        except socket.timeout as e:
            raise TransportTimeout(
                f"receive deadline expired after {got}/{n} bytes "
                f"of {what}") from e
        except (ConnectionError, OSError) as e:
            raise TransportClosed(
                f"connection lost after {got}/{n} bytes of {what}: "
                f"{e}") from e
        if not chunk:
            raise TransportClosed(
                f"peer closed after {got}/{n} bytes of {what} "
                f"(truncated frame)")
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


def parse_payload(header: bytes, body: bytes):
    """Decode a frame's header+body (CRC already verified) into
    ``(kind, meta, arrays)`` — shared by :func:`recv_frame` and the
    WAL's file reader (``cluster/wal.py``), so the wire format and the
    durable-record format can never drift."""
    try:
        doc = json.loads(header)
    except json.JSONDecodeError as e:
        raise TransportError(f"undecodable frame header: {e}") from e
    arrays, off = {}, 0
    for spec in doc.get("arrays", ()):
        dt = _check_dtype(np.dtype(spec["d"]))
        shape = tuple(int(x) for x in spec["s"])
        nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        if off + nbytes > len(body):
            raise TransportError(
                f"array {spec['n']!r} ({shape}, {dt}) overruns the "
                f"frame body ({off + nbytes} > {len(body)})")
        arrays[spec["n"]] = np.frombuffer(
            body, dtype=dt, count=int(np.prod(shape, dtype=np.int64)),
            offset=off).reshape(shape).copy()
        off += nbytes
    return doc.get("k", "?"), doc.get("meta", {}), arrays


def recv_frame(sock: socket.socket, *,
               deadline: float = DEFAULT_DEADLINE_SECONDS,
               max_frame: int = DEFAULT_MAX_FRAME_BYTES):
    """Receive one frame -> ``(kind, meta, arrays)`` with every
    blocking read bounded by ``deadline`` seconds from entry."""
    _inject_rpc()
    deadline_at = time.monotonic() + deadline
    raw = _recv_exact(sock, _PREFIX.size, deadline_at, "frame prefix")
    magic, hlen, blen, crc = _PREFIX.unpack(raw)
    if magic != MAGIC:
        raise TransportError(
            f"bad frame magic {magic!r} — peer is not speaking the "
            f"cluster transport (or the stream desynchronized)")
    if hlen > MAX_HEADER_BYTES:
        raise FrameTooLarge(
            f"header length {hlen} exceeds {MAX_HEADER_BYTES}")
    if blen > max_frame:
        raise FrameTooLarge(
            f"frame body of {blen} bytes exceeds max_frame="
            f"{max_frame} — refusing the allocation (corrupt length "
            f"prefix, or raise max_frame for genuinely larger models)")
    header = _recv_exact(sock, hlen, deadline_at, "frame header")
    body = _recv_exact(sock, blen, deadline_at, "frame body")
    got_crc = zlib.crc32(header)
    got_crc = zlib.crc32(body, got_crc) & 0xFFFFFFFF
    if got_crc != crc:
        raise TransportError(
            f"frame CRC mismatch (stored {crc:#010x}, computed "
            f"{got_crc:#010x}) — corrupted in flight")
    return parse_payload(header, body)


def connect(host: str, port: int, *,
            deadline: float = DEFAULT_DEADLINE_SECONDS,
            attempts: int = 40, retry_sleep: float = 0.25
            ) -> socket.socket:
    """Dial the coordinator with bounded patience: a worker racing the
    coordinator's bind retries ``ConnectionRefusedError`` briefly, and
    every attempt carries a connect timeout."""
    last: Exception | None = None
    for _ in range(max(1, attempts)):
        try:
            sock = socket.create_connection((host, port),
                                            timeout=deadline)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock
        except (ConnectionRefusedError, socket.timeout, OSError) as e:
            last = e
            time.sleep(retry_sleep)
    raise TransportClosed(
        f"could not reach coordinator at {host}:{port} after "
        f"{attempts} attempts: {last}")


def request(sock: socket.socket, kind: str,
            meta: dict | None = None, arrays: dict | None = None,
            *, deadline: float = DEFAULT_DEADLINE_SECONDS):
    """One request/response round trip on a worker's connection."""
    send_frame(sock, kind, meta, arrays, deadline=deadline)
    return recv_frame(sock, deadline=deadline)
