"""Length-prefixed TCP transport — framed numpy buffers, no pickle.

The cluster runtime's one wire format (TDA060's liveness spirit,
machine-checked here by TDA090): every message is a single FRAME with
an explicit length prefix, every blocking receive carries a DEADLINE,
and the payload is JSON metadata plus raw C-contiguous numpy buffers —
never pickled code, so a compromised or version-skewed peer can
corrupt a training run's numbers but can never execute anything.

Frame layout (all integers little-endian)::

    magic  b"TDAC"                      4 bytes
    u32    header length                (JSON, <= MAX_HEADER_BYTES)
    u64    body length                  (<= max_frame bytes)
    u32    CRC32 of header || body      (a torn/corrupt frame is
                                         DETECTED, mirroring the
                                         checkpoint footer contract)
    header JSON: {"k": kind, "meta": {...},
                  "arrays": [{"n": name, "d": dtype, "s": shape}, ...]}
    body   the arrays' raw bytes, concatenated in header order

Failure taxonomy — every receive path lands in exactly one:

  * :class:`TransportClosed` — EOF (peer died / socket slammed): a
    ``kill -9``'d worker is observed HERE, immediately;
  * :class:`TransportTimeout` — the deadline expired mid-receive (a
    network partition / ``cluster:rpc hang`` injection);
  * :class:`FrameTooLarge` — a length prefix past ``max_frame`` (a
    corrupt prefix must not become a multi-GB allocation);
  * :class:`TransportError` — bad magic, CRC mismatch, or a dtype the
    safe set does not admit (object dtypes would be pickle by the
    back door).

Fault seam ``cluster:rpc`` (``faults/registry.py``): injected at the
top of :func:`send_frame` and :func:`recv_frame` — ``oserror`` models
a torn connection, ``hang`` a partition that the recv deadline and the
coordinator's heartbeat timeout must observe, not wedge on.

Stdlib + numpy only: workers and coordinator use it before (and
without) any jax import.
"""

from __future__ import annotations

import json
import socket
import struct
import time
import zlib

import numpy as np

from tpu_distalg import faults

MAGIC = b"TDAC"
_PREFIX = struct.Struct("<4sIQI")  # magic, header len, body len, crc

#: refuse headers past this (a header is a few hundred bytes of JSON)
MAX_HEADER_BYTES = 1 << 20
#: default ceiling for one frame's body (center pytrees are MBs, not GBs)
DEFAULT_MAX_FRAME_BYTES = 1 << 28
#: default bound for any single blocking receive
DEFAULT_DEADLINE_SECONDS = 30.0
#: dtype kinds a frame may carry — everything numeric/bool/bytes-free;
#: 'O' (object) would be pickle by the back door and is refused on
#: BOTH ends
SAFE_DTYPE_KINDS = frozenset("biufc")

_RECV_CHUNK = 1 << 20


class TransportError(RuntimeError):
    """Malformed frame: bad magic, CRC mismatch, unsafe dtype."""


class TransportClosed(TransportError):
    """EOF — the peer died or closed mid-frame (a truncated frame is
    this, not a parse error: the bytes simply stopped)."""


class TransportTimeout(TransportError):
    """The receive deadline expired — a partition or a wedged peer."""


class FrameTooLarge(TransportError):
    """A length prefix past the configured ceiling."""


def _inject_rpc() -> None:
    """The ``cluster:rpc`` fault seam, folded into the transport's
    failure taxonomy: an injected ``oserror`` IS a torn connection,
    so it must surface as :class:`TransportClosed` — the error every
    handler/reconnect path already rides — not as a raw ``OSError``
    that would skewer a coordinator handler thread."""
    try:
        faults.inject("cluster:rpc")
    except OSError as e:
        raise TransportClosed(
            f"injected torn connection: {e}") from e


def _check_dtype(dt: np.dtype) -> np.dtype:
    dt = np.dtype(dt)
    if dt.kind not in SAFE_DTYPE_KINDS:
        raise TransportError(
            f"refusing dtype {dt!r} on the wire (kind {dt.kind!r}): "
            f"only plain numeric/bool buffers are framed — object "
            f"dtypes would be pickle by the back door")
    return dt


def encode_frame(kind: str, meta: dict | None = None,
                 arrays: dict | None = None) -> bytes:
    """One wire frame for ``(kind, meta, arrays)``. ``meta`` must be
    JSON-serializable; ``arrays`` maps name -> ndarray (made
    C-contiguous here)."""
    specs, chunks = [], []
    for name, arr in (arrays or {}).items():
        a = np.ascontiguousarray(arr)
        _check_dtype(a.dtype)
        specs.append({"n": str(name), "d": a.dtype.str,
                      "s": list(a.shape)})
        chunks.append(a.tobytes())
    header = json.dumps(
        {"k": kind, "meta": meta or {}, "arrays": specs},
        separators=(",", ":")).encode()
    if len(header) > MAX_HEADER_BYTES:
        raise FrameTooLarge(
            f"frame header of {len(header)} bytes exceeds "
            f"{MAX_HEADER_BYTES} — metadata belongs in arrays")
    body = b"".join(chunks)
    crc = zlib.crc32(header)
    crc = zlib.crc32(body, crc) & 0xFFFFFFFF
    return (_PREFIX.pack(MAGIC, len(header), len(body), crc)
            + header + body)


def send_frame(sock: socket.socket, kind: str,
               meta: dict | None = None, arrays: dict | None = None,
               *, deadline: float | None = DEFAULT_DEADLINE_SECONDS
               ) -> None:
    """Frame and send one message; ``deadline`` bounds the whole send
    (a full peer socket buffer must not wedge the sender forever)."""
    _inject_rpc()
    buf = encode_frame(kind, meta, arrays)
    try:
        sock.settimeout(deadline)
        sock.sendall(buf)
    except socket.timeout as e:
        raise TransportTimeout(
            f"send of {len(buf)}-byte {kind!r} frame timed out after "
            f"{deadline}s — peer wedged or partitioned") from e
    except (BrokenPipeError, ConnectionError, OSError) as e:
        raise TransportClosed(
            f"send of {kind!r} frame failed: {e}") from e


def _recv_exact(sock: socket.socket, n: int, deadline_at: float,
                what: str) -> bytes:
    """Exactly ``n`` bytes, every recv bounded by the remaining
    deadline; EOF mid-read is :class:`TransportClosed` naming how many
    bytes arrived (the truncated-frame diagnosis)."""
    parts, got = [], 0
    while got < n:
        remaining = deadline_at - time.monotonic()
        if remaining <= 0:
            raise TransportTimeout(
                f"receive deadline expired after {got}/{n} bytes "
                f"of {what}")
        sock.settimeout(remaining)
        try:
            chunk = sock.recv(min(n - got, _RECV_CHUNK))
        except socket.timeout as e:
            raise TransportTimeout(
                f"receive deadline expired after {got}/{n} bytes "
                f"of {what}") from e
        except (ConnectionError, OSError) as e:
            raise TransportClosed(
                f"connection lost after {got}/{n} bytes of {what}: "
                f"{e}") from e
        if not chunk:
            raise TransportClosed(
                f"peer closed after {got}/{n} bytes of {what} "
                f"(truncated frame)")
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


def parse_payload(header: bytes, body: bytes):
    """Decode a frame's header+body (CRC already verified) into
    ``(kind, meta, arrays)`` — shared by :func:`recv_frame` and the
    WAL's file reader (``cluster/wal.py``), so the wire format and the
    durable-record format can never drift."""
    try:
        doc = json.loads(header)
    except json.JSONDecodeError as e:
        raise TransportError(f"undecodable frame header: {e}") from e
    arrays, off = {}, 0
    for spec in doc.get("arrays", ()):
        dt = _check_dtype(np.dtype(spec["d"]))
        shape = tuple(int(x) for x in spec["s"])
        nbytes = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        if off + nbytes > len(body):
            raise TransportError(
                f"array {spec['n']!r} ({shape}, {dt}) overruns the "
                f"frame body ({off + nbytes} > {len(body)})")
        arrays[spec["n"]] = np.frombuffer(
            body, dtype=dt, count=int(np.prod(shape, dtype=np.int64)),
            offset=off).reshape(shape).copy()
        off += nbytes
    return doc.get("k", "?"), doc.get("meta", {}), arrays


def recv_frame(sock: socket.socket, *,
               deadline: float = DEFAULT_DEADLINE_SECONDS,
               max_frame: int = DEFAULT_MAX_FRAME_BYTES):
    """Receive one frame -> ``(kind, meta, arrays)`` with every
    blocking read bounded by ``deadline`` seconds from entry."""
    _inject_rpc()
    deadline_at = time.monotonic() + deadline
    raw = _recv_exact(sock, _PREFIX.size, deadline_at, "frame prefix")
    magic, hlen, blen, crc = _PREFIX.unpack(raw)
    if magic != MAGIC:
        raise TransportError(
            f"bad frame magic {magic!r} — peer is not speaking the "
            f"cluster transport (or the stream desynchronized)")
    if hlen > MAX_HEADER_BYTES:
        raise FrameTooLarge(
            f"header length {hlen} exceeds {MAX_HEADER_BYTES}")
    if blen > max_frame:
        raise FrameTooLarge(
            f"frame body of {blen} bytes exceeds max_frame="
            f"{max_frame} — refusing the allocation (corrupt length "
            f"prefix, or raise max_frame for genuinely larger models)")
    header = _recv_exact(sock, hlen, deadline_at, "frame header")
    body = _recv_exact(sock, blen, deadline_at, "frame body")
    got_crc = zlib.crc32(header)
    got_crc = zlib.crc32(body, got_crc) & 0xFFFFFFFF
    if got_crc != crc:
        raise TransportError(
            f"frame CRC mismatch (stored {crc:#010x}, computed "
            f"{got_crc:#010x}) — corrupted in flight")
    return parse_payload(header, body)


def connect(host: str, port: int, *,
            deadline: float = DEFAULT_DEADLINE_SECONDS,
            attempts: int = 40, retry_sleep: float = 0.25
            ) -> socket.socket:
    """Dial the coordinator with bounded patience: a worker racing the
    coordinator's bind retries ``ConnectionRefusedError`` briefly, and
    every attempt carries a connect timeout."""
    last: Exception | None = None
    for _ in range(max(1, attempts)):
        try:
            sock = socket.create_connection((host, port),
                                            timeout=deadline)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock
        except (ConnectionRefusedError, socket.timeout, OSError) as e:
            last = e
            time.sleep(retry_sleep)
    raise TransportClosed(
        f"could not reach coordinator at {host}:{port} after "
        f"{attempts} attempts: {last}")


def request(sock: socket.socket, kind: str,
            meta: dict | None = None, arrays: dict | None = None,
            *, deadline: float = DEFAULT_DEADLINE_SECONDS):
    """One request/response round trip on a worker's connection."""
    send_frame(sock, kind, meta, arrays, deadline=deadline)
    return recv_frame(sock, deadline=deadline)
