"""Command-line entry points — one subcommand per reference script.

The reference exposes its workloads as ``python <script>.py`` with
module-global knobs edited by hand (SURVEY.md §5 config); here every knob
is a CLI flag with the same name and default, e.g.::

    python -m tpu_distalg.cli ssgd --n-iterations 1500 --eta 0.1 \
        --mini-batch-fraction 0.1 --plot ssgd_acc_plot.png

Run ``--emulate N`` to execute on N virtual CPU devices (Spark
``local[*]``-style) when no TPU is attached.
"""

from __future__ import annotations

import argparse
import re
import sys
import time


def parse_mesh_shape(text: str) -> tuple[int, int]:
    """``'DxM'`` → ``(data, model)`` — the 2-D mesh config the
    partition-rule engine makes a knob instead of a code path
    (``parallel/partition.py``). ``'8x1'`` is pure data parallel,
    ``'2x4'`` puts 4-way model parallelism inside each data replica."""
    m = re.fullmatch(r"(\d+)[xX](\d+)", text.strip())
    if not m or int(m.group(1)) < 1 or int(m.group(2)) < 1:
        raise ValueError(
            f"--mesh-shape wants DATAxMODEL (e.g. 4x2), got {text!r}")
    return int(m.group(1)), int(m.group(2))


def _add_mesh_shape(p) -> None:
    """The one definition of the ``--mesh-shape`` flag (six subcommands
    carry it — a copy per parser would drift like the ``--n-slices``
    duplication it extends)."""
    p.add_argument("--mesh-shape", type=str, default=None,
                   metavar="DxM",
                   help="full 2-D mesh geometry data x model (e.g. "
                        "2x2); placement falls out of the workload's "
                        "partition rule table — replaces --n-slices")


def _mesh(args):
    from tpu_distalg.parallel import MeshContext

    # MeshContext is the SparkSession analogue: the one runtime object
    # every workload receives (its .mesh)
    shape = getattr(args, "mesh_shape", None)
    if shape:
        if getattr(args, "n_slices", 0) > 0:
            raise SystemExit(
                "--mesh-shape and --n-slices both set: --mesh-shape "
                "IS the full (data x model) geometry; drop --n-slices")
        try:
            data, model = parse_mesh_shape(shape)
        except ValueError as e:
            raise SystemExit(str(e)) from None
        # only the workloads whose rule tables name a model-axis
        # placement consume model>1 — everywhere else those devices
        # would be silent passengers, so say so instead of wasting
        # them quietly (ssgd validates and engages the tp split in
        # its own branch; als shards V over the model axis)
        if model > 1 and getattr(args, "cmd", None) not in (
                "ssgd", "als"):
            print(
                f"[mesh] warning: --mesh-shape {data}x{model} puts "
                f"{model}-way model parallelism on a workload whose "
                f"rule table has no model-axis placement — those "
                f"devices will idle; use --mesh-shape "
                f"{data * model}x1 (or --n-slices {data * model}) "
                f"for full data parallelism", file=sys.stderr)
        return MeshContext.create(data=data, model=model).mesh
    return MeshContext.create(
        data=args.n_slices if args.n_slices > 0 else None
    ).mesh


def _add_common(p, n_iterations, eta=None, frac=None, samplers=None,
                sync=False):
    p.add_argument("--n-slices", type=int, default=0,
                   help="data-axis size; 0 = all devices")
    _add_mesh_shape(p)
    p.add_argument("--n-iterations", type=int, default=n_iterations)
    if eta is not None:
        p.add_argument("--eta", type=float, default=eta)
        # the gradient/parameter sync schedule (parallel/comms.py) —
        # SGD-family trainers only (the others have no per-round model
        # sync to re-schedule)
        p.add_argument(
            "--comm", default="dense", metavar="SCHED",
            help="cross-shard sync schedule: dense (bitwise the "
                 "classic psum — default), bucketed[:elems] "
                 "(ppermute-chunk ring), hier[:groups] "
                 "(reduce-scatter intra-group / ring across groups / "
                 "all-gather), bf16, int8[:seed[:bucket]] (native "
                 "int8 wire: seeded stochastic rounding, int8 in both "
                 "ring phases), topk[:frac] (sparse allreduce + error "
                 "feedback). bucketed/int8 overlap their bucket "
                 "exchange with compute by default; append @seq for "
                 "the bitwise-identical sequential exchange (a no-op "
                 "for the single-bucket topk/hier). Emits "
                 "comm.bytes_wire/bytes_logical/rounds telemetry "
                 "counters per run")
    if sync:
        # stale-synchronous & elastic training (parallel/ssp.py +
        # parallel/membership.py) — the SGD-family trainers only
        p.add_argument(
            "--sync", default="bsp", metavar="MODE",
            help="synchronization discipline: bsp (lock-step, one "
                 "collective per step/round — bitwise the classic "
                 "trainer; default) or ssp[:s[:decay]] (stale-"
                 "synchronous: shards run up to s steps ahead of the "
                 "slowest, the merge runs once per s-tick window with "
                 "staleness-weighted averaging / delayed gradients, "
                 "and a clock vector gates bound violations — a "
                 "straggler no longer serializes every step). Seeded "
                 "shard:straggle / shard:leave --fault-plan rules "
                 "compile into deterministic straggler and elastic-"
                 "membership schedules; the same plan replays bitwise. "
                 "A checkpointed ssp run resumed with a different "
                 "--n-slices renegotiates the ring (membership epoch) "
                 "instead of rejecting")
    if frac is not None:
        p.add_argument("--mini-batch-fraction", type=float, default=frac)
        # TPU perf knobs (see ssgd.SSGDConfig.sampler for semantics);
        # each subcommand advertises only the samplers its training
        # path accepts
        p.add_argument("--sampler", default="bernoulli",
                       choices=samplers)
        p.add_argument("--x-dtype", default="float32",
                       choices=["float32", "bfloat16"])
        p.add_argument("--gather-block-rows", type=int, default=1024)
        p.add_argument("--fused-pack", type=int, default=16)
        p.add_argument("--shuffle-seed", type=int, default=None)
        p.add_argument("--mega-steps", type=int, default=None,
                       help="steps per megakernel launch "
                            "(sampler=fused_train); default auto-picks "
                            "the largest divisor of --n-iterations "
                            "<= 125 so any iteration count works")
    p.add_argument("--plot", type=str, default=None,
                   help="save an accuracy plot PNG here")
    p.add_argument("--quiet", action="store_true")
    _add_ckpt(p, 500)


def _add_data_backend(p, block_rows: int):
    """The data-placement knob (tpu_distalg/data/): where the workload's
    dataset bytes live — on-device HBM, host RAM, or a disk packed
    cache streamed block by block. A PLACEMENT knob, not an algorithm
    knob: staged batches are bitwise-identical across backends."""
    p.add_argument("--data-backend", default="resident",
                   choices=["resident", "virtual", "streamed"],
                   help="where the dataset lives: resident = device "
                        "HBM, virtual = host RAM, streamed = disk "
                        "packed cache (needs --stream-cache); virtual/"
                        "streamed stage sampled blocks through the "
                        "prefetch pipeline (tpu_distalg/data/)")
    p.add_argument("--stream-cache", type=str, default=None,
                   metavar="PATH",
                   help="packed-cache path for --data-backend "
                        "streamed (created on first use)")
    p.add_argument("--block-rows", type=int, default=block_rows,
                   help="rows per gathered block (the out-of-core "
                        "transfer granularity)")


def _add_telemetry(p):
    """Telemetry + chaos flags — on EVERY subcommand: structured JSONL
    runtime events (marks, spans, heartbeats, stalls, restarts) for the
    run, summarized by ``tda report DIR`` (tpu_distalg/telemetry/), and
    the deterministic fault-injection plan (tpu_distalg/faults/)."""
    p.add_argument("--telemetry-dir", type=str, default=None,
                   metavar="DIR",
                   help="write structured JSONL runtime events here "
                        "($TDA_TELEMETRY_DIR is the default when "
                        "unset); summarize with 'tda report DIR'")
    p.add_argument("--fault-plan", type=str, default=None,
                   metavar="SPEC",
                   help="deterministic fault-injection plan: inline "
                        "'seed=N;point@hit=kind[:arg];...' or a JSON "
                        "plan file ($TDA_FAULT_PLAN is the default; "
                        "points: ckpt:write, ckpt:read, cache:write, "
                        "data:gather, data:h2d, backend:init, "
                        "segment:run; kinds: oserror, hang, corrupt, "
                        "kill). The same plan+seed replays the same "
                        "failure sequence bitwise — see 'tda chaos'")
    p.add_argument("--tune", type=str, default="off", metavar="MODE",
                   help="platform-aware geometry (tpu_distalg/tune/): "
                        "'off' = the hand-pinned default tables, "
                        "'auto' = resolve comm schedule, bucket "
                        "elems, mesh shape, ps-shards/mode, block "
                        "sizes and pull-refresh cadence from this "
                        "rig's newest measured profile (run 'tda "
                        "tune' once), or a RIGPROFILE_*.json path. "
                        "Explicit flags always win; every resolved "
                        "knob logs a tune.* event with its WHY. "
                        "Tuning changes geometry, never determinism")


def _add_ckpt(p, every_default):
    """Checkpoint/watchdog flags — on EVERY subcommand, optimizer or
    not: the task-retry capability Spark gives every reference script
    (r4 verdict ask #5). State is tiny in each case (weights / centers
    / rank vector / path buffer / factor matrices)."""
    p.add_argument("--checkpoint-dir", type=str, default=None,
                   help="segmented checkpoint/resume directory")
    p.add_argument("--checkpoint-every", type=int, default=every_default)
    p.add_argument("--max-restarts", type=int, default=0,
                   help="auto-restart the run up to N times on crash or "
                        "NaN-guard trip; with --checkpoint-dir each "
                        "restart resumes from the latest checkpoint "
                        "(bitwise-identical to an uninterrupted run)")
    _add_telemetry(p)


def _report_optimizer(name, res, args, t):
    from tpu_distalg.utils import metrics

    if not args.quiet:
        print(f"Final w: {list(map(float, res.w))}")
    print(f"Final acc: {res.final_acc:.6f}")
    print(f"[{name}] {args.n_iterations} iterations in {t:.3f}s "
          f"({args.n_iterations / t:.1f} steps/s)")
    if args.plot:
        metrics.draw_acc_plot(res.accs, args.plot)
        print(f"saved plot: {args.plot}")


def main(argv=None):
    parser = argparse.ArgumentParser(prog="tpu_distalg")
    parser.add_argument("--emulate", type=int, default=0, metavar="N",
                        help="run on N virtual CPU devices")
    parser.add_argument("--profile", type=str, default=None, metavar="DIR",
                        help="capture a jax.profiler device trace of the "
                             "run into DIR (TensorBoard / Perfetto)")
    parser.add_argument("--multihost", action="store_true",
                        help="initialise the multi-host runtime "
                             "(jax.distributed over DCN) before building "
                             "the mesh; run the same command on every "
                             "host of the slice group")
    parser.add_argument("--coordinator-address", type=str, default=None,
                        help="host:port of process 0's coordinator "
                             "(with --multihost); omit on TPU pods and "
                             "managed clusters, where jax.distributed "
                             "auto-detects the topology")
    parser.add_argument("--num-processes", type=int, default=None,
                        help="total process count (with "
                             "--coordinator-address)")
    parser.add_argument("--process-id", type=int, default=None,
                        help="this process's rank (with "
                             "--coordinator-address)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("lr", help="full-batch logistic regression")
    _add_common(p, 1500, eta=0.1)

    p = sub.add_parser("ssgd", help="synchronous minibatch SGD")
    _add_common(p, 1500, eta=0.1, frac=0.1,
                samplers=["bernoulli", "fixed", "fused", "fused_gather",
                          "fused_train"], sync=True)
    p.add_argument("--lam", type=float, default=0.0)
    p.add_argument("--reg-type", default="l2",
                   choices=["none", "l2", "l1", "elastic_net"])
    p.add_argument("--stream-cache", type=str, default=None,
                   metavar="PATH",
                   help="train the streamed >HBM path from a disk-"
                        "backed packed dataset at PATH (created via "
                        "utils.datasets.streamed_packed_cache if "
                        "missing — see --stream-rows); sampled blocks "
                        "are host-gathered and staged per step "
                        "(models/ssgd_stream.py). Ignores --sampler/"
                        "--x-dtype/--shuffle-seed (the cache fixes "
                        "the bf16 dtype and row layout); rejects "
                        "--mega-steps.")
    p.add_argument("--stream-rows", type=int, default=1 << 22,
                   help="rows to generate when --stream-cache is new")

    for name in ("ma", "bmuf", "easgd"):
        p = sub.add_parser(name)
        _add_common(p, 1500 if name == "easgd" else 300, eta=0.1,
                    frac=0.1,
                    samplers=["bernoulli", "fused_gather",
                              "fused_train"], sync=True)
        p.add_argument("--n-local-iterations", type=int,
                       default=1 if name == "easgd" else 5)
        p.add_argument("--resample-per-local-step", action="store_true")

    p = sub.add_parser("kmeans")
    p.add_argument("--n-slices", type=int, default=0)
    _add_mesh_shape(p)
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--n-iterations", type=int, default=5)
    p.add_argument("--converge-dist", type=float, default=None)
    p.add_argument("--n-points", type=int, default=0,
                   help="0 = the reference's toy 6x2 matrix; else a "
                        "Gaussian mixture of this many points "
                        "(host-materialized, like the reference)")
    p.add_argument("--scale-points", type=int, default=0,
                   help="scale path: synthesize this many mixture "
                        "points ON DEVICE (host RAM O(k); overrides "
                        "--n-points)")
    p.add_argument("--dim", type=int, default=16,
                   help="point dimension for --scale-points")
    p.add_argument("--plot", type=str, default=None,
                   help="save a cluster scatter PNG (2-D data)")
    _add_data_backend(p, block_rows=2048)
    p.add_argument("--mini-batch-blocks", type=int, default=4,
                   help="blocks per shard per minibatch step "
                        "(minibatch engine)")
    p.add_argument("--minibatch-steps", type=int, default=0,
                   help="run the minibatch engine for N steps over the "
                        "ShardedDataset (0 = classic full-batch Lloyd "
                        "when --data-backend resident, 100 otherwise)")
    _add_ckpt(p, 100)

    p = sub.add_parser("pagerank")
    p.add_argument("--n-slices", type=int, default=0)
    _add_mesh_shape(p)
    p.add_argument("--n-iterations", type=int, default=10)
    p.add_argument("--q", type=float, default=0.15)
    p.add_argument("--mode", default=None,
                   choices=["reference", "standard"],
                   help="default: reference for the resident backend, "
                        "standard for the streamed/virtual engine "
                        "(reference-parity needs resident per-vertex "
                        "receive masks)")
    p.add_argument("--scatter", default="auto",
                   choices=["auto", "pallas", "xla", "spmv"],
                   help="standard-mode sweep path: the Pallas windowed "
                        "one-hot-MXU scatter (when the graph admits a "
                        "window plan), the XLA segment_sum, or the "
                        "fully-fused tiled SpMV kernel ('spmv': gather "
                        "AND scatter in one Pallas launch)")
    p.add_argument("--n-vertices", type=int, default=0,
                   help="0 = the reference's 4-edge toy graph; else an "
                        "Erdős–Rényi graph of this many vertices")
    p.add_argument("--edge-file", type=str, default=None,
                   help="load the graph from a '#'-commented whitespace "
                        "edge-list file (overrides --n-vertices); parsed "
                        "by the native C++ ingest runtime")
    p.add_argument("--edge-capacity", type=int, default=1 << 24,
                   help="max edges the file parser may return")
    p.add_argument("--data-backend", default="resident",
                   choices=["resident", "virtual", "streamed"],
                   help="where the EDGE SET lives: resident = device "
                        "HBM (the fused-SpMV/Pallas/XLA sweeps; "
                        "self-caps at ~12M vertices on the VMEM "
                        "guard), streamed = a dst-sorted CSR edge-"
                        "block disk cache swept out-of-core "
                        "(tpu_distalg/graphs/ — only O(V) state in "
                        "HBM; sparse rank combine), virtual = the "
                        "same engine from host RAM. A resident "
                        "request past the guard warns and degrades "
                        "to streamed instead of dying")
    p.add_argument("--stream-cache", type=str, default=None,
                   metavar="PATH",
                   help="edge-block cache path for the streamed/"
                        "virtual engine (default: a geometry-keyed "
                        "path under $TMPDIR, built on first use)")
    p.add_argument("--block-edges", type=int, default=1 << 16,
                   help="edges per streamed block (the out-of-core "
                        "transfer granularity)")
    p.add_argument("--combine", default="auto",
                   choices=["auto", "sparse", "dense"],
                   help="streamed engine's cross-shard rank combine: "
                        "sparse = ring all-gather of each shard's "
                        "distinct-destination (value, index) pairs "
                        "(comms.sparse_allreduce — the power-law "
                        "win), dense = O(V) psum; auto picks by wire-"
                        "byte accounting")
    _add_ckpt(p, 5)

    p = sub.add_parser("closure", help="transitive closure")
    p.add_argument("--n-slices", type=int, default=0)
    _add_mesh_shape(p)
    p.add_argument("--n-vertices", type=int, default=0)
    p.add_argument("--sparse", action="store_true",
                   help="sort-dedup path-set closure (O(closure) memory "
                        "— required beyond ~30k vertices). NOTE: with "
                        "--n-vertices the generated graph is a chain "
                        "forest, not the dense mode's Erdős–Rényi graph "
                        "(an ER closure is an inherently quadratic "
                        "output); results are not comparable across "
                        "modes")
    p.add_argument("--capacity", type=int, default=0,
                   help="sparse path-buffer capacity; 0 = 8x edges")
    _add_ckpt(p, 8)

    p = sub.add_parser("als", help="ALS matrix decomposition")
    p.add_argument("--n-slices", type=int, default=0)
    _add_mesh_shape(p)
    p.add_argument("--m", type=int, default=100)
    p.add_argument("--n", type=int, default=500)
    p.add_argument("--k", type=int, default=10)
    p.add_argument("--lam", type=float, default=0.01)
    p.add_argument("--n-iterations", type=int, default=5)
    _add_data_backend(p, block_rows=256)
    p.add_argument("--rmse-every", type=int, default=1,
                   help="streamed/virtual backends: stream one extra "
                        "RMSE evaluation pass every N sweeps (0 = once "
                        "after the final sweep — each pass re-reads R)")
    _add_ckpt(p, 5)

    p = sub.add_parser(
        "serve",
        help="micro-batched online serving from checkpointed "
             "artifacts: bounded queue -> deadline-or-size dispatch -> "
             "one batched predict per micro-batch -> scatter replies; "
             "ALS top-k rides the fused Pallas matmul+top-k kernel "
             "with model-axis-sharded item factors; runs a closed-loop "
             "demo load and prints qps/p50/p99")
    p.add_argument("--artifact", action="append", required=True,
                   metavar="PATH",
                   help="checkpoint directory to serve (repeatable); "
                        "training CLIs run with --checkpoint-dir print "
                        "the machine-readable 'artifact_path: PATH' "
                        "line this flag consumes")
    p.add_argument("--n-slices", type=int, default=0,
                   help="data-axis size; 0 = all devices")
    p.add_argument("--model-slices", type=int, default=1,
                   help="mesh model-axis size: ALS item factors are "
                        "sharded across it; per-shard top-k candidates "
                        "merge via the --comm schedule")
    p.add_argument("--max-batch", type=int, default=16,
                   help="dispatch a micro-batch at this many requests")
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="... or this many ms after its first request")
    p.add_argument("--queue-depth", type=int, default=128,
                   help="bounded request queue; a full queue SHEDS "
                        "(reply carries ServeOverloadError) instead of "
                        "growing or dying")
    p.add_argument("--k-top", type=int, default=10,
                   help="ALS recommendations per request")
    p.add_argument("--comm", default="sparse",
                   choices=["sparse", "dense"],
                   help="ALS cross-shard candidate merge: sparse = "
                        "ring all-gather of each shard's k (value, "
                        "index) pairs (8k(S-1) B/request), dense = "
                        "all-gather of the full score blocks (the O(N) "
                        "baseline)")
    p.add_argument("--requests", type=int, default=256,
                   help="closed-loop demo load per served model")
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed-loop worker count")
    _add_telemetry(p)

    p = sub.add_parser(
        "cluster",
        help="multi-process elastic runtime (tpu_distalg/cluster/): a "
             "coordinator process plus N worker processes exchanging "
             "staleness-weighted deltas with a parameter-server tier "
             "over a framed TCP transport — kill -9 a worker mid-"
             "window and training continues at reduced quorum; a "
             "fresh worker rejoins by pulling the center")
    p.add_argument("--role", default="local",
                   choices=["coordinator", "worker", "local",
                            "replica", "router"],
                   help="coordinator = serve rendezvous/clock/PS on "
                        "--host:--port; worker = join a coordinator at "
                        "--connect; local = spawn a coordinator plus "
                        "--workers N workers on this machine (the "
                        "test/bench mode); replica = one serving "
                        "replica of the distributed serving plane "
                        "(loads --artifact, scores over the framed "
                        "transport, hot-swappable); router = the "
                        "serving front end dispatching at --replicas")
    p.add_argument("--workers", type=int, default=3,
                   help="worker slot count (coordinator/local roles)")
    p.add_argument("--spawn", default="process",
                   choices=["process", "thread"],
                   help="local role: real worker processes (kill -9 is "
                        "the genuine article) or threads (same "
                        "protocol/sockets, fast for tests)")
    p.add_argument("--coordinator-spawn", default="inproc",
                   choices=["inproc", "process"],
                   help="local role: run the coordinator in-process "
                        "(a cluster:coordinator kill cell slams its "
                        "sockets) or as a REAL subprocess (the kill "
                        "is a genuine kill -9 of the control plane; "
                        "the launcher respawns it on the same port "
                        "and it recovers from the durable WAL — "
                        "needs --checkpoint-dir)")
    p.add_argument("--connect", type=str, default=None,
                   metavar="HOST:PORT",
                   help="worker role: the coordinator's address")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="coordinator bind port (0 = ephemeral, "
                        "printed at start)")
    p.add_argument("--slot", type=int, default=None,
                   help="worker role: requested slot (default: any "
                        "free)")
    p.add_argument("--rejoin", action="store_true",
                   help="worker role: this is a replacement for a "
                        "departed slot")
    p.add_argument("--admit-at", type=int, default=None,
                   help="worker role: pin admission to this window "
                        "(the launcher's replay-determinism hook)")
    p.add_argument("--n-windows", type=int, default=24,
                   help="merge windows to train (each = s local ticks "
                        "per worker)")
    p.add_argument("--sync", default="ssp:4", metavar="MODE",
                   help="staleness discipline ssp[:s[:decay]] — the "
                        "cluster is stale-synchronous by construction "
                        "(parallel/ssp.py semantics over the wire); "
                        "s = ticks per window AND the clock gate's "
                        "bound, decay = the PS merge weight decay^age")
    p.add_argument("--algo", default="ssgd",
                   choices=["ssgd", "local_sgd"],
                   help="the existing trainer each worker wraps "
                        "between push/pull seams")
    p.add_argument("--ps-shards", type=int, default=2,
                   help="parameter-server tier width: the center is "
                        "split across this many PS shards per the "
                        "model's partition rule table (uneven splits "
                        "are first-class)")
    p.add_argument("--comm", default="dense", metavar="SCHED",
                   help="cluster wire schedule: dense (f32 snapshots, "
                        "the pre-compression protocol bit-for-bit), "
                        "int8[:seed] (seeded stochastic rounding, "
                        "~1 byte/elem both directions) or topk[:frac] "
                        "((value,index) pairs with worker-side error "
                        "feedback; pulls ride the int8 codec) — "
                        "compressed pushes overlap the next window's "
                        "compute on a background sender; append @seq "
                        "to force synchronous pushes (e.g. int8@seq)")
    p.add_argument("--ps-mode", default="replicated",
                   choices=["replicated", "rowstore"],
                   help="PS tier state layout: replicated = each "
                        "shard holds dense slices, merges whole "
                        "deltas (the pre-rowstore protocol "
                        "bit-for-bit); rowstore = shards own disjoint "
                        "leading-dim row ranges (partition rule "
                        "table), pushes carry {leaf}.rows index "
                        "arrays and merge row-wise with per-row "
                        "versions — sparse pulls/pushes for models "
                        "bigger than one host")
    p.add_argument("--pull-refresh-windows", type=int, default=None,
                   metavar="N",
                   help="compressed-pull refresh cadence: every Nth "
                        "commit ships a dense version-pinned pull "
                        "bounding the pull-noise random walk "
                        "(default: the tuner's table value; --tune "
                        "auto re-derives it from the measured wire)")
    p.add_argument("--policy", default="elastic",
                   choices=["elastic", "restart"],
                   help="death handling: elastic = continue at "
                        "reduced quorum + rejoin; restart = abort and "
                        "respawn everything from the checkpoint (the "
                        "measured BSP-restart baseline)")
    p.add_argument("--rejoin-after", type=int, default=3,
                   help="local elastic role: windows a killed slot "
                        "stays away before its replacement is "
                        "admitted")
    p.add_argument("--heartbeat-timeout", type=float, default=5.0,
                   help="seconds of worker silence before the "
                        "coordinator declares it dead (EOF on its "
                        "connection is detected immediately)")
    p.add_argument("--heartbeat-interval", type=float, default=0.5,
                   help="seconds between worker liveness beats")
    p.add_argument("--rpc-deadline", type=float, default=30.0,
                   help="bound on any single blocking transport "
                        "round trip")
    p.add_argument("--reconnect-grace", type=float, default=1.0,
                   help="seconds a connection's EOF leaves its slot "
                        "SUSPECT before the death fires — the window "
                        "a reconnecting worker's re-dial has to race "
                        "the EOF sweep without burning a membership "
                        "epoch")
    p.add_argument("--n-rows", type=int, default=4096,
                   help="training rows of the shared synthetic task")
    p.add_argument("--train-json", type=str, default=None,
                   metavar="JSON",
                   help="coordinator role plumbing: the EXACT "
                        "TrainTask as JSON (the local launcher's "
                        "subprocess handoff — every field, not just "
                        "--algo/--n-rows; overrides both)")
    p.add_argument("--artifact", type=str, default=None,
                   metavar="CKPT_DIR",
                   help="replica role: checkpoint directory to serve "
                        "(the artifact_path: line a training CLI "
                        "prints)")
    p.add_argument("--replica-shards", type=int, default=1,
                   help="replica role: total model-axis shard count "
                        "of the fleet this replica belongs to")
    p.add_argument("--shard", type=int, default=0,
                   help="replica role: this replica's model-axis "
                        "shard index")
    p.add_argument("--k-top", type=int, default=10,
                   help="serving plane: top-k candidates per ALS "
                        "retrieval request")
    p.add_argument("--merge", default="sparse",
                   choices=["sparse", "dense"],
                   help="serving plane: cross-replica ALS candidate "
                        "merge — sparse (value,index) pair merge or "
                        "the dense score-block all-gather baseline")
    p.add_argument("--replicas", type=str, default=None,
                   metavar="HOST:PORT[,HOST:PORT...]",
                   help="router role: the replica fleet's addresses")
    p.add_argument("--dispatch", default="least_loaded",
                   choices=["least_loaded", "consistent_hash"],
                   help="router role: dispatch policy")
    p.add_argument("--serve-mode", default="routed",
                   choices=["routed", "sharded"],
                   help="router role: routed = each request to ONE "
                        "replica (redundancy, re-route on death); "
                        "sharded = fan out to every model-axis shard "
                        "and merge candidates")
    p.add_argument("--wal-dir", type=str, default=None,
                   metavar="DIR",
                   help="router role: durable admission/routing WAL — "
                        "a restarted router replays it and rebinds "
                        "the same port")
    p.add_argument("--deadline", type=float, default=600.0,
                   help="local/coordinator roles: give up if the run "
                        "is still incomplete after this many seconds")
    _add_ckpt(p, 8)

    p = sub.add_parser("mc", help="Monte-Carlo pi")
    p.add_argument("--n-slices", type=int, default=0)
    _add_mesh_shape(p)
    p.add_argument("--n", type=int, default=400_000)
    p.add_argument("--max-restarts", type=int, default=0,
                   help="retry the (stateless, deterministic) estimate "
                        "up to N times on a device crash")
    _add_telemetry(p)

    p = sub.add_parser(
        "chaos",
        help="run a small workload twice — undisturbed, then under an "
             "injected fault schedule with the full recovery stack "
             "armed — and verify the recovered final state is bitwise-"
             "equal (rc 1 on mismatch)")
    p.add_argument("--workload", default="lr",
                   choices=["lr", "ssgd", "kmeans", "als",
                            "kmeans_stream", "pagerank_stream",
                            "serve", "ssp", "cluster",
                            "cluster_serve", "rowstore"])
    p.add_argument("--n-slices", type=int, default=0)
    _add_mesh_shape(p)
    p.add_argument("--n-iterations", type=int, default=None,
                   help="override the workload's small default")
    p.add_argument("--checkpoint-every", type=int, default=None)
    p.add_argument("--max-restarts", type=int, default=3,
                   help="restart budget for the chaos run")
    p.add_argument("--spawn", default="thread",
                   choices=["thread", "process"],
                   help="cluster workload only: thread-mode workers "
                        "(fast smoke — the bench fast path runs this) "
                        "or real worker processes (a cluster:"
                        "coordinator kill is then a mid-window kill "
                        "of the in-process coordinator either way; "
                        "the genuine subprocess kill -9 is 'tda "
                        "cluster --coordinator-spawn process')")
    p.add_argument("--comm", default="dense", metavar="SCHED",
                   help="cluster/rowstore workloads only: the wire "
                        "schedule both the undisturbed and the chaos "
                        "run use (dense/int8[:seed]/topk[:frac]) — "
                        "the compression×chaos composition acceptance "
                        "is 'tda chaos --workload cluster --comm "
                        "int8' (and --workload rowstore for the "
                        "sparse row wire)")
    p.add_argument("--workdir", type=str, default=None,
                   help="checkpoint scratch directory (default: a "
                        "fresh temp dir, removed on success)")
    _add_telemetry(p)

    p = sub.add_parser(
        "tune",
        help="measure this rig — framed-TCP loopback bandwidth/RTT, "
             "host memcpy, matmul FLOP/s, host RAM, per---comm codec "
             "throughput, backend init time, optionally a device "
             "collective — and persist a versioned rig-tagged "
             "RigProfile JSON; every subcommand's '--tune auto' then "
             "resolves its geometry from the newest profile via the "
             "cost model (tune/resolve.py)")
    p.add_argument("--out-dir", type=str, default=None, metavar="DIR",
                   help="profile directory (default $TDA_PROFILE_DIR "
                        "or ./.tda_profiles)")
    p.add_argument("--seed", type=int, default=0,
                   help="measurement seed (profiles are seeded and "
                        "deterministic modulo the measured timings)")
    p.add_argument("--quick", action="store_true",
                   help="smaller working sets (smoke/CI tier; the "
                        "artifact records quick=true)")
    p.add_argument("--no-backend-init", action="store_true",
                   help="skip the subprocess-timed backend init "
                        "measurement (the slowest pass)")
    p.add_argument("--collective", action="store_true",
                   help="also measure a device collective (imports "
                        "jax and builds the mesh; omit for the "
                        "jax-free host-only profile)")
    p.add_argument("--n-slices", type=int, default=0,
                   help="with --collective: data-axis size; 0 = all "
                        "devices")
    _add_mesh_shape(p)
    p.add_argument("--telemetry-dir", type=str, default=None,
                   metavar="DIR",
                   help="record the profiling pass as telemetry "
                        "events (a 'tune' span)")

    p = sub.add_parser(
        "lint",
        help="static analysis for the framework's own invariants "
             "(TDA0xx rules: determinism, trace purity, concurrency, "
             "fault-seam coverage, Pallas hygiene); exits 1 on "
             "un-baselined violations; chain-runs ruff when installed")
    from tpu_distalg.analysis import cli as lint_cli

    lint_cli.add_parser_args(p)
    p.add_argument("--telemetry-dir", type=str, default=None,
                   metavar="DIR",
                   help="record the lint run as telemetry events "
                        "(a 'lint' span + per-rule counters)")

    p = sub.add_parser(
        "protocol",
        help="extract the cluster wire contract from source (frame "
             "kinds, payload keys, reply pairings, fencing, WAL "
             "records) as a deterministic table; --check pins "
             "docs/PROTOCOL.md against it")
    lint_cli.add_protocol_args(p)
    p.add_argument("--telemetry-dir", type=str, default=None,
                   metavar="DIR",
                   help="record the extraction as telemetry events "
                        "(a 'protocol' span)")

    p = sub.add_parser("report",
                       help="summarize a telemetry event log: phase "
                            "durations, stalls, backend-init attempts, "
                            "restarts, last heartbeat, metrics; "
                            "several dirs (or a parent of per-worker "
                            "dirs, e.g. a 'tda cluster' telemetry "
                            "root) render ONE merged report with "
                            "per-worker columns for the ssp.*/"
                            "cluster.* counters")
    p.add_argument("dir", nargs="+",
                   help="telemetry directory (of events-*.jsonl), one "
                        "event file, a parent directory of per-worker "
                        "telemetry dirs, or several of these")
    p.add_argument("--json", action="store_true",
                   help="print the full summary as JSON (for CI)")

    args = parser.parse_args(argv)

    if args.cmd == "lint":
        # pure source analysis — no backend, no mesh, no jax import
        from tpu_distalg import telemetry
        from tpu_distalg.analysis import cli as lint_cli

        telemetry.configure(args.telemetry_dir)
        return lint_cli.run_lint(args)

    if args.cmd == "protocol":
        # pure source analysis — no backend, no mesh, no jax import
        from tpu_distalg import telemetry
        from tpu_distalg.analysis import cli as lint_cli

        telemetry.configure(args.telemetry_dir)
        return lint_cli.run_protocol(args)

    if args.cmd == "tune":
        # host-only measurement — jax-free unless --collective asks
        # for the device pass
        from tpu_distalg import telemetry

        telemetry.configure(args.telemetry_dir)
        return _run_tune(args)

    if args.cmd == "report":
        # pure log analysis — no backend, no mesh, no jax import
        from tpu_distalg.telemetry import report as treport

        try:
            return treport.report_main(args.dir, as_json=args.json)
        except FileNotFoundError as e:
            # a typo'd path is the expected human error here — message,
            # not traceback
            print(f"tda report: {e}", file=sys.stderr)
            return 2

    from tpu_distalg import faults, telemetry

    tdir = getattr(args, "telemetry_dir", None)
    if args.cmd == "cluster" and args.role == "local" and tdir:
        # per-process telemetry layout: the coordinator's events land
        # under DIR/coordinator, each spawned worker's under
        # DIR/worker-N — 'tda report DIR' merges them with per-worker
        # columns (configured here so no stray root event file is
        # left behind)
        import os as _os

        tdir = _os.path.join(tdir, "coordinator")
    telemetry.configure(tdir)
    if args.cmd != "chaos":
        # the chaos harness owns the registry lifecycle itself (it runs
        # an undisturbed reference first); everywhere else the plan is
        # live for the whole run
        faults.configure(getattr(args, "fault_plan", None))
    if getattr(args, "checkpoint_dir", None):
        # SIGTERM/SIGINT become a graceful "checkpoint at the next
        # segment boundary, then exit PREEMPTED_RC" request
        # (faults/preempt.py) — the spot-VM/eviction contract every
        # production scheduler assumes. Only when a checkpoint dir
        # exists to satisfy the request: a non-checkpointed run has no
        # boundary to save at, and swallowing its SIGTERM/first-SIGINT
        # would make it HARDER to stop, not more graceful.
        faults.preempt.install()

    # platform-aware geometry: BEFORE the jax import and mesh build
    # (the resolver may set --mesh-shape) and with the raw argv in
    # hand so explicitly spelled flags win over resolved values
    _apply_tune(args, argv if argv is not None else sys.argv[1:])

    if args.emulate:
        from tpu_distalg.parallel.mesh import emulate_devices

        emulate_devices(args.emulate)

    if args.multihost:
        from tpu_distalg.parallel.mesh import multihost_initialize

        if (args.coordinator_address is None
                and (args.num_processes is not None
                     or args.process_id is not None)):
            parser.error(
                "--num-processes/--process-id require "
                "--coordinator-address (omit all three to auto-detect)"
            )
        kwargs = {
            k: v for k, v in (
                ("coordinator_address", args.coordinator_address),
                ("num_processes", args.num_processes),
                ("process_id", args.process_id),
            ) if v is not None
        }
        multihost_initialize(**kwargs)

    import jax  # after emulation setup

    from tpu_distalg.utils import profiling

    # stall threshold well above the legitimately silent multi-minute
    # phases a healthy run contains (first XLA/Mosaic compiles, the
    # spmv plan's host sorts) — marks land at phase boundaries, not
    # inside them, and a stall line on a healthy run muddies the one
    # signal built to diagnose real hangs
    hb = telemetry.start_heartbeat(stall_after=600.0)
    try:
        with profiling.maybe_trace(args.profile):
            with telemetry.span(f"cli:{args.cmd}"):
                return _dispatch(args, jax)
    except faults.Preempted as e:
        # the graceful exit: the boundary checkpoint is already on
        # disk — re-running the same command resumes bitwise
        print(f"[preempted] checkpoint saved at step {e.step}; "
              f"re-run the same command to resume "
              f"(rc={faults.PREEMPTED_RC})", file=sys.stderr)
        return faults.PREEMPTED_RC
    finally:
        if hb is not None:
            hb.stop()


#: --tune knob -> (argparse dest, the CLI option strings that mark it
#: explicitly spelled). A knob is applied only where the subcommand
#: actually grew the flag; explicit flags always win.
_TUNE_FLAG_KNOBS = (
    ("comm", "comm", ("--comm",)),
    ("mesh_shape", "mesh_shape", ("--mesh-shape",)),
    ("ps_shards", "ps_shards", ("--ps-shards",)),
    ("ps_mode", "ps_mode", ("--ps-mode",)),
    ("block_rows", "block_rows", ("--block-rows",)),
    ("block_edges", "block_edges", ("--block-edges",)),
    ("pull_refresh_windows", "pull_refresh_windows",
     ("--pull-refresh-windows",)),
)


def _spelled_options(argv) -> set:
    """The long-option strings the user actually typed (``--opt`` and
    ``--opt=value`` spellings both count)."""
    return {a.split("=", 1)[0] for a in argv if a.startswith("--")}


def _tune_workload(args, ttune):
    """The workload descriptor the resolver prices this subcommand
    against."""
    if args.cmd == "cluster":
        # the coordinator's TrainTask: breast-cancer-shaped synthetic
        # two-class rows (30 features + bias), host TCP wire
        return ttune.Workload(
            d=31, n_rows=getattr(args, "n_rows", 0) or 0,
            n_workers=getattr(args, "workers", None)
            or ttune.defaults.CLUSTER_SLOTS,
            family="data", transport="host")
    family = {"kmeans": "kmeans", "als": "als", "pagerank": "graph",
              "closure": "graph"}.get(args.cmd, "data")
    # the reference task's model dim (breast-cancer: 30 features +
    # bias); graph/kmeans block knobs scale from bandwidth, not d
    return ttune.Workload(
        d=31, n_rows=getattr(args, "n_rows", 0) or 0,
        family=family, transport="device",
        n_shards=getattr(args, "n_slices", 0) or None)


def _apply_tune(args, argv) -> None:
    """Resolve ``--tune`` into the args namespace (tentpole wiring):
    load the requested profile, price the workload, and overwrite
    every resolved knob the subcommand exposes — except knobs the
    user explicitly spelled, which always win. Logged per knob as
    ``tune.*`` telemetry with the WHY."""
    mode = getattr(args, "tune", "off") or "off"
    if mode == "off":
        return
    import socket

    from tpu_distalg import tune as ttune

    if mode == "auto":
        profile, _ = ttune.newest_profile(rig=socket.gethostname())
        if profile is None:
            print("tda --tune auto: no profile for this rig (run "
                  "'tda tune' once); table defaults stand",
                  file=sys.stderr)
            return
    else:
        try:
            profile = ttune.load_profile(mode)
        except ttune.ProfileError as e:
            raise SystemExit(f"--tune: {e}")
    spelled = _spelled_options(argv)
    explicit = {
        knob: getattr(args, dest)
        for knob, dest, opts in _TUNE_FLAG_KNOBS
        if hasattr(args, dest) and any(o in spelled for o in opts)}
    res = ttune.resolve(profile, _tune_workload(args, ttune),
                        explicit=explicit)
    for knob, dest, _opts in _TUNE_FLAG_KNOBS:
        if not hasattr(args, dest):
            continue
        c = res.choices[knob]
        if c.source != "resolved" or c.value is None:
            continue
        setattr(args, dest,
                res.comm_string() if knob == "comm" else c.value)
    args._tune_profile_id = res.profile_id
    ttune.emit_resolution(res)
    if not getattr(args, "quiet", False):
        for knob in ttune.KNOBS:
            c = res.choices[knob]
            print(f"tune[{knob}]: {c.value} ({c.source}) {c.why}",
                  file=sys.stderr)


def _run_tune(args):
    """``tda tune`` — the seeded profiling pass: measure the rig,
    persist the versioned rig-tagged RigProfile artifact."""
    import os

    from tpu_distalg import telemetry
    from tpu_distalg import tune as ttune

    collective = None
    backend = (os.environ.get("JAX_PLATFORMS") or "cpu"
               ).split(",")[0] or "cpu"
    with telemetry.span("cli:tune"):
        if args.collective:
            import jax

            backend = jax.default_backend()
            collective = ttune.measure_collective(_mesh(args))
        m = ttune.measure_rig(
            seed=args.seed, quick=args.quick,
            include_backend_init=not args.no_backend_init,
            collective=collective)
        # the one wall-clock read: created_unix orders profile
        # artifacts on disk and tags when the rig was measured — it
        # never influences run behavior or replay
        created = time.time()  # tda: ignore[TDA001] -- artifact timestamp, not run state
        profile = ttune.build_profile(
            m, created_unix=created, seed=args.seed, backend=backend)
        path = ttune.save_profile(profile, args.out_dir)
    lb = m["loopback"]
    print(f"tune: rig={profile['rig']} backend={backend} "
          f"id={profile['profile_id']}")
    print(f"tune: loopback {lb['bandwidth_bytes_s'] / 1e6:.0f} MB/s "
          f"rtt {lb['rtt_s'] * 1e6:.0f}us | memcpy "
          f"{m['memcpy_bytes_s'] / 1e9:.1f} GB/s | matmul "
          f"{m['matmul_flops_s'] / 1e9:.1f} GFLOP/s")
    for name, rates in sorted(m["codecs"].items()):
        print(f"tune: codec {name}: encode "
              f"{rates['encode_elems_s'] / 1e6:.1f} Melem/s, decode "
              f"{rates['decode_elems_s'] / 1e6:.1f} Melem/s")
    if collective:
        print(f"tune: collective "
              f"{collective['bandwidth_bytes_s'] / 1e6:.0f} MB/s "
              f"rtt {collective['rtt_s'] * 1e6:.0f}us over "
              f"{collective['n_shards']} shards")
    if m.get("backend_init_s") is not None:
        print(f"tune: backend init {m['backend_init_s']:.1f}s")
    print(f"tune: wrote {path}")
    return 0


def _run_cluster(args):
    """``tda cluster`` — the multi-process elastic runtime."""
    import json as _json
    import os

    from tpu_distalg import cluster as clus
    from tpu_distalg import telemetry
    from tpu_distalg.parallel import ssp as pssp

    if args.role in ("replica", "router"):
        return _run_serving_plane(args)
    spec = pssp.SyncSpec.parse(args.sync)
    if not spec.is_ssp:
        raise SystemExit(
            "the cluster runtime is stale-synchronous by construction "
            "— --sync ssp[:s[:decay]] (a BSP cluster is the restart-"
            "policy baseline the bench measures, not a mode)")
    err = lambda m: print(m, file=sys.stderr)  # noqa: E731
    if args.role == "worker":
        if not args.connect:
            raise SystemExit("--role worker needs --connect HOST:PORT")
        host, _, port = args.connect.rpartition(":")
        stats = clus.run_worker(
            host or "127.0.0.1", int(port), slot=args.slot,
            rejoin=args.rejoin, admit_at=args.admit_at, logger=err)
        print("cluster_worker: " + _json.dumps(
            {k: v for k, v in stats.items()
             if not isinstance(v, list)}))
        return 0
    plan = args.fault_plan or os.environ.get("TDA_FAULT_PLAN") or None
    train = (clus.TrainTask(**_json.loads(args.train_json))
             if args.train_json
             else clus.TrainTask(algo=args.algo, n_rows=args.n_rows))
    extra = {}
    if args.pull_refresh_windows is not None:
        extra["pull_refresh_windows"] = args.pull_refresh_windows
    cfg = clus.ClusterConfig(
        n_slots=args.workers, n_windows=args.n_windows,
        staleness=spec.staleness, decay=spec.decay,
        ps_shards=args.ps_shards, host=args.host, port=args.port,
        heartbeat_timeout=args.heartbeat_timeout,
        heartbeat_interval=args.heartbeat_interval,
        rpc_deadline=args.rpc_deadline,
        reconnect_grace=args.reconnect_grace,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        policy=args.policy, plan_spec=plan, comm=args.comm,
        ps_mode=args.ps_mode,
        tune_profile=getattr(args, "_tune_profile_id", None),
        train=train, **extra)
    if args.role == "coordinator":
        coord = clus.Coordinator(cfg).start()
        print(f"cluster_coordinator: listening on "
              f"{cfg.host}:{coord.port}", flush=True)
        coord.wait(timeout=args.deadline)
        # linger briefly for the workers' byes (their stats ride
        # them): done fires at the final commit, a breath before the
        # last deferred acks + byes drain; the result snapshots AFTER
        coord_deadline = time.monotonic() + 10.0
        while time.monotonic() < coord_deadline and any(
                st.status == "active"
                for st in coord.slots.values()):
            time.sleep(0.05)
        res = coord.result()
        coord.stop()
    else:
        # (main() already pointed this process's telemetry at
        # DIR/coordinator; spawned workers get DIR/worker-N)
        res = clus.run_local_cluster(
            cfg, spawn=args.spawn,
            coordinator_spawn=args.coordinator_spawn,
            rejoin_after=args.rejoin_after,
            telemetry_dir=args.telemetry_dir, timeout=args.deadline,
            logger=err)
    from tpu_distalg.cluster.local import event_digest

    # machine-readable tail line: the replay acceptance compares the
    # event digest of two runs under the same plan. A subprocess
    # coordinator already digested its own sequences (its result line
    # is what the launcher parsed) — pass that through verbatim.
    print("cluster_result: " + _json.dumps({
        "accuracy": round(res["accuracy"], 6),
        "version": res["version"],
        "gen": res["gen"],
        "merges": res.get("merges",
                          len(res.get("merge_sequence", ()))),
        "respawns": res.get("respawns", 0),
        "restarts": res.get("restarts", 0),
        "recoveries": res.get(
            "coordinator_recoveries",
            1 if res.get("recovered") else 0),
        "recovery_ms": res.get("recovery_ms", []),
        "wal_records_replayed": res.get("wal_records_replayed", 0),
        "event_digest": res.get("event_digest",
                                None) or event_digest(res),
    }, default=float))
    return 0


def _run_serving_plane(args):
    """``tda cluster --role {replica,router}`` — the distributed
    serving plane's two process kinds. Both park until --deadline (or
    a kill); the port announcement line is the launcher handshake."""
    err = lambda m: print(m, file=sys.stderr)  # noqa: E731
    if args.role == "replica":
        from tpu_distalg.cluster import serve as cserve

        if not args.artifact:
            raise SystemExit("--role replica needs --artifact "
                             "CKPT_DIR")
        rep = cserve.run_replica(
            args.slot or 0, args.artifact, shard=args.shard,
            n_shards=args.replica_shards, k_top=args.k_top,
            merge=args.merge, comm=args.comm, host=args.host,
            port=args.port, logger=err)
        print(f"cluster_replica: listening on "
              f"{args.host}:{rep.port}", flush=True)
        deadline = time.monotonic() + args.deadline
        try:
            while (time.monotonic() < deadline
                   and not rep._stop.is_set()):
                time.sleep(0.2)
        finally:
            rep.stop()
        return 0
    from tpu_distalg.cluster.router import Router, RouterConfig

    if not args.replicas:
        raise SystemExit("--role router needs --replicas "
                         "HOST:PORT[,HOST:PORT...]")
    addrs = []
    for tok in args.replicas.split(","):
        host, _, port = tok.strip().rpartition(":")
        addrs.append((host or "127.0.0.1", int(port)))
    router = Router(RouterConfig(
        replicas=tuple(addrs), mode=args.serve_mode,
        policy=args.dispatch, comm=args.comm, port=args.port,
        wal_dir=args.wal_dir, k_top=args.k_top, merge=args.merge,
        hb_interval=args.heartbeat_interval,
        hb_timeout=args.heartbeat_timeout,
        rpc_deadline=args.rpc_deadline), logger=err).start()
    print(f"cluster_router: listening on "
          f"{args.host}:{router.port}", flush=True)
    deadline = time.monotonic() + args.deadline
    try:
        while (time.monotonic() < deadline
               and not router._stop.is_set()):
            time.sleep(0.2)
        router.emit_gauges()
    finally:
        router.stop()
    return 0


def _dispatch(args, jax):
    if args.cmd == "cluster":
        return _run_cluster(args)
    if args.cmd in ("lr", "ssgd", "ma", "bmuf", "easgd"):
        from tpu_distalg.utils import datasets

        data = datasets.breast_cancer_split()
        mesh = _mesh(args)
        t0 = time.perf_counter()
        if args.cmd == "lr":
            from tpu_distalg.models import logistic_regression as m

            def run_once():
                return m.train(
                    *data, mesh, m.LRConfig(
                        n_iterations=args.n_iterations, eta=args.eta,
                        comm=args.comm),
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every)
        elif args.cmd == "ssgd" and args.stream_cache is not None:
            from tpu_distalg.models import ssgd as m
            from tpu_distalg.models import ssgd_stream
            from tpu_distalg.utils import datasets

            if args.mega_steps is not None:
                raise SystemExit(
                    "--mega-steps applies to sampler=fused_train only; "
                    "the streamed path runs one kernel per step")
            if args.comm != "dense":
                raise SystemExit(
                    "--comm applies to the in-memory trainers; the "
                    "streamed trainer (--stream-cache) stages blocks "
                    "host->device per step and syncs dense")
            if args.sync != "bsp":
                raise SystemExit(
                    "--sync ssp applies to the in-memory trainers; "
                    "the streamed trainer (--stream-cache) runs BSP")
            n_shards = int(mesh.shape["data"])
            X2, meta, (X_te, y_te) = datasets.streamed_packed_cache(
                args.stream_cache, n_rows=args.stream_rows,
                n_features=125, n_shards=n_shards,
                pack=args.fused_pack,
                gather_block_rows=args.gather_block_rows)
            cfg = m.SSGDConfig(
                n_iterations=args.n_iterations, eta=args.eta,
                mini_batch_fraction=args.mini_batch_fraction,
                lam=args.lam, reg_type=args.reg_type,
                fused_pack=args.fused_pack,
                gather_block_rows=args.gather_block_rows,
                sampler="fused_gather", shuffle_seed=None,
                eval_every=max(1, args.n_iterations // 10))

            def run_once():
                return ssgd_stream.train(
                    X2, meta, mesh, cfg, X_te, y_te,
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every)
        elif args.cmd == "ssgd":
            from tpu_distalg.models import ssgd as m

            kw = dict(
                n_iterations=args.n_iterations, eta=args.eta,
                mini_batch_fraction=args.mini_batch_fraction,
                lam=args.lam, reg_type=args.reg_type,
                sampler=args.sampler, x_dtype=args.x_dtype,
                gather_block_rows=args.gather_block_rows,
                fused_pack=args.fused_pack,
                shuffle_seed=args.shuffle_seed,
                comm=args.comm, sync=args.sync)
            n_model = int(mesh.shape["model"])
            if n_model > 1:
                # a 2-D --mesh-shape IS the tp request: the feature
                # dim shards over the model axis per the ssgd_tp /
                # ssgd_feature_sharded rule tables — a config, not a
                # code path (parallel/partition.py)
                if args.sampler not in ("bernoulli", "fused_gather"):
                    raise SystemExit(
                        f"--mesh-shape with model={n_model} shards "
                        f"the feature dim, which composes with "
                        f"sampler=bernoulli or fused_gather (got "
                        f"{args.sampler!r})")
                kw["feature_sharded"] = True
            if args.sampler != "fused_train" and \
                    args.mega_steps is not None:
                raise SystemExit(
                    f"--mega-steps applies to sampler=fused_train "
                    f"only (got {args.sampler})"
                )
            if args.sampler == "fused_train":
                mega = args.mega_steps
                if mega is not None and mega < 1:
                    raise SystemExit(
                        f"--mega-steps must be >= 1 (got {mega})")
                if mega is None and args.n_iterations < 1:
                    mega = m.SSGDConfig().mega_steps  # nothing to run
                elif mega is None:
                    # auto-pick: largest divisor of EVERY segment the
                    # run will execute (checkpoint segments, remainder,
                    # resume offset included) within the default launch
                    # size — e.g. 300 iterations picks 100 instead of
                    # failing the divisibility check at trace time
                    import math

                    segs = m.fused_train_segment_lengths(
                        args.checkpoint_dir,
                        (args.checkpoint_every if args.checkpoint_dir
                         else args.n_iterations),
                        args.n_iterations)
                    g = math.gcd(*segs) if segs else args.n_iterations
                    cap = min(m.SSGDConfig().mega_steps, g)
                    mega = max(d for d in range(1, cap + 1)
                               if g % d == 0)
                    if mega < min(m.SSGDConfig().mega_steps,
                                  args.n_iterations) // 2:
                        print(
                            f"[ssgd] note: auto-picked mega_steps="
                            f"{mega} is far below the default launch "
                            f"size — iteration/checkpoint counts with "
                            f"a larger common divisor run faster"
                        )
                kw["mega_steps"] = mega
                # the megakernel evaluates at launch boundaries only
                # (max guards the degenerate n_iterations=0 run)
                kw["eval_every"] = max(1, min(mega, args.n_iterations))

            def run_once():
                return m.train(
                    *data, mesh, m.SSGDConfig(**kw),
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every)
        else:
            mod = {
                "ma": "MAConfig", "bmuf": "BMUFConfig", "easgd": "EASGDConfig"
            }
            import importlib

            m = importlib.import_module(f"tpu_distalg.models.{args.cmd}")
            cfg_cls = getattr(m, mod[args.cmd])
            if args.mega_steps is not None:
                raise SystemExit(
                    f"{args.cmd}: --mega-steps applies to ssgd only — "
                    "local-update megakernels launch n-local-iterations "
                    "steps per round"
                )
            def run_once(m=m, cfg_cls=cfg_cls):
                return m.train(
                    *data, mesh, cfg_cls(
                        n_iterations=args.n_iterations, eta=args.eta,
                        mini_batch_fraction=args.mini_batch_fraction,
                        n_local_iterations=args.n_local_iterations,
                        resample_per_local_step=(
                            args.resample_per_local_step),
                        sampler=args.sampler, x_dtype=args.x_dtype,
                        gather_block_rows=args.gather_block_rows,
                        fused_pack=args.fused_pack,
                        shuffle_seed=args.shuffle_seed,
                        comm=args.comm, sync=args.sync),
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every)
        from tpu_distalg.utils import checkpoint as ckpt

        # the watchdog: crash / NaN-guard trips re-run the job, which
        # resumes from the newest checkpoint (utils/checkpoint.py)
        res = ckpt.run_with_restarts(
            run_once, max_restarts=args.max_restarts)
        jax.block_until_ready(res.w)
        _report_optimizer(args.cmd, res, args, time.perf_counter() - t0)

    elif args.cmd == "kmeans":
        from tpu_distalg.models import kmeans as m
        from tpu_distalg.utils import checkpoint as ckpt
        from tpu_distalg.utils import datasets

        mesh = _mesh(args)
        if args.data_backend != "resident" or args.minibatch_steps:
            # the out-of-core engine: the mixture lives behind a
            # ShardedDataset (host RAM or a disk cache — >HBM fine) and
            # minibatch k-means streams sampled blocks per step
            from tpu_distalg.data import builders

            if args.checkpoint_dir:
                raise SystemExit(
                    "--checkpoint-dir is not supported by the "
                    "minibatch engine yet (state is tiny; rerun "
                    "instead)")
            if args.data_backend == "streamed" and not args.stream_cache:
                raise SystemExit(
                    "--data-backend streamed needs --stream-cache PATH "
                    "(the on-disk packed cache to create or reopen)")
            n_rows = args.scale_points or args.n_points or (1 << 20)
            ds, _ = builders.gaussian_points_dataset(
                mesh, n_rows, dim=args.dim, k=args.k, seed=0,
                block_rows=args.block_rows,
                backend=args.data_backend, path=args.stream_cache)
            steps = args.minibatch_steps or 100

            def run_once():
                return m.fit_minibatch(
                    ds, m.KMeansConfig(k=args.k), n_steps=steps,
                    mini_batch_blocks=args.mini_batch_blocks)

            res = ckpt.run_with_restarts(
                run_once, max_restarts=args.max_restarts)
            print(f"Final centers: {res.centers.tolist()}")
            print(f"minibatch steps run: {res.n_iterations_run} "
                  f"(backend={args.data_backend})")
            return 0
        if args.scale_points:
            make_rows, _ = datasets.gaussian_mixture_rows(
                k=args.k, dim=args.dim, seed=0)

            def run_once():
                return m.fit_scaled(
                    mesh, args.scale_points, make_rows,
                    m.KMeansConfig(k=args.k,
                                   n_iterations=args.n_iterations,
                                   converge_dist=args.converge_dist,
                                   init="farthest"),
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every)

            pts = None  # points never leave the devices (O(k) host RAM)
        else:
            pts = (datasets.toy_kmeans_matrix() if args.n_points == 0
                   else datasets.gaussian_mixture(args.n_points,
                                                  k=args.k))

            def run_once():
                return m.fit(pts, mesh, m.KMeansConfig(
                    k=args.k, n_iterations=args.n_iterations,
                    converge_dist=args.converge_dist),
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every)

        res = ckpt.run_with_restarts(
            run_once, max_restarts=args.max_restarts)
        print(f"Final centers: {res.centers.tolist()}")
        print(f"iterations run: {res.n_iterations_run}")
        if args.plot and pts is None:
            print("--plot ignored with --scale-points (points stay "
                  "on device)")
        elif args.plot:
            from tpu_distalg.utils import metrics

            import numpy as np

            metrics.display_clusters(
                pts, np.asarray(res.assignments)[: len(pts)], args.plot,
                k=args.k,
            )
            print(f"saved plot: {args.plot}")

    elif args.cmd == "pagerank":
        from tpu_distalg.models import pagerank as m
        from tpu_distalg.utils import datasets

        if args.edge_file is not None:
            from tpu_distalg import native

            edges = native.parse_edges_text(
                args.edge_file, args.edge_capacity)
        elif args.n_vertices == 0:
            edges = datasets.toy_graph_edges()
        else:
            edges = datasets.erdos_renyi_edges(args.n_vertices)
        from tpu_distalg.utils import checkpoint as ckpt

        import numpy as np

        mesh = _mesh(args)
        # the edge content is authoritative for --edge-file (it
        # documents itself as overriding --n-vertices, and an
        # undersized count must never reach the degree histogram); the
        # synthetic path keeps its isolated tail vertices
        n_v = int(np.asarray(edges).max()) + 1 if len(edges) else 1
        if args.edge_file is None and args.n_vertices:
            n_v = max(n_v, args.n_vertices)
        backend, warn = m.choose_data_backend(args.data_backend, n_v,
                                              scatter=args.scatter)
        if warn:
            print(warn, file=sys.stderr)
        if backend != "resident" and args.mode == "reference":
            raise SystemExit(
                "[pagerank] the reference-parity mode is resident-only "
                "(per-vertex receive masks); the streamed engine runs "
                "mode='standard' — drop --mode reference or use "
                "--data-backend resident on a smaller graph")
        mode = args.mode or ("reference" if backend == "resident"
                             else "standard")
        t0 = time.perf_counter()
        if backend == "resident":
            res = ckpt.run_with_restarts(
                lambda: m.run(edges, mesh, m.PageRankConfig(
                    n_iterations=args.n_iterations, q=args.q,
                    mode=mode, scatter=args.scatter),
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every),
                max_restarts=args.max_restarts)
            ranks = np.asarray(res.ranks)
            mask = np.asarray(res.has_rank) > 0
            tail = ""
        else:
            import hashlib
            import os
            import tempfile

            from tpu_distalg import graphs

            n_shards = int(mesh.shape["data"])
            # the default path is keyed on the edge CONTENT too — two
            # different graphs sharing a vertex count must not collide
            # on one stale tmp cache
            sha = hashlib.sha1(
                np.ascontiguousarray(edges, np.int64).tobytes()
            ).hexdigest()
            path = args.stream_cache or os.path.join(
                tempfile.gettempdir(),
                f"tda_graph_cache_v{n_v}_s{n_shards}"
                f"_b{args.block_edges}_{sha[:12]}")
            if args.stream_cache is None:
                print(f"[pagerank] edge-block cache: {path} "
                      f"(set --stream-cache to keep it elsewhere)",
                      file=sys.stderr)
            graphs.build_edge_block_cache(
                edges, path, n_shards=n_shards,
                block_edges=args.block_edges, n_vertices=n_v,
                source={"kind": "edges", "sha1": sha})
            gd = graphs.open_graph_dataset(path, mesh, backend=backend)
            cfg = graphs.StreamedPageRankConfig(
                n_iterations=args.n_iterations, q=args.q,
                combine=args.combine)
            res = ckpt.run_with_restarts(
                lambda: graphs.run_streamed_pagerank(
                    gd, cfg, checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every),
                max_restarts=args.max_restarts)
            ranks = np.asarray(res.ranks)
            mask = np.ones(len(ranks), bool)
            st = res.comm_stats
            wire = (st["bytes_wire"] if res.combine == "sparse"
                    else st["bytes_dense_ring"])
            tail = (f" [{backend} engine, combine={res.combine}: "
                    f"{wire} B wire/sweep; accounting sparse "
                    f"{st['bytes_wire']} B vs dense-ring "
                    f"{st['bytes_dense_ring']} B]")
        jax.block_until_ready(res.ranks)
        dt = time.perf_counter() - t0
        shown = np.argsort(-ranks)[:10]
        for v in shown:
            if mask[v]:
                print(f"{v} has rank: {ranks[v]}.")
        print(f"[pagerank] {args.n_iterations} iterations in {dt:.3f}s "
              f"({args.n_iterations / dt:.2f} iter/s){tail}")

    elif args.cmd == "closure":
        from tpu_distalg.models import transitive_closure as m
        from tpu_distalg.utils import datasets

        if args.n_vertices == 0:
            edges = datasets.toy_graph_edges()
        elif args.sparse:
            # bounded-closure graph: an ER graph's closure is Θ(V²) pairs
            # (inherently quadratic output) — chains keep it linear in V
            edges = datasets.chain_forest_edges(args.n_vertices)
        else:
            edges = datasets.erdos_renyi_edges(args.n_vertices, 2.0)
        from tpu_distalg.utils import checkpoint as ckpt

        mesh = _mesh(args)
        if args.sparse:
            def run_once():
                return m.run_sparse(
                    edges, mesh,
                    m.SparseClosureConfig(capacity=args.capacity or None),
                    checkpoint_dir=args.checkpoint_dir,
                    checkpoint_every=args.checkpoint_every)
        else:
            def run_once():
                return m.run(edges, mesh,
                             checkpoint_dir=args.checkpoint_dir,
                             checkpoint_every=args.checkpoint_every)
        res = ckpt.run_with_restarts(
            run_once, max_restarts=args.max_restarts)
        print(f"The original graph has {res.n_paths} paths "
              f"({res.n_rounds} rounds)")

    elif args.cmd == "als":
        from tpu_distalg.models import als as m
        from tpu_distalg.utils import checkpoint as ckpt

        mesh = _mesh(args)
        cfg = m.ALSConfig(lam=args.lam, m=args.m, n=args.n, k=args.k,
                          n_iterations=args.n_iterations)
        if args.data_backend != "resident":
            # R behind a ShardedDataset: host RAM or a disk cache —
            # each sweep streams the row blocks per solve epoch, so R
            # is bounded by disk, not HBM (models/als.fit_streamed)
            from tpu_distalg.data import builders

            if args.checkpoint_dir:
                raise SystemExit(
                    "--checkpoint-dir is not supported by the "
                    "streamed ALS path yet")
            if args.data_backend == "streamed" and not args.stream_cache:
                raise SystemExit(
                    "--data-backend streamed needs --stream-cache PATH "
                    "(the on-disk packed cache to create or reopen)")
            ds, _ = builders.rank_k_rows_dataset(
                mesh, args.m, args.n, args.k, seed=cfg.seed,
                block_rows=args.block_rows,
                backend=args.data_backend, path=args.stream_cache)
            res = ckpt.run_with_restarts(
                lambda: m.fit_streamed(ds, cfg,
                                       rmse_every=args.rmse_every),
                max_restarts=args.max_restarts)
        else:
            res = ckpt.run_with_restarts(
                lambda: m.fit(mesh, cfg,
                              checkpoint_dir=args.checkpoint_dir,
                              checkpoint_every=args.checkpoint_every),
                max_restarts=args.max_restarts)
        import numpy as np

        # ONE device fetch for the whole history: float(e) per element
        # is a D2H round-trip per line (the per-step-host-sync shape
        # TDA011 polices); values print bitwise-identically
        for t, e in enumerate(np.asarray(res.rmse_history)):
            print(f"iterations: {t}, rmse: {float(e):f}")
        if args.checkpoint_dir:
            # machine-readable artifact handoff: `tda serve --artifact`
            # consumes this exact line (and the telemetry event) — no
            # directory globbing needed to find where the factors went
            from tpu_distalg.telemetry import events as tevents

            tevents.emit("artifact_path", workload="als",
                         path=args.checkpoint_dir)
            print(f"artifact_path: {args.checkpoint_dir}")

    elif args.cmd == "chaos":
        import os
        import tempfile

        from tpu_distalg import faults
        from tpu_distalg.faults import chaos

        spec = args.fault_plan or os.environ.get(faults.registry.ENV_PLAN)
        if not spec:
            raise SystemExit(
                "tda chaos needs a fault schedule: pass --fault-plan "
                "'seed=N;point@hit=kind[:arg];...' (or a JSON plan "
                "file, or export $TDA_FAULT_PLAN)")
        mesh = _mesh(args)
        workdir = args.workdir
        made_tmp = workdir is None
        if made_tmp:
            workdir = tempfile.mkdtemp(prefix="tda-chaos-")
        res = None
        try:
            res = chaos.run_chaos(
                args.workload, mesh, plan=spec, workdir=workdir,
                n_iterations=args.n_iterations,
                checkpoint_every=args.checkpoint_every,
                max_restarts=args.max_restarts,
                spawn=args.spawn, comm=args.comm,
                logger=lambda m: print(f"[chaos] {m}"))
        finally:
            if made_tmp:
                if res is not None and res.equal:
                    import shutil

                    shutil.rmtree(workdir, ignore_errors=True)
                else:
                    # a mismatch (or a blown restart budget) is exactly
                    # when the checkpoints + quarantined files matter —
                    # keep the evidence
                    print(f"[chaos] scratch kept for debugging: "
                          f"{workdir}", file=sys.stderr)
        print(res.verdict())
        return 0 if res.equal else 1

    elif args.cmd == "serve":
        import numpy as np

        from tpu_distalg import serve as serve_pkg
        from tpu_distalg.parallel import MeshContext
        from tpu_distalg.serve.server import run_closed_loop

        mesh = MeshContext.create(
            data=args.n_slices if args.n_slices > 0 else None,
            model=args.model_slices).mesh
        cfg = serve_pkg.ServeConfig(
            max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
            queue_depth=args.queue_depth, k_top=args.k_top,
            merge=args.comm)
        server = serve_pkg.Server(mesh, cfg)
        try:
            for path in args.artifact:
                model = server.add_artifact(path)
                print(f"[serve] {model.kind} model {model.name!r} from "
                      f"{path} (meta: {model.meta})")
            rng = np.random.default_rng(0)
            for name, model in server.models.items():
                if model.kind == "als":
                    n_users = max(1, model.meta["n_users"])
                    payloads = [np.int32(int(v) % n_users)
                                for v in rng.integers(
                                    0, n_users, size=args.requests)]
                elif model.kind == "kmeans":
                    payloads = list(rng.normal(size=(
                        args.requests, model.meta["dim"])
                    ).astype(np.float32))
                else:
                    payloads = list(rng.normal(size=(
                        args.requests, model.meta["d"])
                    ).astype(np.float32))
                _, info = run_closed_loop(
                    server, name, payloads,
                    concurrency=args.concurrency, retries=2)
                print(f"[serve] {name}: {info['ok']}/{len(payloads)} "
                      f"replies at {info['qps']} req/s (closed loop, "
                      f"{info['concurrency']} workers, "
                      f"{info['retries']} retries)")
            s = server.emit_counters()
            print(f"[serve] total: {s['replies']} replies in "
                  f"{s['batches']} micro-batch(es), p50 {s['p50_ms']} "
                  f"ms / p99 {s['p99_ms']} ms, {s['shed']} shed, max "
                  f"queue depth {s['max_queue_depth']}")
        finally:
            server.close()

    elif args.cmd == "mc":
        from tpu_distalg.models import monte_carlo as m
        from tpu_distalg.utils import checkpoint as ckpt

        mesh = _mesh(args)
        pi, n_used = ckpt.run_with_restarts(
            lambda: m.estimate_pi(mesh, m.MonteCarloConfig(n=args.n)),
            max_restarts=args.max_restarts)
        print(f"Pi is roughly {pi:f}")

    return 0


if __name__ == "__main__":
    sys.exit(main())
