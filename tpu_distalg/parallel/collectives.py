"""Collective operations over the mesh — the treeAggregate/shuffle replacement.

The reference pulls per-point values back to the driver through Spark's
tree reduction (``treeAggregate``, e.g. ``/root/reference/optimization/
ssgd.py:99-103``) and exchanges keyed data through TCP shuffles. Here the
same patterns are XLA collectives riding the ICI links, invoked from inside
``shard_map`` bodies:

  * ``tree_allreduce_sum``  ≙  ``treeAggregate(zero, add, add)`` — but the
    result lands replicated on every chip (no driver), as a single fused
    AllReduce over the pytree.
  * ``ring_shift``  ≙  a neighbour exchange (``ppermute``), the building
    block for ring pipelines (ring attention / ring all-reduce style
    algorithms) — exposed so long-sequence workloads can ride ICI.
  * keyed reductions (``reduceByKey``) are ``jax.ops.segment_sum`` inside the
    shard + a psum across shards; see ``tpu_distalg.ops.graph``.
"""

from __future__ import annotations

import jax
from jax import lax

from tpu_distalg.parallel.mesh import DATA_AXIS
from tpu_distalg.parallel.compat import axis_size as _axis_size



def tree_allreduce_sum(tree, axis_name: str = DATA_AXIS):
    """psum every leaf of a pytree across ``axis_name``.

    Matches the tuple aggregation idiom of the reference — e.g. SSGD's
    ``(grad_sum, count)`` pair (``ssgd.py:99-103``) becomes a pytree of two
    leaves reduced in one collective.
    """
    return jax.tree.map(lambda x: lax.psum(x, axis_name), tree)


def tree_allreduce_mean(tree, axis_name: str = DATA_AXIS):
    """pmean every leaf across ``axis_name`` (MA's model average,
    ``ma.py:104-106``)."""
    return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)


def ring_shift(x: jax.Array, axis_name: str = DATA_AXIS, shift: int = 1):
    """Rotate shards around the ring: shard i receives shard (i - shift).

    A ``ppermute`` over the mesh axis — the ICI-native neighbour exchange
    used by ring algorithms (ring all-reduce, ring attention).
    """
    n = _axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x: jax.Array, axis_name: str = DATA_AXIS, *, split_axis=0,
               concat_axis=0):
    """Transpose shard <-> local-axis ownership (Ulysses-style exchange)."""
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=True,
    )


def all_gather(x: jax.Array, axis_name: str = DATA_AXIS, *, axis=0,
               tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
