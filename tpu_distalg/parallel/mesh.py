"""Device-mesh runtime core.

Replaces the reference's ``SparkSession.builder...getOrCreate()`` + executor
topology (e.g. ``/root/reference/optimization/ssgd.py:78-81`` and the
``n_slices`` partition-count globals) with a ``jax.sharding.Mesh`` over the
available TPU chips. Where Spark runs ``local[*]`` threads as fake executors
for single-machine testing (SURVEY.md §4), we run N virtual CPU devices via
``XLA_FLAGS=--xla_force_host_platform_device_count=N``.

Two mesh axes by default:
  * ``data``  — data parallelism: rows of an RDD-like array live here.
  * ``model`` — model parallelism: factor matrices / feature blocks can be
    sharded here (used by the ALS workload; size 1 by default).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def emulate_devices(n: int = 8, platform: str = "cpu") -> None:
    """Request ``n`` virtual host devices. Must run before JAX is initialised.

    The JAX analogue of Spark ``local[*]`` (no master URL set anywhere in the
    reference, e.g. ``/root/reference/optimization/ssgd.py:78-81``).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", platform)
    # env vars alone lose to site plugins that force another platform via
    # jax.config; the config update wins when no backend is initialised yet
    import jax as _jax

    _jax.config.update("jax_platforms", platform)


def local_device_count() -> int:
    """Devices attached to THIS process (differs from the global count on
    multi-host slices)."""
    return jax.local_device_count()


def multihost_initialize(**kwargs) -> None:
    """Initialise the multi-host runtime (DCN-connected TPU slices).

    Must run before anything initialises an XLA backend (same contract as
    ``jax.distributed.initialize``, which it wraps). Idempotent: a no-op if
    the distributed client is already up.
    """
    if getattr(jax.distributed, "is_initialized", None) is not None:
        if jax.distributed.is_initialized():
            return
    else:
        # pre-0.6 jax: no is_initialized — probe the global client state
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return
    jax.distributed.initialize(**kwargs)


def get_mesh(
    data: int | None = None,
    model: int = 1,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a 2-D ``(data, model)`` mesh.

    ``data=None`` uses every available device on the data axis (after
    dividing out ``model``). This is the stand-in for the per-script
    ``n_slices`` globals (``ssgd.py:17``): partition count == mesh data size.

    Topology awareness (TPU, all devices used, none pinned explicitly):

      * multi-slice (devices spanning >1 ``slice_index``): a DCN-hybrid
        mesh via ``mesh_utils.create_hybrid_device_mesh`` — the data
        axis spans slices over DCN (one gradient AllReduce per step
        tolerates DCN latency) while the model axis stays inside a
        slice so its per-matmul collectives ride ICI;
      * single slice, >1 chip (covers multi-host pods too):
        ``mesh_utils.create_device_mesh`` orders devices along the
        physical ICI torus so neighbouring mesh coordinates are
        neighbouring chips (ring collectives stay nearest-neighbour);
      * otherwise (CPU emulation, one chip, explicit ``devices``, or a
        shape the topology helpers cannot express): a plain row-major
        grid — deterministic ordering for tests.
    """
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if data is None:
        if n % model != 0:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    need = data * model
    if need > n:
        raise ValueError(f"mesh {data}x{model} needs {need} devices, have {n}")
    grid = _topology_grid(devs, data, model, explicit=devices is not None)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def _topology_grid(devs, data: int, model: int, *, explicit: bool):
    """Arrange ``devs`` into the ``(data, model)`` grid per the topology
    policy in :func:`get_mesh`'s docstring. Pure device-list → grid
    function so the DCN-hybrid / ICI-torus / fallback branches are unit-
    testable with fake device objects (no TPU hardware required)."""
    need = data * model
    if (not explicit and need == len(devs) and len(devs) > 1
            and devs[0].platform == "tpu"):
        from jax.experimental import mesh_utils

        n_slices = len({getattr(d, "slice_index", 0) for d in devs})
        try:
            if n_slices > 1 and data % n_slices == 0:
                return mesh_utils.create_hybrid_device_mesh(
                    (data // n_slices, model), (n_slices, 1), devices=devs
                )
            if n_slices == 1:
                return mesh_utils.create_device_mesh(
                    (data, model), devices=devs
                )
        except (NotImplementedError, ValueError):
            pass  # topology can't express the shape: row-major fallback
    return np.array(devs[:need]).reshape(data, model)


@dataclasses.dataclass(frozen=True)
class MeshContext:
    """A mesh plus the axis names workloads shard over.

    The one runtime object workloads receive — the role SparkSession plays in
    every reference script.
    """

    mesh: Mesh

    @classmethod
    def create(cls, data: int | None = None, model: int = 1) -> "MeshContext":
        return cls(mesh=get_mesh(data=data, model=model))

    @property
    def n_data(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def n_model(self) -> int:
        return self.mesh.shape[MODEL_AXIS]

    @property
    def axis_sizes(self) -> Mapping[str, int]:
        return dict(self.mesh.shape)
