"""Communication-efficient collectives — the instrumented comms layer.

The reference's entire aggregation story is Spark's ``treeAggregate`` +
``broadcast``; our original replacement was a naive per-leaf ``lax.psum``
(``collectives.tree_allreduce_sum``) — full-precision, unbucketed,
unoverlapped gradient traffic on every sync round of every SGD-family
trainer. This module is the single choke point that traffic now routes
through: a :class:`CommSpec`-driven schedule selected per run, with
per-sync wire-byte accounting so the artifact can finally say how many
bytes a trainer moved.

Schedules (all deterministic and bitwise-replayable — fixed reduction
order, counter-based PRNG only):

  ``dense``     today's fused psum per leaf, bitwise-identical to
                ``tree_allreduce_sum`` — the default.
  ``bucketed``  the pytree is flattened into fixed-size buckets; each
                bucket is reduced by a ``ppermute``-chunk ring
                (reduce-scatter + all-gather, the ``ring.py``
                ``fori_loop`` idiom), scanned bucket-by-bucket so the
                collective of bucket *b* overlaps the unpacking compute
                of bucket *b−1* (cf. the chunked, topology-aware
                schedules of arXiv:2112.01075).
  ``hier``      hierarchical: ring reduce-scatter INSIDE each group
                (the intra-host/ICI axis), a cross-group ring of the
                owned chunk (the DCN axis — 1/m of the payload crosses
                the slow links), then an intra-group all-gather.
                Groups come from the mesh's hybrid layout
                (``slice_index``/``process_index`` of the data-axis
                devices) or from ``hier_groups``.
  ``bf16``      cast to bfloat16 on the wire, one psum, cast back —
                half the bytes, the standard gradient-compression
                baseline.
  ``int8``      seeded STOCHASTIC rounding to int8 against a pmax-shared
                scale, integer psum, dequantize — ~4x fewer wire bytes,
                unbiased in expectation, bitwise-replayable because the
                rounding noise is threefry(seed, step, shard).
  ``topk``      top-k sparsification with ERROR FEEDBACK: each shard
                keeps the k largest-|.| entries of (gradient +
                residual), all-reduces only those, and carries the
                unsent remainder in the scan state so nothing is ever
                lost — the sparse-allreduce construction of
                arXiv:1312.3020 with the EF-SGD residual correction
                that preserves convergence.

Compression applies to float leaves with more than one element; scalars
and integer leaves (step counts, minibatch counts) always go dense — a
compressed count would corrupt the update denominators for no
measurable byte win.

Byte accounting (:meth:`CommSync.stats`): ``bytes_wire`` is the
per-shard payload that crosses the interconnect per sync under a
bandwidth-optimal ring at the schedule's wire precision
(``2·B·(n−1)/n`` for an allreduce of B bytes); ``bytes_logical`` is the
f32 payload the sync logically reduces. Trainers multiply by the sync
count and bump the ``comm.bytes_wire`` / ``comm.bytes_logical`` /
``comm.rounds`` telemetry counters, so ``tda report`` shows the
compression ratio actually achieved.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from tpu_distalg.parallel.mesh import DATA_AXIS

SCHEDULES = ("dense", "bucketed", "hier", "bf16", "int8", "topk")

#: float leaves with more elements than this are compressed; at or
#: below it (and for every integer leaf) the schedule falls back to a
#: dense psum — the (grad, count) pairs every trainer syncs keep their
#: count exact.
MIN_COMPRESS_ELEMS = 1


def psum(x, axis_name: str = DATA_AXIS):
    """The blessed raw psum — same op as ``lax.psum``, imported from
    the comms layer so ``tda lint`` (TDA050) can keep every cross-shard
    reduction in ``models/`` behind this instrumentable choke point."""
    from jax import lax

    return lax.psum(x, axis_name)


def pmean(x, axis_name: str = DATA_AXIS):
    """Blessed raw pmean (see :func:`psum`)."""
    from jax import lax

    return lax.pmean(x, axis_name)


def pmax(x, axis_name: str = DATA_AXIS):
    """Blessed raw pmax (see :func:`psum`)."""
    from jax import lax

    return lax.pmax(x, axis_name)


def pmin(x, axis_name: str = DATA_AXIS):
    """Blessed raw pmin (see :func:`psum`)."""
    from jax import lax

    return lax.pmin(x, axis_name)


@dataclasses.dataclass(frozen=True)
class CommSpec:
    """One run's aggregation schedule + knobs.

    ``parse`` accepts the CLI spelling: a schedule name with an
    optional ``:arg`` — ``topk:0.01`` (kept fraction), ``bucketed:65536``
    (elements per bucket), ``hier:2`` (group count; 0 = infer from the
    mesh topology), ``int8:7`` (stochastic-rounding seed).
    """

    schedule: str = "dense"
    bucket_elems: int = 1 << 16      # 'bucketed': elements per bucket
    topk_fraction: float = 0.01      # 'topk': fraction of entries kept
    hier_groups: int = 0             # 'hier': 0 = infer from topology
    seed: int = 0                    # 'int8': stochastic-rounding seed

    def __post_init__(self):
        if self.schedule not in SCHEDULES:
            raise ValueError(
                f"unknown comm schedule {self.schedule!r}; want one of "
                f"{', '.join(SCHEDULES)}")
        if not (0.0 < self.topk_fraction <= 1.0):
            raise ValueError(
                f"topk_fraction must be in (0, 1], got "
                f"{self.topk_fraction}")
        if self.bucket_elems < 1:
            raise ValueError(
                f"bucket_elems must be >= 1, got {self.bucket_elems}")

    @classmethod
    def parse(cls, text: str | "CommSpec" | None) -> "CommSpec":
        if isinstance(text, cls):
            return text
        if not text:
            return cls()
        name, _, arg = str(text).partition(":")
        kw = {}
        if arg:
            if name == "topk":
                kw["topk_fraction"] = float(arg)
            elif name == "bucketed":
                kw["bucket_elems"] = int(arg)
            elif name == "hier":
                kw["hier_groups"] = int(arg)
            elif name == "int8":
                kw["seed"] = int(arg)
            else:
                raise ValueError(
                    f"comm schedule {name!r} takes no argument "
                    f"(got {text!r})")
        return cls(schedule=name, **kw)

    @property
    def stateful(self) -> bool:
        """Whether the schedule carries error-feedback residuals."""
        return self.schedule == "topk"


def infer_groups(mesh, axis_name: str = DATA_AXIS) -> int:
    """Group count for the hierarchical schedule, off the mesh's hybrid
    layout: the number of distinct slices (TPU multi-slice DCN
    boundary) or host processes among the data-axis devices. Falls back
    to 2 when the topology is flat but even (so CPU-emulated meshes
    still exercise both levels), else 1 (plain ring)."""
    axis = list(mesh.axis_names).index(axis_name)
    n = mesh.devices.shape[axis]
    # one representative device per data-axis coordinate
    devs = np.moveaxis(mesh.devices, axis, 0).reshape(n, -1)[:, 0]
    for attr in ("slice_index", "process_index"):
        marks = [getattr(d, attr, 0) or 0 for d in devs]
        g = len(set(marks))
        if 1 < g < n and n % g == 0:
            return g
    return 2 if n % 2 == 0 and n > 2 else 1


def _eligible(leaf) -> bool:
    """Compressible: a float leaf with more than MIN_COMPRESS_ELEMS
    elements (works on arrays and ShapeDtypeStructs)."""
    dt = np.dtype(leaf.dtype)
    return (dt.kind == "f"
            and int(np.prod(leaf.shape)) > MIN_COMPRESS_ELEMS)


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def _ring_allreduce(v, axis_name: str, n: int):
    """Bandwidth-optimal ring allreduce of a flat ``(n·chunk,)`` f32
    vector: n−1 reduce-scatter steps then n−1 all-gather steps, all
    ``ppermute`` chunk rotations (the ``ring.py`` fori_loop idiom).
    Deterministic: the accumulation order around the ring is fixed."""
    import jax.numpy as jnp
    from jax import lax

    if n == 1:
        return v
    my = lax.axis_index(axis_name)
    chunk = v.shape[0] // n
    blocks = v.reshape(n, chunk)
    perm = _ring_perm(n)

    # reduce-scatter: at step s shard i sends its partial of block
    # (i − s) mod n and accumulates the arriving partial of block
    # (i − s − 1) mod n; after n−1 steps shard i owns the fully
    # reduced block (i + 1) mod n
    def rs(s, blocks):
        send_id = (my - s) % n
        buf = lax.dynamic_index_in_dim(blocks, send_id, keepdims=False)
        buf = lax.ppermute(buf, axis_name, perm)
        recv_id = (my - s - 1) % n
        old = lax.dynamic_index_in_dim(blocks, recv_id, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            blocks, old + buf, recv_id, 0)

    blocks = lax.fori_loop(0, n - 1, rs, blocks)

    # all-gather: rotate the finished blocks around the ring; at step s
    # shard i holds (and forwards) the reduced block owned by shard
    # (i − s) mod n, i.e. block (i − s + 1) mod n
    own_id = (my + 1) % n
    out0 = lax.dynamic_update_index_in_dim(
        jnp.zeros_like(blocks),
        lax.dynamic_index_in_dim(blocks, own_id, keepdims=False),
        own_id, 0)

    def ag(s, carry):
        buf, out = carry
        buf = lax.ppermute(buf, axis_name, perm)
        blk_id = (my - s) % n  # arrived from shard (i−s−1): its block
        out = lax.dynamic_update_index_in_dim(out, buf, blk_id, 0)
        return buf, out

    buf0 = lax.dynamic_index_in_dim(blocks, own_id, keepdims=False)
    _, out = lax.fori_loop(0, n - 1, ag, (buf0, out0))
    return out.reshape(-1)


def _hier_allreduce(v, axis_name: str, n: int, g: int):
    """Two-level allreduce of a flat ``(m·chunk,)`` vector over ``g``
    groups of ``m = n/g`` shards: intra-group ring reduce-scatter (the
    fast/ICI links carry the full payload), a cross-group ring of the
    owned chunk (only 1/m of the payload crosses the slow/DCN links),
    then an intra-group all-gather."""
    import jax.numpy as jnp
    from jax import lax

    m = n // g
    if m == 1 or g == 1:
        # no intra-group phase: the caller padded v to a multiple of n
        # for exactly this flat-ring fallback
        return _ring_allreduce(v, axis_name, n)
    my = lax.axis_index(axis_name)
    grp, loc = my // m, my % m
    chunk = v.shape[0] // m
    blocks = v.reshape(m, chunk)
    # intra-group ring: i → (same group, local+1)
    perm_in = [(G * m + L, G * m + (L + 1) % m)
               for G in range(g) for L in range(m)]
    # cross-group ring between same-local shards: i → (group+1, local)
    perm_x = [(G * m + L, ((G + 1) % g) * m + L)
              for G in range(g) for L in range(m)]

    def rs(s, blocks):
        send_id = (loc - s) % m
        buf = lax.dynamic_index_in_dim(blocks, send_id, keepdims=False)
        buf = lax.ppermute(buf, axis_name, perm_in)
        recv_id = (loc - s - 1) % m
        old = lax.dynamic_index_in_dim(blocks, recv_id, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            blocks, old + buf, recv_id, 0)

    blocks = lax.fori_loop(0, m - 1, rs, blocks)
    own_id = (loc + 1) % m
    own = lax.dynamic_index_in_dim(blocks, own_id, keepdims=False)

    # cross-group all-gather of the owned chunk, then ORIGIN-ORDER
    # accumulation (group 0 first): every shard with the same local
    # index owns the SAME block id, and summing the g group-partials
    # in a fixed order keeps the result bitwise-identical on every
    # shard — an accumulate-and-forward would sum in each group's own
    # rotational order and silently de-replicate the output for g >= 3
    # (float addition is not associative; same reason the topk path
    # gathers before accumulating)
    all_c = lax.dynamic_update_index_in_dim(
        jnp.zeros((g,) + own.shape, own.dtype), own, grp, 0)

    def xg(s, carry):
        buf, all_c = carry
        buf = lax.ppermute(buf, axis_name, perm_x)
        src = (grp - s - 1) % g
        all_c = lax.dynamic_update_index_in_dim(all_c, buf, src, 0)
        return buf, all_c

    _, all_c = lax.fori_loop(0, g - 1, xg, (own, all_c))
    own = lax.fori_loop(
        0, g, lambda j, acc: acc + all_c[j], jnp.zeros_like(own))

    # intra-group all-gather of the m finished blocks
    out0 = lax.dynamic_update_index_in_dim(
        jnp.zeros_like(blocks), own, own_id, 0)

    def ag(s, carry):
        buf, out = carry
        buf = lax.ppermute(buf, axis_name, perm_in)
        blk_id = (loc - s) % m
        out = lax.dynamic_update_index_in_dim(out, buf, blk_id, 0)
        return buf, out

    _, out = lax.fori_loop(0, m - 1, ag, (own, out0))
    return out.reshape(-1)


class CommSync:
    """One sync point's compiled-in schedule: built once per trainer
    from the spec, the mesh and an example pytree (shapes/dtypes), then
    called INSIDE the shard_map body every sync round.

    ``reduce(tree, res, t)`` returns ``(summed_tree, res_new)`` where
    ``res`` is the flat error-feedback residual — shape ``(1, ef_elems)``
    inside the body (the caller shards the ``(n_shards, ef_elems)``
    state over the data axis, exactly like per-replica models), or
    ``None`` for stateless schedules. ``t`` is the absolute sync/step id
    — the int8 stochastic-rounding key folds it in, so segmented
    checkpoint/resume replays identical rounding noise.
    """

    def __init__(self, spec: CommSpec, mesh, example, *,
                 axis_name: str = DATA_AXIS):
        import jax

        self.spec = spec
        self.axis_name = axis_name
        self.n_shards = int(mesh.shape[axis_name])
        self.groups = (spec.hier_groups
                       or infer_groups(mesh, axis_name))
        if self.spec.schedule == "hier" and self.n_shards % self.groups:
            raise ValueError(
                f"hier: {self.groups} groups do not divide the "
                f"'{axis_name}' axis size {self.n_shards}")
        leaves = jax.tree.leaves(example)
        self._eligible_mask = [_eligible(x) for x in leaves]
        self._sizes = [int(np.prod(x.shape)) for x in leaves]
        self.ef_elems = sum(
            s for s, e in zip(self._sizes, self._eligible_mask) if e)

    # ---------------------------------------------------------- state

    @property
    def stateful(self) -> bool:
        return self.spec.stateful and self.ef_elems > 0

    def init_state(self):
        """Host-side zero residual, ``(n_shards, ef_elems)`` — shard it
        ``P(axis, None)`` and thread it through the trainer's scan
        carry. Zero-WIDTH (``(n_shards, 0)``) for stateless schedules,
        so callers keep one uniform carry/checkpoint layout per comm
        run instead of a stateful/stateless fork."""
        width = self.ef_elems if self.stateful else 0
        return np.zeros((self.n_shards, width), np.float32)

    # ------------------------------------------------------- schedule

    def reduce(self, tree, res=None, t=0):
        """Allreduce-SUM ``tree`` across the axis under the schedule.
        Returns ``(tree_summed, res_new)``; ``res_new`` is ``None``
        exactly when :attr:`stateful` is false."""
        import jax

        if self.spec.schedule == "dense" or self.n_shards == 1:
            from jax import lax

            out = jax.tree.map(
                lambda x: lax.psum(x, self.axis_name), tree)
            return out, res
        return self._reduce_split(tree, res, t)

    def reduce_mean(self, tree, res=None, t=0):
        """Allreduce-MEAN: ``dense`` uses ``lax.pmean`` (bitwise-equal
        to ``tree_allreduce_mean``); compressed schedules sum then
        divide. Error feedback is applied to the MEAN's deviation, so
        the topk residual correction carries the right scale."""
        import jax

        if self.spec.schedule == "dense" or self.n_shards == 1:
            from jax import lax

            out = jax.tree.map(
                lambda x: lax.pmean(x, self.axis_name), tree)
            return out, res
        if self.spec.schedule == "topk":
            # compress x/n so the residual tracks the mean-scale error
            scaled = jax.tree.map(lambda x: x / self.n_shards, tree)
            return self._reduce_split(scaled, res, t)
        out, res = self._reduce_split(tree, res, t)
        return jax.tree.map(lambda x: x / self.n_shards, out), res

    def _reduce_split(self, tree, res, t):
        """Dense-psum the ineligible leaves, run the schedule on the
        eligible ones."""
        import jax
        from jax import lax

        leaves, treedef = jax.tree.flatten(tree)
        comp = [x for x, e in zip(leaves, self._eligible_mask) if e]
        if len(self._eligible_mask) != len(leaves):
            raise ValueError(
                f"CommSync built for {len(self._eligible_mask)} leaves,"
                f" got {len(leaves)}")
        comp_out, res_new = self._run_schedule(comp, res, t)
        it = iter(comp_out)
        out = [next(it) if e else lax.psum(x, self.axis_name)
               for x, e in zip(leaves, self._eligible_mask)]
        return jax.tree.unflatten(treedef, out), res_new

    def _run_schedule(self, comp, res, t):
        import jax
        import jax.numpy as jnp
        from jax import lax

        sched = self.spec.schedule
        shapes = [x.shape for x in comp]
        dtypes = [x.dtype for x in comp]
        sizes = [int(np.prod(s)) for s in shapes]

        def flatten(xs):
            return jnp.concatenate(
                [x.astype(jnp.float32).ravel() for x in xs]) \
                if xs else jnp.zeros((0,), jnp.float32)

        def unflatten(v):
            out, off = [], 0
            for shape, dt, sz in zip(shapes, dtypes, sizes):
                out.append(v[off:off + sz].reshape(shape).astype(dt))
                off += sz
            return out

        if sched == "bf16":
            out = [lax.psum(x.astype(jnp.bfloat16), self.axis_name)
                   .astype(x.dtype) for x in comp]
            return out, res

        if sched == "int8":
            key = jax.random.fold_in(
                jax.random.fold_in(
                    jax.random.key(self.spec.seed), t),
                lax.axis_index(self.axis_name))
            out = []
            for i, x in enumerate(comp):
                scale = lax.pmax(jnp.max(jnp.abs(x)),
                                 self.axis_name) / 127.0
                scale = jnp.maximum(scale, jnp.float32(1e-30))
                u = jax.random.uniform(
                    jax.random.fold_in(key, i), x.shape)
                q = jnp.clip(jnp.floor(x / scale + u), -127, 127)
                s = lax.psum(q.astype(jnp.int32), self.axis_name)
                out.append((s.astype(jnp.float32) * scale)
                           .astype(x.dtype))
            return out, res

        if sched == "topk":
            n = self.n_shards
            flat = flatten(comp) + res[0]
            k = max(1, int(round(self.spec.topk_fraction
                                 * max(1, self.ef_elems))))
            _, idx = lax.top_k(jnp.abs(flat), k)
            vals = flat[idx]
            # the sparse allreduce is a RING ALL-GATHER of the k
            # (value, index) pairs — n−1 ppermute hops of an 8k-byte
            # buffer, so the bytes crossing the interconnect really
            # are what stats() records (a psum of a zero-padded dense
            # vector would move full-length f32 on the wire). Every
            # shard then accumulates the n contributions in ORIGIN
            # order (shard 0 first), so the float result is identical
            # on every shard — the replicated-output contract psum
            # gave us, kept without psum.
            my = lax.axis_index(self.axis_name)
            all_v = lax.dynamic_update_index_in_dim(
                jnp.zeros((n, k), vals.dtype), vals, my, 0)
            all_i = lax.dynamic_update_index_in_dim(
                jnp.zeros((n, k), idx.dtype), idx, my, 0)
            perm = _ring_perm(n)

            def hop(s, carry):
                v_buf, i_buf, all_v, all_i = carry
                v_buf = lax.ppermute(v_buf, self.axis_name, perm)
                i_buf = lax.ppermute(i_buf, self.axis_name, perm)
                src = (my - s - 1) % n
                all_v = lax.dynamic_update_index_in_dim(
                    all_v, v_buf, src, 0)
                all_i = lax.dynamic_update_index_in_dim(
                    all_i, i_buf, src, 0)
                return v_buf, i_buf, all_v, all_i

            _, _, all_v, all_i = lax.fori_loop(
                0, n - 1, hop, (vals, idx, all_v, all_i))
            out = lax.fori_loop(
                0, n,
                lambda j, out: out.at[all_i[j]].add(all_v[j]),
                jnp.zeros_like(flat))
            contrib = jnp.zeros_like(flat).at[idx].set(vals)
            return unflatten(out), (flat - contrib)[None, :]

        if sched in ("bucketed", "hier"):
            n = self.n_shards
            g = self.groups if sched == "hier" else 1
            m = max(1, n // g)
            # ring chunking granularity: n blocks for the flat ring,
            # n/g intra-group blocks for the two-level ring. g == n or
            # g == 1 degenerate to the flat ring (m == 1 has no
            # intra-group phase), whose padding granularity is n.
            n_blocks = m if (sched == "hier" and m > 1 and g > 1) \
                else n
            flat = flatten(comp)
            e = flat.shape[0]
            if sched == "bucketed":
                n_buckets = max(1, math.ceil(e / self.spec.bucket_elems))
            else:
                n_buckets = 1
            bucket = n_blocks * math.ceil(
                max(1, e) / (n_buckets * n_blocks))
            pad = n_buckets * bucket - e
            flat = jnp.pad(flat, (0, pad))
            ring = (_ring_allreduce if sched == "bucketed"
                    else lambda v, a, nn: _hier_allreduce(v, a, nn, g))

            def one_bucket(_, b):
                return None, ring(b, self.axis_name, n)

            # scan pipelines bucket b's ppermute chain against bucket
            # b−1's unpack — the overlapped-bucket schedule
            _, out = lax.scan(
                one_bucket, None, flat.reshape(n_buckets, bucket))
            return unflatten(out.reshape(-1)[:e]), res

        raise AssertionError(f"unreachable schedule {sched!r}")

    # ---------------------------------------------------------- stats

    def stats(self) -> dict:
        """Per-sync byte accounting (host-side, static): per-shard
        ``bytes_wire`` under a bandwidth-optimal ring at the schedule's
        wire precision, the f32 ``bytes_logical`` payload, and the
        collective ``rounds`` launched per sync.

        This is the SCHEDULE'S payload accounting — what each sync
        fundamentally has to move — not a measurement of the XLA
        lowering underneath. bf16 and topk match it on the wire today
        (a bf16 psum moves bf16; topk's ring all-gather moves exactly
        the 8k-byte pair buffers). int8 is the known gap: XLA has no
        int8 AllReduce, so the quantized payload rides an int32 psum
        (4 bytes/elem on the wire until a custom collective lands) —
        the counter records the schedule's achievable bytes, which is
        what the --comm knob is selecting for."""
        n = self.n_shards
        dense_elems = sum(
            s for s, e in zip(self._sizes, self._eligible_mask)
            if not e)
        ce = self.ef_elems  # compressible elements
        ring = 2.0 * (n - 1) / n if n > 1 else 0.0
        b_logical = 4 * (ce + dense_elems)
        dense_wire = 4 * dense_elems * ring
        n_comp_leaves = sum(self._eligible_mask)
        sched = self.spec.schedule
        if sched == "dense" or n == 1:
            wire = 4 * ce * ring + dense_wire
            rounds = 1
        elif sched == "bf16":
            wire = 2 * ce * ring + dense_wire
            rounds = 1 + (1 if dense_elems else 0)
        elif sched == "int8":
            # int8 payload + one f32 pmax per leaf for the shared scale
            wire = ce * ring + 4 * n_comp_leaves * ring + dense_wire
            rounds = 2 * n_comp_leaves + (1 if dense_elems else 0)
        elif sched == "topk":
            k = max(1, int(round(self.spec.topk_fraction * max(1, ce))))
            # k (value, index) pairs exchanged all-gather-style
            wire = 8 * k * (n - 1) + dense_wire
            rounds = 1 + (1 if dense_elems else 0)
        elif sched == "bucketed":
            wire = 4 * ce * ring + dense_wire
            rounds = max(1, math.ceil(
                max(1, ce) / self.spec.bucket_elems)) \
                + (1 if dense_elems else 0)
        elif sched == "hier":
            g = self.groups
            m = max(1, n // g)
            ici = 4 * ce * (2.0 * (m - 1) / m if m > 1 else 0.0)
            dcn = 4 * (ce / m) * (2.0 * (g - 1) / g if g > 1 else 0.0)
            wire = ici + dcn + dense_wire
            rounds = 3 + (1 if dense_elems else 0)
        else:  # pragma: no cover
            raise AssertionError(sched)
        return {"bytes_wire": int(round(wire)),
                "bytes_logical": int(round(b_logical)),
                "rounds": int(rounds)}


def make_sync(spec, mesh, example, *, axis_name: str = DATA_AXIS):
    """Build a :class:`CommSync` — ``spec`` may be a :class:`CommSpec`
    or its CLI string spelling."""
    return CommSync(CommSpec.parse(spec), mesh, example,
                    axis_name=axis_name)


def emit_sync_counters(sync: CommSync, n_syncs: int) -> dict:
    """Bump the ``comm.*`` telemetry counters for a run of ``n_syncs``
    sync rounds (a no-op when telemetry is disabled) and return the
    per-sync stats for callers that also report them inline."""
    from tpu_distalg.telemetry import events as tevents

    st = sync.stats()
    tevents.counter("comm.bytes_wire", st["bytes_wire"] * n_syncs)
    tevents.counter("comm.bytes_logical",
                    st["bytes_logical"] * n_syncs)
    tevents.counter("comm.rounds", st["rounds"] * n_syncs)
    tevents.counter("comm.syncs", n_syncs)
    return st
